"""Parameterized Pallas TPU kernel: small-G grouped aggregation.

Generalizes the hand-written Q1 kernel (ops/pallas_agg.py) into a
substrate the SQL path can route through (reference analog:
MultiChannelGroupByHash.java:54's specialized small-group loops): any
aggregate list of count / count_star / sum / avg / min / max over
integral-storage columns, grouped by up to PALLAS_MAX_GROUPS dense group
ids, compiles to ONE streaming pass — where the XLA composition runs
G x A masked reductions.

Exactness without int64 (Pallas TPU has no 64-bit reductions): sum
inputs are decomposed OUTSIDE the kernel into 16-bit limb channels
(l0, l1 unsigned, l2 = x >> 32 signed); each 16384-row block sums
channels in int32 (bound 2^16 * 2^14 = 2^30), per-block tiles combine
outside in int64 — exact for |x| < 2^45, asserted against the input
types' value bounds. min/max ride int32 channels directly (their
storage is int32-safe for the eligible types).

Eligibility (maybe_grouped_aggregate returns None otherwise): every
group key is a small-domain dictionary/boolean column, G <= 32, every
aggregate is count/count_star/sum/avg/min/max over integral storage.

DEPLOYMENT: Mosaic kernels execute through the axon tunnel (round-4
verification, TPU_STATUS.md §1). CPU CI validates in interpret mode
against the XLA path; on a TPU backend flip it on per query with the
`pallas_groupby` session property (Session(pallas_groupby=True) or
X-Presto-Session).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..expr.compiler import evaluate
from ..page import Block, Page
from .aggregate import AggSpec, avg_from_sum_count

BLK_ROWS = 16384  # 128 x 128 rows per grid step
# G cap: the per-block output tile gate (rows_pad <= 1024 rows) is the
# real bound — at G=64 a 16-channel plan exactly fills the 512KB tile.
# Single-pass wins GROW with G vs the XLA fallback (one data read vs
# G x A masked column reads), so eligible mid-size domains route here.
PALLAS_MAX_GROUPS = 64
MAX_CHANNELS = 128  # one output lane per channel
_SUM_BOUND = 1 << 45  # |sum input| bound keeping block limb sums in int32


def _rows_pad(num_groups: int, num_channels: int) -> int:
    """Output tile rows: one row per (group, channel), padded to the
    int32 sublane multiple (8)."""
    return -(-(num_groups * num_channels) // 8) * 8


def _kernel_factory(num_groups: int, num_channels: int, reduce_kinds,
                    dtype=jnp.int32):
    """Build the grid kernel for a (G, channels) plan. reduce_kinds[k] in
    {'add', 'min', 'max'} selects the per-channel block reduction.
    dtype is the tile/channel element type: int32 for the exact limb
    path, float32 for the hi/lo-split float64 path.

    Only SUBLANE (axis 0) reductions happen in-kernel — the generic
    lax.reduce primitive has no Mosaic lowering, and cross-lane scalar
    reduction is what the VPU is worst at. Row g*num_channels+k of the
    output tile holds channel k of group g as 128 per-lane partials; the
    lane fold happens outside the kernel in XLA int64/f64."""

    rpad = _rows_pad(num_groups, num_channels)

    def kernel(cnt_ref, *refs):
        from jax.experimental import pallas as pl

        gid_ref, live_ref = refs[0], refs[1]
        chan_refs = refs[2:-1]
        out_ref = refs[-1]
        i = pl.program_id(0)
        gid = gid_ref[:]
        base = i * BLK_ROWS
        rows = jax.lax.broadcasted_iota(jnp.int32, gid.shape, 0) * 128
        lanes = jax.lax.broadcasted_iota(jnp.int32, gid.shape, 1)
        live = ((base + rows + lanes) < cnt_ref[0]) & (live_ref[:] != 0)

        if dtype == jnp.int32:
            zero = jnp.int32(0)
            imax = jnp.int32(np.iinfo(np.int32).max)
            imin = jnp.int32(np.iinfo(np.int32).min)
        else:
            zero = dtype(0)
            imax = dtype(np.inf)
            imin = dtype(-np.inf)
        rows_out: List = []
        for g in range(num_groups):
            sel = live & (gid == g)
            for k, ref in enumerate(chan_refs):
                ch = ref[:]
                kind = reduce_kinds[k]
                if kind == "add":
                    rows_out.append(
                        jnp.sum(jnp.where(sel, ch, zero), axis=0,
                                dtype=dtype)
                    )
                elif kind == "min":
                    rows_out.append(
                        jnp.min(jnp.where(sel, ch, imax), axis=0)
                    )
                else:
                    rows_out.append(
                        jnp.max(jnp.where(sel, ch, imin), axis=0)
                    )
        rows_out.extend(
            [jnp.full((128,), zero, dtype)] * (rpad - len(rows_out))
        )
        out_ref[:] = jnp.stack(rows_out)[None]

    return kernel


def _pallas_partials(gid, live, channels, count, num_groups, reduce_kinds,
                     dtype=jnp.int32):
    """(blocks, rows_pad, 128) per-block per-lane partials in `dtype`;
    row g*len(channels)+k = channel k of group g (see _kernel_factory)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = gid.shape[0]
    pad = -n % BLK_ROWS
    if pad:
        gid = jnp.pad(gid, (0, pad))
        live = jnp.pad(live, (0, pad))
        channels = [jnp.pad(c, (0, pad)) for c in channels]
        n += pad
    blocks = n // BLK_ROWS
    view = lambda x: x.reshape(n // 128, 128)
    interpret = jax.default_backend() != "tpu"

    col_spec = pl.BlockSpec(
        (128, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    kernel = _kernel_factory(
        num_groups, len(channels), tuple(reduce_kinds), dtype
    )
    rpad = _rows_pad(num_groups, len(channels))
    ins = (
        count.reshape(1).astype(jnp.int32),
        view(gid.astype(jnp.int32)),
        view(live.astype(jnp.int32)),
        *[view(c.astype(dtype)) for c in channels],
    )
    # trace with x64 OFF: under global x64 the BlockSpec index maps trace
    # to i64 functions, which Mosaic fails to legalize ("func.return
    # (i64)"); the kernel is explicit int32/float32 throughout
    with jax.enable_x64(False):
        return pl.pallas_call(
            kernel,
            grid=(blocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
            + [col_spec] * (2 + len(channels)),
            out_specs=pl.BlockSpec(
                (1, rpad, 128),
                lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct(
                (blocks, rpad, 128), dtype
            ),
            interpret=interpret,
        )(*ins)


def _eligible_keys(page: Page, group_exprs) -> Optional[Tuple[list, list]]:
    """Evaluated key Vals + domain sizes when every key is small-domain."""
    keys, domains = [], []
    for e in group_exprs:
        v = evaluate(e, page)
        if isinstance(v.type, T.VarcharType) and v.dictionary is not None:
            d = len(v.dictionary)
        elif isinstance(v.type, T.BooleanType):
            d = 2
        else:
            return None
        if d == 0:
            d = 1
        keys.append(v)
        domains.append(d)
    total = 1
    for d in domains:
        total *= d
    if not 0 < total <= PALLAS_MAX_GROUPS:
        return None
    return keys, domains


_SUPPORTED = {"count", "count_star", "sum", "avg", "min", "max"}


def maybe_grouped_aggregate(
    page: Page, group_exprs, group_names, aggs: Sequence[AggSpec], pre_mask
) -> Optional[Page]:
    """Route an eligible aggregation through the Pallas kernel; None when
    the shape is not eligible (caller falls back to the XLA path)."""
    if not group_exprs:
        return None
    if any(a.func not in _SUPPORTED for a in aggs):
        return None
    elig = _eligible_keys(page, group_exprs)
    if elig is None:
        return None
    keys, domains = elig
    ins = []
    for a in aggs:
        if a.input is None:
            ins.append(None)
            continue
        v = evaluate(a.input, page)
        if v.data.ndim != 1:
            return None
        integral = jnp.issubdtype(v.data.dtype, jnp.integer) or isinstance(
            v.type, T.BooleanType
        )
        # float64 rides the hi/lo-split f32 channel path, sum/avg only
        # (min/max would need 64-bit compares the kernel does not have)
        floating = jnp.issubdtype(v.data.dtype, jnp.floating)
        if not integral and not (floating and a.func in ("sum", "avg")):
            return None
        ins.append(v)

    # dense mixed-radix group id. NULL keys form their OWN group (SQL
    # GROUP BY semantics — dropping them was a silent wrong-result on
    # the default-on TPU path): each nullable key gets one extra slot.
    from .aggregate import _masked_live

    live = _masked_live(page, pre_mask)
    gid = jnp.zeros(page.capacity, jnp.int32)
    eff_domains: List[int] = []
    for v, d in zip(keys, domains):
        code = jnp.clip(v.data.astype(jnp.int32), 0, d - 1)
        eff = d
        if v.valid is not None:
            code = jnp.where(v.valid, code, d)  # null slot = last
            eff = d + 1
        gid = gid * eff + code
        eff_domains.append(eff)
    G = 1
    for d in eff_domains:
        G *= d
    if G > PALLAS_MAX_GROUPS:
        return None

    # channel plan: (agg index, role, limb index, reduce kind)
    channels: List = []
    plan: List[Tuple[int, str]] = []
    kinds: List[str] = []
    fchannels: List = []  # float32 hi/lo channels (their own kernel/tile)
    fplan: List[Tuple[int, str]] = []

    def add_channel(arr, tag, kind="add"):
        channels.append(arr)
        plan.append(tag)
        kinds.append(kind)

    def add_fchannel(arr, tag):
        fchannels.append(arr)
        fplan.append(tag)

    ones = jnp.ones(page.capacity, jnp.int32)
    for ai, (a, v) in enumerate(zip(aggs, ins)):
        contrib = live if v is None or v.valid is None else (live & v.valid)
        cmask = contrib.astype(jnp.int32)
        if a.func in ("count", "count_star", "avg"):
            add_channel(ones * cmask, (ai, "count", 0))
        if a.func in ("sum", "avg") and jnp.issubdtype(
            v.data.dtype, jnp.floating
        ):
            # hi/lo split: hi = f32(x), lo = f32(x - hi) represents the
            # f64 value to ~48 mantissa bits; block partials sum in f32,
            # blocks combine in f64 outside (documented tolerance — the
            # XLA f64 path is the exact-comparison oracle in tests)
            xf = v.data.astype(jnp.float64)
            hi = xf.astype(jnp.float32)
            lo = (xf - hi.astype(jnp.float64)).astype(jnp.float32)
            fm = cmask.astype(jnp.float32)
            add_fchannel(hi * fm, (ai, "fsum", 0))
            add_fchannel(lo * fm, (ai, "fsum", 1))
            continue
        if a.func in ("sum", "avg"):
            x = v.data.astype(jnp.int64)
            add_channel(
                (x & 0xFFFF).astype(jnp.int32) * cmask, (ai, "sum", 0)
            )
            add_channel(
                ((x >> 16) & 0xFFFF).astype(jnp.int32) * cmask,
                (ai, "sum", 1),
            )
            add_channel(
                (x >> 32).astype(jnp.int32) * cmask, (ai, "sum", 2)
            )
        if a.func in ("min", "max"):
            x = v.data.astype(jnp.int32)
            add_channel(
                x, (ai, a.func, 0), kind=a.func
            )  # masking happens in-kernel via `sel`
    if len(channels) > MAX_CHANNELS or len(fchannels) > MAX_CHANNELS:
        return None
    # bound the per-block output tile (rows x 128 lanes) to 512KB VMEM
    if max(
        _rows_pad(G, len(channels)), _rows_pad(G, len(fchannels))
    ) > 1024:
        return None

    CH = len(channels)
    if CH:
        partials = _pallas_partials(
            gid, live, channels, page.count, G, kinds
        )
        pv = (
            partials[:, : G * CH, :]
            .reshape(-1, G, CH, 128)
            .astype(jnp.int64)
        )
        s = jnp.sum(pv, axis=(0, 3))  # (G, CH)
        # min/max channels combine across blocks AND lanes by min/max
        # (their in-kernel fill values imax/imin survive empty groups)
        pmin = jnp.min(pv, axis=(0, 3))
        pmax = jnp.max(pv, axis=(0, 3))
    else:
        s = pmin = pmax = jnp.zeros((G, 0), jnp.int64)
    fs = None
    if fchannels:
        CHF = len(fchannels)
        fpartials = _pallas_partials(
            gid, live, fchannels, page.count, G,
            ["add"] * CHF, dtype=jnp.float32,
        )
        fs = jnp.sum(
            fpartials[:, : G * CHF, :]
            .reshape(-1, G, CHF, 128)
            .astype(jnp.float64),
            axis=(0, 3),
        )

    # per-agg recomposition
    by_agg: dict = {}
    for k, tag in enumerate(plan):
        by_agg.setdefault(tag[0], {})[(tag[1], tag[2])] = k
    by_agg_f: dict = {}
    for k, tag in enumerate(fplan):
        by_agg_f.setdefault(tag[0], {})[(tag[1], tag[2])] = k

    def fsum_of(ai):
        chs = by_agg_f[ai]
        return fs[:, chs[("fsum", 0)]] + fs[:, chs[("fsum", 1)]]

    counts_live = None
    out_blocks: List[Block] = []
    out_names: List[str] = []
    # group key columns from the dense gid (mixed radix decode over the
    # EFFECTIVE domains; a nullable key's last slot decodes to NULL)
    grange = jnp.arange(G, dtype=jnp.int32)
    rem = grange
    key_codes = []
    for d in reversed(eff_domains):
        key_codes.append(rem % d)
        rem = rem // d
    key_codes = list(reversed(key_codes))
    for v, nm, code, d, eff in zip(
        keys, group_names, key_codes, domains, eff_domains
    ):
        valid = (code < d) if eff != d else None
        out_blocks.append(
            Block(jnp.clip(code, 0, d - 1), v.type, valid, v.dict_id)
        )
        out_names.append(nm)

    # rows-per-group (for empty-group compaction): any count channel, else
    # compute from a dedicated pass? count channels exist for count/avg;
    # guarantee one by construction below
    group_rows = None
    for ai, a in enumerate(aggs):
        ch = by_agg.get(ai, {}).get(("count", 0))
        if ch is not None:
            group_rows = s[:, ch]
            break
    if group_rows is None:
        # no counting aggregate requested: derive occupancy with one tiny
        # XLA reduction (still one pass over gid, not per-agg)
        occ = (
            jnp.zeros(G + 1, jnp.int32)
            .at[jnp.where(live, gid, G)]
            .add(1, mode="drop")
        )
        group_rows = occ[:G].astype(jnp.int64)

    from . import decimal128 as d128

    def sum_of(ai):
        chs = by_agg[ai]
        l0 = s[:, chs[("sum", 0)]]
        l1 = s[:, chs[("sum", 1)]]
        l2 = s[:, chs[("sum", 2)]]
        return l0 + (l1 << 16) + (l2 << 32)

    for ai, a in enumerate(aggs):
        has = group_rows > 0
        if a.func in ("count", "count_star"):
            out_blocks.append(
                Block(s[:, by_agg[ai][("count", 0)]], T.BIGINT, None)
            )
        elif a.func == "sum" and ai in by_agg_f:
            out_blocks.append(
                Block(
                    fsum_of(ai).astype(a.output_type.storage_dtype),
                    a.output_type,
                    has,
                )
            )
        elif a.func == "avg" and ai in by_agg_f:
            cnt = s[:, by_agg[ai][("count", 0)]]
            data = avg_from_sum_count(
                fsum_of(ai), cnt, a.output_type, a.input.type
            )
            out_blocks.append(Block(data, a.output_type, cnt > 0))
        elif a.func == "sum":
            total = sum_of(ai)
            if isinstance(a.output_type, T.DecimalType) and a.output_type.is_long:
                out_blocks.append(
                    Block(d128.from_int64(total), a.output_type, has)
                )
            else:
                out_blocks.append(
                    Block(
                        total.astype(a.output_type.storage_dtype),
                        a.output_type,
                        has,
                    )
                )
        elif a.func == "avg":
            cnt = s[:, by_agg[ai][("count", 0)]]
            data = avg_from_sum_count(
                sum_of(ai), cnt, a.output_type, a.input.type
            )
            out_blocks.append(Block(data, a.output_type, cnt > 0))
        else:  # min / max
            ch = by_agg[ai][(a.func, 0)]
            col = pmin[:, ch] if a.func == "min" else pmax[:, ch]
            out_blocks.append(
                Block(
                    col.astype(a.output_type.storage_dtype),
                    a.output_type,
                    has,
                )
            )
        out_names.append(a.name)

    out = Page.from_blocks(out_blocks, out_names, count=G)
    from .filter import compact

    return compact(out, group_rows > 0)


def pallas_available() -> bool:
    return True  # interpret mode always works; TPU uses Mosaic
