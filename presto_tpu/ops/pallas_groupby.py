"""Parameterized Pallas TPU kernel: small-G grouped aggregation.

Generalizes the hand-written Q1 kernel (ops/pallas_agg.py) into a
substrate the SQL path can route through (reference analog:
MultiChannelGroupByHash.java:54's specialized small-group loops): any
aggregate list of count / count_star / sum / avg / min / max over
integral-storage columns, grouped by up to PALLAS_MAX_GROUPS dense group
ids, compiles to ONE streaming pass — where the XLA composition runs
G x A masked reductions.

Exactness without int64 (Pallas TPU has no 64-bit reductions): sum
inputs are decomposed OUTSIDE the kernel into 16-bit limb channels
(l0, l1 unsigned, l2 = x >> 32 signed); each 16384-row block sums
channels in int32 (bound 2^16 * 2^14 = 2^30), per-block tiles combine
outside in int64 — exact for |x| < 2^45, asserted against the input
types' value bounds. min/max ride int32 channels directly (their
storage is int32-safe for the eligible types).

Eligibility (maybe_grouped_aggregate returns None otherwise): every
group key is a small-domain dictionary/boolean column, G <= 32, every
aggregate is count/count_star/sum/avg/min/max over integral storage.

DEPLOYMENT: Mosaic kernels execute through the axon tunnel (round-4
verification, TPU_STATUS.md §1). CPU CI validates in interpret mode
against the XLA path; on a TPU backend flip it on per query with the
`pallas_groupby` session property (Session(pallas_groupby=True) or
X-Presto-Session).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..expr.compiler import evaluate
from ..page import Block, Page
from .aggregate import AggSpec, avg_from_sum_count

BLK_ROWS = 16384  # 128 x 128 rows per grid step
# G cap: the per-block output tile gate (rows_pad <= 1024 rows) is the
# real bound — at G=64 a 16-channel plan exactly fills the 512KB tile.
# Single-pass wins GROW with G vs the XLA fallback (one data read vs
# G x A masked column reads), so eligible mid-size domains route here.
PALLAS_MAX_GROUPS = 64
MAX_CHANNELS = 128  # one output lane per channel
_SUM_BOUND = 1 << 45  # |sum input| bound keeping block limb sums in int32


def _rows_pad(num_groups: int, num_channels: int) -> int:
    """Output tile rows: one row per (group, channel), padded to the
    int32 sublane multiple (8)."""
    return -(-(num_groups * num_channels) // 8) * 8


def _kernel_factory(num_groups: int, num_channels: int, reduce_kinds,
                    dtype=jnp.int32):
    """Build the grid kernel for a (G, channels) plan. reduce_kinds[k] in
    {'add', 'min', 'max'} selects the per-channel block reduction.
    dtype is the tile/channel element type: int32 for the exact limb
    path, float32 for the hi/lo-split float64 path.

    Only SUBLANE (axis 0) reductions happen in-kernel — the generic
    lax.reduce primitive has no Mosaic lowering, and cross-lane scalar
    reduction is what the VPU is worst at. Row g*num_channels+k of the
    output tile holds channel k of group g as 128 per-lane partials; the
    lane fold happens outside the kernel in XLA int64/f64."""

    rpad = _rows_pad(num_groups, num_channels)

    def kernel(cnt_ref, *refs):
        from jax.experimental import pallas as pl

        gid_ref, live_ref = refs[0], refs[1]
        chan_refs = refs[2:-1]
        out_ref = refs[-1]
        i = pl.program_id(0)
        gid = gid_ref[:]
        base = i * BLK_ROWS
        rows = jax.lax.broadcasted_iota(jnp.int32, gid.shape, 0) * 128
        lanes = jax.lax.broadcasted_iota(jnp.int32, gid.shape, 1)
        live = ((base + rows + lanes) < cnt_ref[0]) & (live_ref[:] != 0)

        if dtype == jnp.int32:
            zero = jnp.int32(0)
            imax = jnp.int32(np.iinfo(np.int32).max)
            imin = jnp.int32(np.iinfo(np.int32).min)
        else:
            zero = dtype(0)
            imax = dtype(np.inf)
            imin = dtype(-np.inf)
        rows_out: List = []
        for g in range(num_groups):
            sel = live & (gid == g)
            for k, ref in enumerate(chan_refs):
                ch = ref[:]
                kind = reduce_kinds[k]
                if kind == "add":
                    rows_out.append(
                        jnp.sum(jnp.where(sel, ch, zero), axis=0,
                                dtype=dtype)
                    )
                elif kind == "min":
                    rows_out.append(
                        jnp.min(jnp.where(sel, ch, imax), axis=0)
                    )
                else:
                    rows_out.append(
                        jnp.max(jnp.where(sel, ch, imin), axis=0)
                    )
        rows_out.extend(
            [jnp.full((128,), zero, dtype)] * (rpad - len(rows_out))
        )
        out_ref[:] = jnp.stack(rows_out)[None]

    return kernel


def _pallas_partials(gid, live, channels, count, num_groups, reduce_kinds,
                     dtype=jnp.int32):
    """(blocks, rows_pad, 128) per-block per-lane partials in `dtype`;
    row g*len(channels)+k = channel k of group g (see _kernel_factory)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = gid.shape[0]
    pad = -n % BLK_ROWS
    if pad:
        gid = jnp.pad(gid, (0, pad))
        live = jnp.pad(live, (0, pad))
        channels = [jnp.pad(c, (0, pad)) for c in channels]
        n += pad
    blocks = n // BLK_ROWS
    view = lambda x: x.reshape(n // 128, 128)
    interpret = jax.default_backend() != "tpu"

    col_spec = pl.BlockSpec(
        (128, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    kernel = _kernel_factory(
        num_groups, len(channels), tuple(reduce_kinds), dtype
    )
    rpad = _rows_pad(num_groups, len(channels))
    ins = (
        count.reshape(1).astype(jnp.int32),
        view(gid.astype(jnp.int32)),
        view(live.astype(jnp.int32)),
        *[view(c.astype(dtype)) for c in channels],
    )
    # trace with x64 OFF: under global x64 the BlockSpec index maps trace
    # to i64 functions, which Mosaic fails to legalize ("func.return
    # (i64)"); the kernel is explicit int32/float32 throughout.
    # jax.experimental.disable_x64 is the spelling this jax line ships
    # (plain jax.enable_x64(False) was removed)
    from jax.experimental import disable_x64

    with disable_x64():
        return pl.pallas_call(
            kernel,
            grid=(blocks,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
            + [col_spec] * (2 + len(channels)),
            out_specs=pl.BlockSpec(
                (1, rpad, 128),
                lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct(
                (blocks, rpad, 128), dtype
            ),
            interpret=interpret,
        )(*ins)


def _eligible_keys(page: Page, group_exprs) -> Optional[Tuple[list, list]]:
    """Evaluated key Vals + domain sizes when every key is small-domain."""
    keys, domains = [], []
    for e in group_exprs:
        v = evaluate(e, page)
        if isinstance(v.type, T.VarcharType) and v.dictionary is not None:
            d = len(v.dictionary)
        elif isinstance(v.type, T.BooleanType):
            d = 2
        else:
            return None
        if d == 0:
            d = 1
        keys.append(v)
        domains.append(d)
    total = 1
    for d in domains:
        total *= d
    if not 0 < total <= PALLAS_MAX_GROUPS:
        return None
    return keys, domains


_SUPPORTED = {"count", "count_star", "sum", "avg", "min", "max"}


def maybe_grouped_aggregate(
    page: Page, group_exprs, group_names, aggs: Sequence[AggSpec], pre_mask
) -> Optional[Page]:
    """Route an eligible aggregation through the Pallas kernel; None when
    the shape is not eligible (caller falls back to the XLA path)."""
    if not group_exprs:
        return None
    if any(a.func not in _SUPPORTED for a in aggs):
        return None
    elig = _eligible_keys(page, group_exprs)
    if elig is None:
        return None
    keys, domains = elig
    ins = []
    for a in aggs:
        if a.input is None:
            ins.append(None)
            continue
        v = evaluate(a.input, page)
        if v.data.ndim != 1:
            return None
        integral = jnp.issubdtype(v.data.dtype, jnp.integer) or isinstance(
            v.type, T.BooleanType
        )
        # float64 rides the hi/lo-split f32 channel path, sum/avg only
        # (min/max would need 64-bit compares the kernel does not have)
        floating = jnp.issubdtype(v.data.dtype, jnp.floating)
        if not integral and not (floating and a.func in ("sum", "avg")):
            return None
        ins.append(v)

    # dense mixed-radix group id. NULL keys form their OWN group (SQL
    # GROUP BY semantics — dropping them was a silent wrong-result on
    # the default-on TPU path): each nullable key gets one extra slot.
    from .aggregate import _masked_live

    live = _masked_live(page, pre_mask)
    gid = jnp.zeros(page.capacity, jnp.int32)
    eff_domains: List[int] = []
    for v, d in zip(keys, domains):
        code = jnp.clip(v.data.astype(jnp.int32), 0, d - 1)
        eff = d
        if v.valid is not None:
            code = jnp.where(v.valid, code, d)  # null slot = last
            eff = d + 1
        gid = gid * eff + code
        eff_domains.append(eff)
    G = 1
    for d in eff_domains:
        G *= d
    if G > PALLAS_MAX_GROUPS:
        return None

    # channel plan: (agg index, role, limb index, reduce kind)
    channels: List = []
    plan: List[Tuple[int, str]] = []
    kinds: List[str] = []
    fchannels: List = []  # float32 hi/lo channels (their own kernel/tile)
    fplan: List[Tuple[int, str]] = []

    def add_channel(arr, tag, kind="add"):
        channels.append(arr)
        plan.append(tag)
        kinds.append(kind)

    def add_fchannel(arr, tag):
        fchannels.append(arr)
        fplan.append(tag)

    ones = jnp.ones(page.capacity, jnp.int32)
    for ai, (a, v) in enumerate(zip(aggs, ins)):
        contrib = live if v is None or v.valid is None else (live & v.valid)
        cmask = contrib.astype(jnp.int32)
        if a.func in ("count", "count_star", "avg"):
            add_channel(ones * cmask, (ai, "count", 0))
        if a.func in ("sum", "avg") and jnp.issubdtype(
            v.data.dtype, jnp.floating
        ):
            # hi/lo split: hi = f32(x), lo = f32(x - hi) represents the
            # f64 value to ~48 mantissa bits; block partials sum in f32,
            # blocks combine in f64 outside (documented tolerance — the
            # XLA f64 path is the exact-comparison oracle in tests)
            xf = v.data.astype(jnp.float64)
            hi = xf.astype(jnp.float32)
            lo = (xf - hi.astype(jnp.float64)).astype(jnp.float32)
            fm = cmask.astype(jnp.float32)
            add_fchannel(hi * fm, (ai, "fsum", 0))
            add_fchannel(lo * fm, (ai, "fsum", 1))
            continue
        if a.func in ("sum", "avg"):
            x = v.data.astype(jnp.int64)
            add_channel(
                (x & 0xFFFF).astype(jnp.int32) * cmask, (ai, "sum", 0)
            )
            add_channel(
                ((x >> 16) & 0xFFFF).astype(jnp.int32) * cmask,
                (ai, "sum", 1),
            )
            add_channel(
                (x >> 32).astype(jnp.int32) * cmask, (ai, "sum", 2)
            )
        if a.func in ("min", "max"):
            x = v.data.astype(jnp.int32)
            add_channel(
                x, (ai, a.func, 0), kind=a.func
            )  # masking happens in-kernel via `sel`
    if len(channels) > MAX_CHANNELS or len(fchannels) > MAX_CHANNELS:
        return None
    # bound the per-block output tile (rows x 128 lanes) to 512KB VMEM
    if max(
        _rows_pad(G, len(channels)), _rows_pad(G, len(fchannels))
    ) > 1024:
        return None

    CH = len(channels)
    if CH:
        partials = _pallas_partials(
            gid, live, channels, page.count, G, kinds
        )
        pv = (
            partials[:, : G * CH, :]
            .reshape(-1, G, CH, 128)
            .astype(jnp.int64)
        )
        s = jnp.sum(pv, axis=(0, 3))  # (G, CH)
        # min/max channels combine across blocks AND lanes by min/max
        # (their in-kernel fill values imax/imin survive empty groups)
        pmin = jnp.min(pv, axis=(0, 3))
        pmax = jnp.max(pv, axis=(0, 3))
    else:
        s = pmin = pmax = jnp.zeros((G, 0), jnp.int64)
    fs = None
    if fchannels:
        CHF = len(fchannels)
        fpartials = _pallas_partials(
            gid, live, fchannels, page.count, G,
            ["add"] * CHF, dtype=jnp.float32,
        )
        fs = jnp.sum(
            fpartials[:, : G * CHF, :]
            .reshape(-1, G, CHF, 128)
            .astype(jnp.float64),
            axis=(0, 3),
        )

    # per-agg recomposition
    by_agg: dict = {}
    for k, tag in enumerate(plan):
        by_agg.setdefault(tag[0], {})[(tag[1], tag[2])] = k
    by_agg_f: dict = {}
    for k, tag in enumerate(fplan):
        by_agg_f.setdefault(tag[0], {})[(tag[1], tag[2])] = k

    def fsum_of(ai):
        chs = by_agg_f[ai]
        return fs[:, chs[("fsum", 0)]] + fs[:, chs[("fsum", 1)]]

    counts_live = None
    out_blocks: List[Block] = []
    out_names: List[str] = []
    # group key columns from the dense gid (mixed radix decode over the
    # EFFECTIVE domains; a nullable key's last slot decodes to NULL)
    grange = jnp.arange(G, dtype=jnp.int32)
    rem = grange
    key_codes = []
    for d in reversed(eff_domains):
        key_codes.append(rem % d)
        rem = rem // d
    key_codes = list(reversed(key_codes))
    for v, nm, code, d, eff in zip(
        keys, group_names, key_codes, domains, eff_domains
    ):
        valid = (code < d) if eff != d else None
        out_blocks.append(
            Block(jnp.clip(code, 0, d - 1), v.type, valid, v.dict_id)
        )
        out_names.append(nm)

    # rows-per-group (for empty-group compaction): any count channel, else
    # compute from a dedicated pass? count channels exist for count/avg;
    # guarantee one by construction below
    group_rows = None
    for ai, a in enumerate(aggs):
        ch = by_agg.get(ai, {}).get(("count", 0))
        if ch is not None:
            group_rows = s[:, ch]
            break
    if group_rows is None:
        # no counting aggregate requested: derive occupancy with one tiny
        # XLA reduction (still one pass over gid, not per-agg)
        occ = (
            jnp.zeros(G + 1, jnp.int32)
            .at[jnp.where(live, gid, G)]
            .add(1, mode="drop")
        )
        group_rows = occ[:G].astype(jnp.int64)

    from . import decimal128 as d128

    def sum_of(ai):
        chs = by_agg[ai]
        l0 = s[:, chs[("sum", 0)]]
        l1 = s[:, chs[("sum", 1)]]
        l2 = s[:, chs[("sum", 2)]]
        return l0 + (l1 << 16) + (l2 << 32)

    for ai, a in enumerate(aggs):
        has = group_rows > 0
        if a.func in ("count", "count_star"):
            out_blocks.append(
                Block(s[:, by_agg[ai][("count", 0)]], T.BIGINT, None)
            )
        elif a.func == "sum" and ai in by_agg_f:
            out_blocks.append(
                Block(
                    fsum_of(ai).astype(a.output_type.storage_dtype),
                    a.output_type,
                    has,
                )
            )
        elif a.func == "avg" and ai in by_agg_f:
            cnt = s[:, by_agg[ai][("count", 0)]]
            data = avg_from_sum_count(
                fsum_of(ai), cnt, a.output_type, a.input.type
            )
            out_blocks.append(Block(data, a.output_type, cnt > 0))
        elif a.func == "sum":
            total = sum_of(ai)
            if isinstance(a.output_type, T.DecimalType) and a.output_type.is_long:
                out_blocks.append(
                    Block(d128.from_int64(total), a.output_type, has)
                )
            else:
                out_blocks.append(
                    Block(
                        total.astype(a.output_type.storage_dtype),
                        a.output_type,
                        has,
                    )
                )
        elif a.func == "avg":
            cnt = s[:, by_agg[ai][("count", 0)]]
            data = avg_from_sum_count(
                sum_of(ai), cnt, a.output_type, a.input.type
            )
            out_blocks.append(Block(data, a.output_type, cnt > 0))
        else:  # min / max
            ch = by_agg[ai][(a.func, 0)]
            col = pmin[:, ch] if a.func == "min" else pmax[:, ch]
            out_blocks.append(
                Block(
                    col.astype(a.output_type.storage_dtype),
                    a.output_type,
                    has,
                )
            )
        out_names.append(a.name)

    out = Page.from_blocks(out_blocks, out_names, count=G)
    from .filter import compact

    return compact(out, group_rows > 0)


def pallas_available() -> bool:
    return True  # interpret mode always works; TPU uses Mosaic


# -- hash-slot grouped aggregation (PR 11) -----------------------------------
#
# The dense path above needs every key to be a SMALL-DOMAIN dictionary /
# boolean column (mixed-radix gid over the domain product, G <= 64). The
# hash-slot path below lifts that ceiling: ARBITRARY-valued keys (int64
# order keys, composite keys, floats, NULLs) map to dense group ids
# through the same linear-probe slot machinery as ops/pallas_join.py —
# a distinct-insert pass assigns each row the slot of its key's first
# occurrence (true key equality verified against the slot's
# representative row, so 32-bit tag collisions re-probe instead of
# merging groups), occupied slots rank-compact to gid 0..G-1, and the
# accumulation runs over gids:
#
# * tpu / interp — the SAME _pallas_partials streaming kernel as the
#   dense path (gid is just no longer a radix code), eligible while the
#   output tile fits: rows_pad(G, channels) <= 1024, i.e. G up to 512
#   with a sum+count plan — an 8x group ceiling lift with identical
#   exactness (16-bit limb channels).
# * cpu (engine default for this path) — numpy bincount per limb
#   channel: one C pass per channel, exact (limb partial sums stay
#   below 2^53 for any page under 2^37 rows), beating the jitted
#   sort-compose fallback on high-NDV shapes.
#
# Behind the pallas_groupby_hash breaker; ineligible/overflow shapes
# return None and the caller falls through to the MXU one-hot matmul or
# the sort strategy exactly as before.

HASH_MAX_GROUPS_HOST = 1 << 16
_HASH_START_BITS = 13
_HASH_ROUNDS = 96  # distinct-insert advance bound before resizing


def _concrete(*arrays) -> bool:
    """Eager-only guard (the ops/sort.py idiom): the slot assignment is
    host work; traced callers keep the XLA compositions."""
    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


def _keys_match(keys_np, rows_a: np.ndarray, rows_b: np.ndarray):
    """GROUP BY equality of key tuples at rows_a vs rows_b: NULL == NULL,
    NaN == NaN, -0.0 == 0.0 (reference doubleToLongBits grouping)."""
    ok = np.ones(len(rows_a), bool)
    for data, valid in keys_np:
        a, b = data[rows_a], data[rows_b]
        part = a == b
        if np.issubdtype(data.dtype, np.floating):
            part = part | (np.isnan(a) & np.isnan(b))
        if part.ndim == 2:
            part = part.all(axis=-1)
        if valid is not None:
            va, vb = valid[rows_a], valid[rows_b]
            part = (part & va & vb) | (~va & ~vb)
        ok &= part
    return ok


def _assign_slots(tag: np.ndarray, keys_np, live: np.ndarray, bits: int):
    """Distinct-insert: every live row ends at the slot of its key's
    first occurrence. Returns (slot_of_row, slot_rep, occupied) or None
    when displacement exhausts _HASH_ROUNDS (caller retries with a
    bigger table)."""
    size = (1 << bits) + _HASH_ROUNDS + 2
    limit = size - 2
    slot_rep = np.full(size, -1, np.int64)  # representative row per slot
    slot_tag = np.full(size, np.uint32(0xFFFFFFFF), np.uint32)
    desired = (tag >> np.uint32(32 - bits)).astype(np.int64)
    n = len(tag)
    slot_of = np.full(n, -1, np.int64)
    pend = np.flatnonzero(live)
    off = np.zeros(n, np.int64)
    for _ in range(2 * _HASH_ROUNDS):
        if not len(pend):
            break
        cand = np.minimum(desired[pend] + off[pend], limit)
        occ = slot_rep[cand] >= 0
        done = np.zeros(len(pend), bool)
        # (a) occupied: join when tag AND true keys match the
        # representative; otherwise advance (collision / other group)
        if occ.any():
            same = occ & (slot_tag[cand] == tag[pend])
            if same.any():
                si = np.flatnonzero(same)
                km = _keys_match(
                    keys_np, pend[si], slot_rep[cand[si]]
                )
                joined = si[km]
                slot_of[pend[joined]] = cand[joined]
                done[joined] = True
                off[pend[si[~km]]] += 1
            off[pend[occ & ~same]] += 1
        # (b) vacant: race-insert; winners become representatives,
        # losers retry the SAME slot next round (it is occupied now)
        vac = ~occ
        if vac.any():
            vi = np.flatnonzero(vac)
            vc = pend[vi]
            c = cand[vi]
            slot_rep[c] = vc  # last writer wins
            won = slot_rep[c] == vc
            slot_tag[c[won]] = tag[vc[won]]
            slot_of[vc[won]] = c[won]
            done[vi[won]] = True
        if len(pend) and off[pend].max(initial=0) >= _HASH_ROUNDS:
            return None
        pend = pend[~done]
    if len(pend):
        return None
    occupied = np.flatnonzero(slot_rep >= 0)
    return slot_of, slot_rep, occupied


_HASH_SUPPORTED = _SUPPORTED  # count / count_star / sum / avg / min / max


def _estimate_ndv(tag: np.ndarray, live: np.ndarray, sample: int = 8192) -> int:
    """Cheap NDV estimate from distinct tags in a strided sample: when
    the sample is mostly repeats the domain is about the distinct count;
    when it is mostly unique, scale up linearly (over-estimating is the
    safe direction — it only skips the hash path)."""
    rows = np.flatnonzero(live)
    n = len(rows)
    if n == 0:
        return 0
    if n > sample:
        rows = rows[:: max(n // sample, 1)][:sample]
    u = len(np.unique(tag[rows]))
    s = len(rows)
    if u < s // 2:
        return max(int(u * 1.25), 1)
    return max(int(n * (u / max(s, 1))), 1)


# prestolint: host-function -- eager host orchestration: device key eval,
# host slot assignment, backend-dispatched accumulation
def maybe_grouped_aggregate_hash(
    page: Page, group_exprs, group_names, aggs: Sequence[AggSpec], pre_mask
) -> Optional[Page]:
    """Hash-slot grouped aggregation; None when ineligible (caller falls
    through to the matmul / sort strategies)."""
    if not group_exprs:
        return None
    if any(a.func not in _HASH_SUPPORTED for a in aggs):
        return None
    from .aggregate import _masked_live
    from .hashing import hash_rows

    keys = [evaluate(e, page) for e in group_exprs]
    probe_arrays = [k.data for k in keys] + [page.count]
    if not _concrete(*probe_arrays):
        return None
    mode = _hash_groupby_mode()
    if mode == "off":
        return None
    ins = []
    for a in aggs:
        if a.input is None:
            ins.append(None)
            continue
        v = evaluate(a.input, page)
        if v.data.ndim != 1 or not _concrete(v.data):
            return None
        integral = jnp.issubdtype(v.data.dtype, jnp.integer) or isinstance(
            v.type, T.BooleanType
        )
        floating = jnp.issubdtype(v.data.dtype, jnp.floating)
        if not integral and not floating:
            return None
        if floating and a.func in ("min", "max") and mode != "host":
            return None  # float compares don't ride the int32 channels
        if (
            mode != "host"
            and a.func in ("min", "max")
            and v.data.dtype.itemsize > 4
        ):
            return None  # 64-bit min/max needs the host path
        if a.func in ("sum", "avg") and not jnp.issubdtype(
            v.data.dtype, jnp.floating
        ):
            amax = int(np.abs(np.asarray(v.data)).max(initial=0))
            if isinstance(a.input.type, T.DecimalType):
                # decimal sums must stay EXACT: this path totals in
                # int64 limbs (the sort strategy carries two-lane d128),
                # so bail when |sum| could pass 2^61 — avg's HALF_UP
                # rounding computes 2*|sum|+cnt, which must also fit
                if amax and amax * page.capacity >= (1 << 61):
                    return None
            if mode != "host" and amax >= _SUM_BOUND:
                # the pallas limb kernel's high-limb block partials sum
                # in int32 (module header bound: exact for |x| < 2^45);
                # the host bincount path chunks exactly, so only the
                # kernel modes bail
                return None
        ins.append(v)

    live = np.asarray(_masked_live(page, pre_mask))
    h = np.asarray(hash_rows(keys))
    tag = np.minimum(
        (h >> np.uint64(32)).astype(np.uint32), np.uint32(0xFFFFFFFE)
    )
    keys_np = [
        (
            np.asarray(k.data),
            None if k.valid is None else np.asarray(k.valid),
        )
        for k in keys
    ]
    cap = HASH_MAX_GROUPS_HOST if mode == "host" else 1 << 10
    # size the table from a sampled NDV estimate: a table sized for the
    # wrong order of magnitude costs a full doomed insert pass before the
    # resize loop can react (measured 5x worse than the sort fallback at
    # NDV 30k), and an estimate far above the cap means the sort/matmul
    # strategies win anyway — bail before paying anything
    est = _estimate_ndv(tag, live)
    if est > 2 * cap:
        return None
    # table size is independent of the group cap: start at the estimate
    # (4x headroom) and grow on displacement overflow / hot load, up to
    # 2x cap slots (a table larger than the cap only means a cooler load)
    max_bits = max(
        _HASH_START_BITS, int(np.ceil(np.log2(max(cap * 2, 2))))
    )
    bits = min(
        max(int(np.ceil(np.log2(max(est * 4, 16)))), 8), max_bits
    )
    assigned = None
    while assigned is None and bits <= max_bits:
        assigned = _assign_slots(tag, keys_np, live, bits)
        if assigned is not None and bits < max_bits:
            # resize when the table ran hot (load > 1/2): scans stay short
            if len(assigned[2]) * 2 > (1 << bits):
                assigned = None
        if assigned is None:
            bits += 2
    if assigned is None:
        return None
    slot_of, slot_rep, occupied = assigned
    G = len(occupied)
    if G == 0 or G > cap:
        return None
    rank = np.zeros(len(slot_rep), np.int64)
    rank[occupied] = np.arange(G)
    gid = np.where(live, rank[np.maximum(slot_of, 0)], 0)
    reps = slot_rep[occupied]

    if mode == "host":
        agg_blocks = _host_accumulate(gid, live, aggs, ins, G)
    else:
        agg_blocks = _pallas_accumulate(gid, live, aggs, ins, G, page)
    if agg_blocks is None:
        return None

    out_blocks: List[Block] = []
    out_names: List[str] = []
    for v, nm in zip(keys, group_names):
        data, valid = np.asarray(v.data), v.valid
        out_blocks.append(
            Block(
                jnp.asarray(data[reps]),
                v.type,
                None if valid is None else jnp.asarray(
                    np.asarray(valid)[reps]
                ),
                v.dict_id,
            )
        )
        out_names.append(nm)
    for b, a in zip(agg_blocks, aggs):
        out_blocks.append(b)
        out_names.append(a.name)
    return Page.from_blocks(out_blocks, out_names, count=G)


def _hash_groupby_mode() -> str:
    import os

    forced = os.environ.get("PRESTO_TPU_PALLAS_GROUPBY_HASH", "")
    if forced in ("0", "off"):
        return "off"
    if forced == "interp":
        return "interp"
    return "pallas" if jax.default_backend() == "tpu" else "host"


def _contrib_mask(live, v) -> np.ndarray:
    if v is None or v.valid is None:
        return live
    return live & np.asarray(v.valid)


def _host_accumulate(gid, live, aggs, ins, G) -> Optional[List[Block]]:
    """numpy bincount accumulation: one C pass per limb channel, exact
    (16-bit limbs keep partial sums below 2^53)."""
    from . import decimal128 as d128

    out: List[Block] = []
    counts_cache = {}

    def counts_for(ai, v):
        c = counts_cache.get(ai)
        if c is None:
            m = _contrib_mask(live, v)
            c = np.bincount(gid[m], minlength=G).astype(np.int64)
            counts_cache[ai] = c
        return c

    def exact_sum(x: np.ndarray, m: np.ndarray) -> np.ndarray:
        g = gid[m]
        x = x[m].astype(np.int64)
        # bincount accumulates in f64: 16-bit limbs stay exact to 2^37
        # rows, but the signed high limb can reach 2^31 — chunk it so no
        # partial passes 2^53 regardless of value distribution
        total = np.zeros(G, np.int64)
        step = 1 << 21
        for s0 in range(0, len(x), step):
            xs, gs = x[s0 : s0 + step], g[s0 : s0 + step]
            l0 = np.bincount(gs, weights=(xs & 0xFFFF).astype(np.float64),
                             minlength=G).astype(np.int64)
            l1 = np.bincount(
                gs, weights=((xs >> 16) & 0xFFFF).astype(np.float64),
                minlength=G,
            ).astype(np.int64)
            l2 = np.bincount(gs, weights=(xs >> 32).astype(np.float64),
                             minlength=G).astype(np.int64)
            total += l0 + (l1 << 16) + (l2 << 32)
        return total

    for ai, (a, v) in enumerate(zip(aggs, ins)):
        if a.func in ("count", "count_star"):
            out.append(
                Block(jnp.asarray(counts_for(ai, v)), T.BIGINT, None)
            )
            continue
        m = _contrib_mask(live, v)
        data = np.asarray(v.data)
        has = counts_for(ai, v) > 0
        if a.func in ("sum", "avg"):
            if np.issubdtype(data.dtype, np.floating):
                total = np.bincount(
                    gid[m], weights=data[m].astype(np.float64), minlength=G
                )
            else:
                total = exact_sum(data, m)
            if a.func == "avg":
                cnt = counts_for(ai, v)
                res = avg_from_sum_count(
                    jnp.asarray(total), jnp.asarray(cnt), a.output_type,
                    a.input.type,
                )
                out.append(Block(res, a.output_type, jnp.asarray(has)))
            elif isinstance(a.output_type, T.DecimalType) and (
                a.output_type.is_long
            ):
                out.append(
                    Block(
                        d128.from_int64(jnp.asarray(total)), a.output_type,
                        jnp.asarray(has),
                    )
                )
            else:
                res = jnp.asarray(total).astype(a.output_type.storage_dtype)
                out.append(Block(res, a.output_type, jnp.asarray(has)))
            continue
        # min / max via ufunc.at (correct for any width; the tpu path
        # restricts to int32-safe storage instead)
        if np.issubdtype(data.dtype, np.floating):
            init = np.inf if a.func == "min" else -np.inf
            acc = np.full(G, init, np.float64)
            red = np.minimum if a.func == "min" else np.maximum
            red.at(acc, gid[m], data[m].astype(np.float64))
        else:
            info = np.iinfo(np.int64)
            init = info.max if a.func == "min" else info.min
            acc = np.full(G, init, np.int64)
            red = np.minimum if a.func == "min" else np.maximum
            red.at(acc, gid[m], data[m].astype(np.int64))
        res = jnp.asarray(acc).astype(a.output_type.storage_dtype)
        out.append(Block(res, a.output_type, jnp.asarray(has)))
    return out


# prestolint: host-function -- eager host orchestration around the
# partials kernel (concrete gid/live; occupancy bincount runs on host)
def _pallas_accumulate(gid, live, aggs, ins, G, page) -> Optional[List[Block]]:
    """Accumulate over hash gids with the SAME streaming kernel as the
    dense path (_pallas_partials): limb channels, per-block partials,
    int64/f64 combine outside. None when the output tile gate
    (rows_pad <= 1024) rejects this (G, channels) plan."""
    channels: List = []
    kinds: List[str] = []
    plan: List[Tuple[int, str, int]] = []
    fchannels: List = []
    fplan: List[Tuple[int, str, int]] = []
    livej = jnp.asarray(live)
    ones = jnp.ones(len(gid), jnp.int32)

    for ai, (a, v) in enumerate(zip(aggs, ins)):
        contrib = (
            livej
            if v is None or v.valid is None
            else (livej & jnp.asarray(v.valid))
        )
        cmask = contrib.astype(jnp.int32)
        if a.func in ("count", "count_star", "avg"):
            channels.append(ones * cmask)
            plan.append((ai, "count", 0))
            kinds.append("add")
        if a.func in ("sum", "avg") and jnp.issubdtype(
            v.data.dtype, jnp.floating
        ):
            xf = v.data.astype(jnp.float64)
            hi = xf.astype(jnp.float32)
            lo = (xf - hi.astype(jnp.float64)).astype(jnp.float32)
            fm = cmask.astype(jnp.float32)
            fchannels.append(hi * fm)
            fplan.append((ai, "fsum", 0))
            fchannels.append(lo * fm)
            fplan.append((ai, "fsum", 1))
            continue
        if a.func in ("sum", "avg"):
            x = v.data.astype(jnp.int64)
            for li, limb in enumerate(
                ((x & 0xFFFF), ((x >> 16) & 0xFFFF), (x >> 32))
            ):
                channels.append(limb.astype(jnp.int32) * cmask)
                plan.append((ai, "sum", li))
                kinds.append("add")
        if a.func in ("min", "max"):
            # pre-mask NULL inputs with the fold identity: the kernel's
            # row mask is group-level liveness only
            fill = jnp.int32(
                np.iinfo(np.int32).max if a.func == "min"
                else np.iinfo(np.int32).min
            )
            channels.append(
                jnp.where(contrib, v.data.astype(jnp.int32), fill)
            )
            plan.append((ai, a.func, 0))
            kinds.append(a.func)
    if len(channels) > MAX_CHANNELS or len(fchannels) > MAX_CHANNELS:
        return None
    if max(
        _rows_pad(G, len(channels)), _rows_pad(G, len(fchannels)), 8
    ) > 1024:
        return None

    gidj = jnp.asarray(gid.astype(np.int32))
    count = jnp.asarray(np.int32(len(gid)))  # liveness rides the mask
    CH = len(channels)
    if CH:
        partials = _pallas_partials(gidj, livej, channels, count, G, kinds)
        pv = partials[:, : G * CH, :].reshape(-1, G, CH, 128).astype(
            jnp.int64
        )
        s = jnp.sum(pv, axis=(0, 3))
        pmin = jnp.min(pv, axis=(0, 3))
        pmax = jnp.max(pv, axis=(0, 3))
    else:
        s = pmin = pmax = jnp.zeros((G, 0), jnp.int64)
    fs = None
    if fchannels:
        CHF = len(fchannels)
        fpartials = _pallas_partials(
            gidj, livej, fchannels, count, G, ["add"] * CHF,
            dtype=jnp.float32,
        )
        fs = jnp.sum(
            fpartials[:, : G * CHF, :].reshape(-1, G, CHF, 128).astype(
                jnp.float64
            ),
            axis=(0, 3),
        )

    by_agg: dict = {}
    for k, (ai, role, li) in enumerate(plan):
        by_agg.setdefault(ai, {})[(role, li)] = k
    by_agg_f: dict = {}
    for k, (ai, role, li) in enumerate(fplan):
        by_agg_f.setdefault(ai, {})[(role, li)] = k

    from . import decimal128 as d128

    out: List[Block] = []
    for ai, (a, v) in enumerate(zip(aggs, ins)):
        if a.func in ("count", "count_star"):
            out.append(Block(s[:, by_agg[ai][("count", 0)]], T.BIGINT, None))
            continue
        if ai in by_agg and ("count", 0) in by_agg[ai]:
            cnt = s[:, by_agg[ai][("count", 0)]]
        else:
            m = _contrib_mask(live, v)
            cnt = jnp.asarray(
                np.bincount(gid[m], minlength=G).astype(np.int64)
            )
        has = cnt > 0
        if ai in by_agg_f:
            chs = by_agg_f[ai]
            total = fs[:, chs[("fsum", 0)]] + fs[:, chs[("fsum", 1)]]
            if a.func == "avg":
                out.append(
                    Block(
                        avg_from_sum_count(
                            total, cnt, a.output_type, a.input.type
                        ),
                        a.output_type, has,
                    )
                )
            else:
                out.append(
                    Block(
                        total.astype(a.output_type.storage_dtype),
                        a.output_type, has,
                    )
                )
            continue
        if a.func in ("sum", "avg"):
            chs = by_agg[ai]
            total = (
                s[:, chs[("sum", 0)]]
                + (s[:, chs[("sum", 1)]] << 16)
                + (s[:, chs[("sum", 2)]] << 32)
            )
            if a.func == "avg":
                out.append(
                    Block(
                        avg_from_sum_count(
                            total, cnt, a.output_type, a.input.type
                        ),
                        a.output_type, has,
                    )
                )
            elif isinstance(a.output_type, T.DecimalType) and (
                a.output_type.is_long
            ):
                out.append(
                    Block(d128.from_int64(total), a.output_type, has)
                )
            else:
                out.append(
                    Block(
                        total.astype(a.output_type.storage_dtype),
                        a.output_type, has,
                    )
                )
            continue
        ch = by_agg[ai][(a.func, 0)]
        col = pmin[:, ch] if a.func == "min" else pmax[:, ch]
        out.append(
            Block(col.astype(a.output_type.storage_dtype), a.output_type, has)
        )
    return out
