"""Pallas-native hash join kernels on a linear-probe slot table.

The reference engine closes the hash-relational perf gap with runtime
bytecode generation (presto-main sql/gen: JoinCompiler emits a
PagesHash + PositionLinks per key signature). The TPU-native equivalent
is a custom kernel family over an explicit HASH TABLE layout
(arXiv:1905.13376's build/probe/multiway design), replacing the
sorted-hash + bucket-directory BuildSide of ops/join.py on backends
where it wins:

* BUILD — bulk parallel linear-probing insert into a power-of-two slot
  array: every pending row scatters its id at (desired slot + round k),
  a gather confirms the winner (the CAS-free formulation of the paper's
  atomic insert; any race winner yields the same probe results), losers
  advance to round k+1. Rows still unplaced after R_MAX rounds (heavy
  single-key skew: duplicates place one per round) move to a dense
  tag-sorted OVERFLOW region probed by binary search — the table never
  degrades quadratically and never wraps (a guaranteed-empty sentinel
  slot terminates every scan).
* PROBE — per probe row: scan slots from the key's desired slot until
  the first EMPTY slot, collecting 32-bit tag matches; true key
  equality (dictionary-unified for varchar) decides membership, so tag
  collisions only cost a re-check. First-match (n1 / semi / anti mark)
  and count-then-emit (1:N expand, statically sized output) variants.
* MULTIWAY — one pass over the probe batch chains two or more build
  tables (star-shaped joins): each fact batch resolves every dimension
  before any intermediate page is materialized or compacted.

Backend dispatch (all behind the pallas_join_build / pallas_join_probe
circuit breakers in exec/breaker.py, with ops/join.py's sorted-hash
composition as the fallback):

* cpu  — the numpy host path below IS the engine default: scans are
  cache-resident C loops and beat both XLA's comparison sort (build)
  and its gather cascades (probe) by 3-10x. Callers route these joins
  AROUND jit (the ops/sort.py host-sort idiom); everything here
  requires concrete operands.
* tpu  — the same scan expressed as Pallas kernels (slot arrays resident
  in VMEM, probe rows blocked over a grid; Mosaic-compiled through the
  axon tunnel, interpret mode in CI). PRESTO_TPU_PALLAS_JOIN=interp
  forces the kernels (interpret mode) on any backend so the kernel path
  itself is CI-tested, not just its host twin.

Partition-bounded inputs: exec/stream.py's hybrid join hands partitions
through the ragged paged layout (ops/ragged.py), which bounds every
build side a kernel sees — that is what keeps slot arrays VMEM-sized on
TPU and keeps R_MAX displacement bounds honest under skew.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..expr.compiler import evaluate
from ..expr.functions import Val
from ..page import Block, Page
from .hashing import np_hash_rows_values, value_hashable

EMPTY_TAG = np.uint32(0xFFFFFFFF)  # slot sentinel; real tags clamp below it
R_MAX = 64  # bounded insert rounds; leftovers go to the overflow region
TABLE_MAX_BUILD = 1 << 22  # larger builds keep the sorted-hash layout
_MAX_BITS = 23


def _concrete(*arrays) -> bool:
    """True when every operand is a real array (not a jit/vmap tracer) —
    the table path runs eagerly by design (host numpy on cpu, eager
    pallas on tpu); under a trace callers use the sorted-hash path."""
    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


def pallas_join_mode() -> str:
    """'host' (numpy), 'pallas' (Mosaic on tpu), 'interp' (pallas
    interpret mode — CI validation of the kernels on any backend), or
    'off'. Resolved per call so tests can flip the env."""
    forced = os.environ.get("PRESTO_TPU_PALLAS_JOIN", "")
    if forced in ("0", "off"):
        return "off"
    if forced == "interp":
        return "interp"
    return "pallas" if jax.default_backend() == "tpu" else "host"


@dataclasses.dataclass
class JoinTable:
    """Linear-probe hash table over one build page (the JoinCompiler
    PagesHash analog). slot arrays have nslots + R_MAX + 1 entries; the
    final entry is permanently EMPTY so scans terminate without wrap.
    Overflow rows (unplaced after R_MAX rounds) sit tag-sorted in
    of_tag/of_row."""

    slot_tag: np.ndarray  # uint32; EMPTY_TAG = vacant
    slot_row: np.ndarray  # int32 build row id; -1 = vacant
    bits: int  # desired slot = tag >> (32 - bits)
    of_tag: np.ndarray  # uint32, sorted ascending (may be empty)
    of_row: np.ndarray  # int32
    page: Page  # build page (payload gathers)
    key_vals: Tuple[Val, ...]  # evaluated build keys (original order)
    key_exprs: tuple  # for the sorted-path rebuild on kernel fault
    count: int  # live build rows
    inserted: int  # rows in the slot array (count - null-key - overflow)

    def occupancy(self) -> float:
        """Live fraction of the power-of-two slot array — the EXPLAIN
        ANALYZE page-table/occupancy metric for this build."""
        return self.inserted / max(1 << self.bits, 1)


def _tag_desired(h: np.ndarray, bits: int):
    """(uint32 tag, int64 desired slot) from 64-bit row hashes. The tag
    keeps the TOP hash bits (desired is derived from the tag alone, so
    kernels carry one array), clamped below the EMPTY sentinel."""
    t = (np.asarray(h) >> np.uint64(32)).astype(np.uint32)
    t = np.minimum(t, np.uint32(0xFFFFFFFE))
    d = (t >> np.uint32(32 - bits)).astype(np.int64)
    return t, d


def _np_live(page: Page) -> np.ndarray:
    """Concrete live mask without an eager device op."""
    return np.arange(page.capacity) < int(page.count)


def _pick_bits(n: int) -> int:
    bits = max(4, int(np.ceil(np.log2(max(n, 1) * 2))))
    return min(bits, _MAX_BITS)


# -- build -------------------------------------------------------------------


def _host_insert(tag: np.ndarray, rows: np.ndarray, bits: int):
    """Parallel linear-probing insert (host twin of the Pallas kernel):
    round k scatters pending rows at desired+k (last writer wins the
    slot), a gather confirms placement, losers continue. Returns the
    slot arrays plus the row ids that overflowed R_MAX rounds."""
    nslots = 1 << bits
    size = nslots + R_MAX + 1
    slot_tag = np.full(size, EMPTY_TAG, np.uint32)
    slot_row = np.full(size, -1, np.int32)
    desired = (tag >> np.uint32(32 - bits)).astype(np.int64)
    limit = size - 2  # last slot stays EMPTY forever
    # round 0 on FULL vectors (every live row is pending; the index
    # indirection below only pays once the pending set has shrunk)
    live = rows >= 0
    cand0 = np.minimum(desired, limit)
    slot_row[np.where(live, cand0, size - 1)] = np.where(live, rows, -1)
    slot_row[size - 1] = -1
    won0 = live & (slot_row[cand0] == rows)
    slot_tag[cand0[won0]] = tag[won0]
    pend = np.flatnonzero(live & ~won0)
    for k in range(1, R_MAX):
        if not len(pend):
            break
        cand = np.minimum(desired[pend] + k, limit)
        vacant = slot_row[cand] == -1
        trial = pend[vacant]
        if len(trial):
            tc = cand[vacant]
            slot_row[tc] = rows[trial]  # races: last writer wins
            won = slot_row[tc] == rows[trial]
            tw = tc[won]
            slot_tag[tw] = tag[trial[won]]
            placed = np.zeros(len(pend), bool)
            placed[np.flatnonzero(vacant)[won]] = True
            pend = pend[~placed]
        # occupied slots (incl. freshly won) simply advance to k+1
    return slot_tag, slot_row, pend


def _host_build(
    tag: np.ndarray, live_rows: np.ndarray, bits: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    slot_tag, slot_row, left = _host_insert(tag, live_rows, bits)
    if len(left):
        of_order = np.argsort(tag[left], kind="stable")
        of_tag = tag[left][of_order]
        of_row = live_rows[left][of_order].astype(np.int32)
    else:
        of_tag = np.empty(0, np.uint32)
        of_row = np.empty(0, np.int32)
    inserted = int((slot_row >= 0).sum())
    return slot_tag, slot_row, of_tag, of_row, inserted


def _pallas_insert_kernel(nrows: int, size: int, rounds: int):
    """Pallas build kernel: the same scatter/confirm rounds with the slot
    arrays resident in VMEM (one grid step — partition-bounded builds).
    Races between lanes scattering into one slot resolve to SOME lane
    (matching the host path's last-writer semantics); the confirming
    gather makes every resolution yield identical join results."""
    from jax.experimental import pallas as pl  # noqa: F401 (kernel ctx)

    def kernel(tag_ref, row_ref, desired_ref, st_ref, sr_ref, pend_ref):
        st_ref[:] = jnp.full((size,), EMPTY_TAG, jnp.uint32)
        sr_ref[:] = jnp.full((size,), -1, jnp.int32)
        limit = size - 2
        tag = tag_ref[:]
        row = row_ref[:]
        desired = desired_ref[:]
        pending = row >= 0

        def one_round(k, state):
            st, sr, pending = state
            cand = jnp.minimum(desired + k, limit)
            vacant = pending & (sr[cand] == -1)
            tc = jnp.where(vacant, cand, size - 1)
            sr = sr.at[tc].set(jnp.where(vacant, row, -1))
            sr = sr.at[size - 1].set(-1)
            won = vacant & (sr[tc] == row)
            st = st.at[jnp.where(won, tc, size - 1)].set(
                jnp.where(won, tag, EMPTY_TAG)
            )
            st = st.at[size - 1].set(EMPTY_TAG)
            return st, sr, pending & ~won

        st, sr, pending = jax.lax.fori_loop(
            0, rounds, one_round,
            (st_ref[:], sr_ref[:], pending),
        )
        st_ref[:] = st
        sr_ref[:] = sr
        pend_ref[:] = pending.astype(jnp.int32)

    return kernel


# prestolint: host-function -- eager host orchestration around the
# insert kernel (concrete arrays in, overflow sort on the host)
def _pallas_build(tag, live_rows, bits: int, interpret: bool):
    """Run the insert kernel; overflow handling (tag sort of the rare
    leftovers) stays outside the kernel — sorting has no Mosaic lowering
    (ops/pallas_groupby.py has the same split)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nslots = 1 << bits
    size = nslots + R_MAX + 1
    n = len(live_rows)
    tag = jnp.asarray(tag)
    rowsj = jnp.asarray(live_rows, dtype=jnp.int32)
    desired = (tag >> jnp.uint32(32 - bits)).astype(jnp.int32)
    kernel = _pallas_insert_kernel(n, size, R_MAX)
    fn = _cached_pallas(
        ("pallas_join_build", n, size, R_MAX, interpret),
        lambda: pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
            out_specs=(
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((size,), jnp.uint32),
                jax.ShapeDtypeStruct((size,), jnp.int32),
                jax.ShapeDtypeStruct((n,), jnp.int32),
            ),
            interpret=interpret,
        ),
    )
    st, sr, pend = fn(tag, rowsj, desired)
    st, sr = np.asarray(st), np.asarray(sr)
    left = np.flatnonzero(np.asarray(pend))
    tag_np = np.asarray(tag)
    if len(left):
        rows_np = np.asarray(live_rows)
        of_order = np.argsort(tag_np[left], kind="stable")
        of_tag = tag_np[left][of_order]
        of_row = rows_np[left][of_order].astype(np.int32)
    else:
        of_tag = np.empty(0, np.uint32)
        of_row = np.empty(0, np.int32)
    return st, sr, of_tag, of_row, int((sr >= 0).sum())


def _cached_pallas(key, make_fn):
    """Compiled pallas_call reuse through the process-wide kernel cache
    (exec/qcache.KERNEL_CACHE) — cross-query compile amortization, same
    contract as Executor._kernel."""
    from ..exec.qcache import KERNEL_CACHE

    gkey = (jax.default_backend(), "pallas_join", key)
    fn = KERNEL_CACHE.get(gkey)
    if fn is None:
        fn = make_fn()
        KERNEL_CACHE.put(gkey, fn)
    return fn


# prestolint: host-function -- eager host orchestration: evaluates keys on
# device, then builds the host-resident slot arrays
def build_table(page: Page, key_exprs) -> Optional[JoinTable]:
    """Build the linear-probe JoinTable for a build page, or None when
    the shape is ineligible (caller falls back to the sorted-hash
    BuildSide): traced operands, empty key list (cross join), oversized
    build, huge-dictionary varchar keys, or a disabled mode."""
    mode = pallas_join_mode()
    if mode == "off" or not key_exprs:
        return None
    if page.capacity > TABLE_MAX_BUILD:
        return None
    keys = [evaluate(e, page) for e in key_exprs]
    datas = [k.data for k in keys] + [
        k.valid for k in keys if k.valid is not None
    ]
    if not _concrete(page.count, *datas):
        return None
    if not value_hashable(keys):
        return None
    h = np_hash_rows_values(keys)
    n = page.capacity
    cnt = int(page.count)
    bits = _pick_bits(cnt)
    tag_np, _ = _tag_desired(h, bits)
    # insert only live rows with fully NON-NULL keys: SQL equi-joins
    # never match NULL, and skew-heavy NULL columns would otherwise
    # pile into one chain
    live = _np_live(page)
    for k in keys:
        if k.valid is not None:
            live = live & np.asarray(k.valid)
    rows = np.where(live, np.arange(n, dtype=np.int32), -1).astype(np.int32)
    if mode in ("pallas", "interp"):
        st, sr, of_tag, of_row, inserted = _pallas_build(
            tag_np, rows, bits, interpret=(mode == "interp")
        )
    else:
        st, sr, of_tag, of_row, inserted = _host_build(tag_np, rows, bits)
    return JoinTable(
        st, sr, bits, of_tag, of_row, page, tuple(keys),
        tuple(key_exprs), cnt, inserted,
    )


# -- key verification --------------------------------------------------------


def _comparable_pair(pv: Val, bv: Val):
    """(probe array, build array) made directly comparable: varchar
    columns with differing dictionaries translate through one unified
    dictionary (ops/join._keys_equal does the same per-gather; here it
    happens ONCE per batch so the scan loop compares plain ints)."""
    if (
        isinstance(pv.type, T.VarcharType)
        and pv.dict_id is not None
        and bv.dict_id is not None
        and pv.dict_id != bv.dict_id
    ):
        from ..expr.functions import unify_dictionaries

        pd_, bd_, _ = unify_dictionaries(pv, bv)
        return np.asarray(pd_), np.asarray(bd_)
    return np.asarray(pv.data), np.asarray(bv.data)


def _host_prepare_keys(jt: JoinTable, probe_keys: Sequence[Val]):
    """Per-key comparable numpy arrays + validity, prepared once per
    probe batch for the in-scan verifier."""
    prep = []
    for pv, bv in zip(probe_keys, jt.key_vals):
        pd_, bd_ = _comparable_pair(pv, bv)
        if jnp.issubdtype(jnp.asarray(pd_).dtype, jnp.floating):
            # canonicalize NaN payloads like ops/hashing: all NaN compare
            # unequal anyway (SQL equi-join), -0.0 == 0.0 holds in numpy
            pass
        prep.append(
            (
                pd_,
                bd_,
                None if pv.valid is None else np.asarray(pv.valid),
                None if bv.valid is None else np.asarray(bv.valid),
            )
        )
    return prep


def _host_verify(prep, probe_idx: np.ndarray, build_rows: np.ndarray):
    """True key equality probe[i] == build[row]; NULL never matches."""
    ok = np.ones(len(probe_idx), bool)
    for pd_, bd_, pvld, bvld in prep:
        a = pd_[probe_idx]
        b = bd_[build_rows]
        part = a == b
        if part.ndim == 2:  # long-decimal lanes
            part = part.all(axis=-1)
        if pvld is not None:
            part = part & pvld[probe_idx]
        if bvld is not None:
            part = part & bvld[build_rows]
        ok &= part
    return ok


# -- probe: first verified match (n1 / semi / anti / mark) -------------------


def _host_probe_n1(jt: JoinTable, ptag, pdesired, live, prep):
    """First VERIFIED match per probe row: scan from the desired slot
    until the first EMPTY slot; tag matches verify true key equality
    in-scan (collisions continue scanning). Returns (matched, build_row)."""
    m = len(ptag)
    matched = np.zeros(m, bool)
    brow = np.zeros(m, np.int32)
    limit = len(jt.slot_tag) - 1
    # step 0 on FULL vectors: at load <= 1/2 nearly every probe resolves
    # at its desired slot, so the first step skips the active-index
    # indirection entirely (measured ~30% of host probe wall)
    cand = np.minimum(pdesired, limit)
    t = jt.slot_tag[cand]
    hit = (t == ptag) & live
    if hit.any():
        hidx = np.flatnonzero(hit)
        rows_c = jt.slot_row[cand[hidx]]
        ok = _host_verify(prep, hidx, rows_c)
        matched[hidx[ok]] = True
        brow[hidx[ok]] = rows_c[ok]
    active = np.flatnonzero(live & (t != EMPTY_TAG) & ~matched)
    k = 1
    while len(active) and k <= limit:
        cand = np.minimum(pdesired[active] + k, limit)
        t = jt.slot_tag[cand]
        hit = t == ptag[active]
        if hit.any():
            hidx = active[hit]
            rows_c = jt.slot_row[cand[hit]]
            ok = _host_verify(prep, hidx, rows_c)
            matched[hidx[ok]] = True
            brow[hidx[ok]] = rows_c[ok]
            cont = t != EMPTY_TAG
            cont[hit] &= ~ok
        else:
            cont = t != EMPTY_TAG
        active = active[cont]
        k += 1
    if len(jt.of_tag):
        pend = np.flatnonzero(live & ~matched)
        if len(pend):
            m2, b2 = _host_probe_overflow(jt, ptag, prep, pend)
            matched[m2] = True
            brow[m2] = b2
    return matched, brow


def _pallas_probe_kernel(size: int, blk: int, max_scan: int):
    """Pallas probe kernel: table arrays whole in VMEM, probe rows
    blocked (blk x 128) over the grid. Emits the first TAG-match
    position per row plus a needs-more flag for rows whose scan ran past
    max_scan without hitting EMPTY — the eager caller resolves those
    (and any tag match that fails true key equality) with the bounded
    continuation scan, so max_scan caps VMEM work, not correctness."""

    def kernel(st_ref, sr_ref, tag_ref, des_ref, start_ref, out_pos,
               out_row, out_more):
        st = st_ref[:]
        sr = sr_ref[:]
        ptag = tag_ref[:]
        des = des_ref[:]
        start = start_ref[:]
        limit = size - 1
        found = jnp.zeros(ptag.shape, jnp.bool_)
        pos = jnp.full(ptag.shape, -1, jnp.int32)
        row = jnp.full(ptag.shape, -1, jnp.int32)
        ended = jnp.zeros(ptag.shape, jnp.bool_)
        for k in range(max_scan):
            cand = jnp.minimum(des + start + k, limit)
            t = jnp.take(st, cand)
            hit = (~found) & (~ended) & (t == ptag)
            pos = jnp.where(hit, cand, pos)
            row = jnp.where(hit, jnp.take(sr, cand), row)
            found = found | hit
            ended = ended | (t == EMPTY_TAG)
        out_pos[:] = pos
        out_row[:] = row
        out_more[:] = ((~found) & (~ended)).astype(jnp.int32)

    return kernel


# prestolint: host-function -- eager host orchestration around the
# probe kernel (pads/blocks concrete probe arrays for the grid)
def _pallas_probe_first(jt: JoinTable, ptag, pdesired, start, interpret,
                        max_scan: int = 16):
    """One kernel launch: first tag-match pos/row per probe row from
    scan offset `start`, plus the needs-deeper-scan flag."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = len(ptag)
    pad = -m % 128
    size = len(jt.slot_tag)

    def pad1(x, fill):
        x = jnp.asarray(x)
        return jnp.pad(x, (0, pad), constant_values=fill) if pad else x

    view = lambda x: x.reshape(-1, 128)
    rows2 = (m + pad) // 128
    fn = _cached_pallas(
        ("pallas_join_probe", size, rows2, max_scan, interpret),
        lambda: pl.pallas_call(
            _pallas_probe_kernel(size, rows2, max_scan),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 5,
            out_specs=(
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((rows2, 128), jnp.int32),
                jax.ShapeDtypeStruct((rows2, 128), jnp.int32),
                jax.ShapeDtypeStruct((rows2, 128), jnp.int32),
            ),
            interpret=interpret,
        ),
    )
    pos, row, more = fn(
        jnp.asarray(jt.slot_tag),
        jnp.asarray(jt.slot_row),
        view(pad1(ptag, 0)),
        view(pad1(pdesired.astype(np.int32), 0)),
        view(pad1(start.astype(np.int32), 0)),
    )
    flat = lambda x: np.asarray(x).reshape(-1)[:m]
    return flat(pos), flat(row), flat(more).astype(bool)


def _probe_n1(jt: JoinTable, ptag, pdesired, live, prep, mode: str):
    """Backend-dispatched first-verified-match probe."""
    if mode not in ("pallas", "interp"):
        return _host_probe_n1(jt, ptag, pdesired, live, prep)
    m = len(ptag)
    matched = np.zeros(m, bool)
    brow = np.zeros(m, np.int32)
    start = np.zeros(m, np.int32)
    pend = np.flatnonzero(live)
    rounds = 0
    limit = len(jt.slot_tag) - 1
    while len(pend) and rounds <= limit:
        pos, row, more = _pallas_probe_first(
            jt, ptag[pend], pdesired[pend], start[pend],
            interpret=(mode == "interp"),
        )
        got = pos >= 0
        ok = np.zeros(len(pend), bool)
        if got.any():
            ok[got] = _host_verify(prep, pend[got], row[got])
            matched[pend[ok]] = True
            brow[pend[ok]] = row[ok]
        # continue: verified-failed tag matches scan past their match
        # position; truncated scans (more) resume where the kernel left
        cont = (got & ~ok) | more
        start[pend[got & ~ok]] = (
            pos[got & ~ok] - pdesired[pend[got & ~ok]] + 1
        )
        start[pend[more & ~got]] += 16
        pend = pend[cont]
        rounds += 1
    if len(jt.of_tag):
        rest = np.flatnonzero(live & ~matched)
        if len(rest):
            m2, b2 = _host_probe_overflow(jt, ptag, prep, rest)
            matched[m2] = True
            brow[m2] = b2
    return matched, brow


def _host_probe_overflow(jt: JoinTable, ptag, prep, pend):
    """First verified match within the tag-sorted overflow region."""
    lo = np.searchsorted(jt.of_tag, ptag[pend], side="left")
    hi = np.searchsorted(jt.of_tag, ptag[pend], side="right")
    sel = lo < hi
    act, lo, hi = pend[sel], lo[sel], hi[sel]
    out_idx: List[np.ndarray] = []
    out_row: List[np.ndarray] = []
    while len(act):
        rows_c = jt.of_row[lo]
        ok = _host_verify(prep, act, rows_c)
        out_idx.append(act[ok])
        out_row.append(rows_c[ok])
        lo = lo + 1
        keep = (~ok) & (lo < hi)
        act, lo, hi = act[keep], lo[keep], hi[keep]
    if out_idx:
        return np.concatenate(out_idx), np.concatenate(out_row)
    return np.empty(0, np.int64), np.empty(0, np.int32)


# -- probe: all matches (1:N expand, count-then-emit) ------------------------


def _host_probe_all(jt: JoinTable, ptag, pdesired, live, prep):
    """EVERY verified match as (probe row, build row) pair arrays —
    the count-then-emit shape: callers size output from len(pairs)."""
    limit = len(jt.slot_tag) - 1
    pi: List[np.ndarray] = []
    bi: List[np.ndarray] = []
    # step 0 on full vectors (see _host_probe_n1)
    cand = np.minimum(pdesired, limit)
    t = jt.slot_tag[cand]
    hit = (t == ptag) & live
    if hit.any():
        hidx = np.flatnonzero(hit)
        rows_c = jt.slot_row[cand[hidx]]
        ok = _host_verify(prep, hidx, rows_c)
        pi.append(hidx[ok])
        bi.append(rows_c[ok])
    active = np.flatnonzero(live & (t != EMPTY_TAG))
    k = 1
    while len(active) and k <= limit:
        cand = np.minimum(pdesired[active] + k, limit)
        t = jt.slot_tag[cand]
        hit = t == ptag[active]
        if hit.any():
            hidx = active[hit]
            rows_c = jt.slot_row[cand[hit]]
            ok = _host_verify(prep, hidx, rows_c)
            pi.append(hidx[ok])
            bi.append(rows_c[ok])
        active = active[t != EMPTY_TAG]
        k += 1
    if len(jt.of_tag):
        pend = np.flatnonzero(live)
        lo = np.searchsorted(jt.of_tag, ptag[pend], side="left")
        hi = np.searchsorted(jt.of_tag, ptag[pend], side="right")
        sel = lo < hi
        act, lo, hi = pend[sel], lo[sel], hi[sel]
        while len(act):
            rows_c = jt.of_row[lo]
            ok = _host_verify(prep, act, rows_c)
            pi.append(act[ok])
            bi.append(rows_c[ok])
            lo = lo + 1
            keep = lo < hi
            act, lo, hi = act[keep], lo[keep], hi[keep]
    if pi:
        probe_idx = np.concatenate(pi)
        build_idx = np.concatenate(bi)
        # probe-row-major pair order (stable by scan step within a row)
        order = np.argsort(probe_idx, kind="stable")
        return probe_idx[order], build_idx[order]
    return np.empty(0, np.int64), np.empty(0, np.int32)


# -- page emission (host) ----------------------------------------------------


def _np_block(b: Block):
    return (
        np.asarray(b.data),
        None if b.valid is None else np.asarray(b.valid),
    )


def _emit_gather(b: Block, idx: np.ndarray, capacity: int,
                 extra_valid: Optional[np.ndarray] = None) -> Block:
    """Gather block rows by host indices into a capacity-padded Block
    (tail rows are dead by the page count invariant, so np.empty tails
    cost nothing)."""
    data, valid = _np_block(b)
    n = len(idx)
    out = np.empty((capacity,) + data.shape[1:], data.dtype)
    out[:n] = data[idx]
    # rows beyond n stay uninitialized: the page contract masks them out
    # (live rows occupy [0, count)), and skipping the tail fill saves a
    # full write pass per column
    v = None
    if valid is not None or extra_valid is not None:
        v = np.zeros(capacity, bool)
        vv = np.ones(n, bool) if valid is None else valid[idx]
        if extra_valid is not None:
            vv = vv & extra_valid
        v[:n] = vv
    return Block(
        jnp.asarray(out), b.type,
        None if v is None else jnp.asarray(v), b.dict_id,
    )


def _host_compact_page(page: Page, keep: np.ndarray) -> Page:
    """compact() twin for concrete pages: ONE flatnonzero + gathers
    instead of a full-capacity sort (ops/filter.py documents why the
    device path sorts; on the host the C gather wins)."""
    idx = np.flatnonzero(keep)
    blocks = tuple(
        _emit_gather(b, idx, page.capacity) for b in page.blocks
    )
    return Page(blocks, page.names, jnp.int32(len(idx)))


# -- public: the kernel-side join API ----------------------------------------


# prestolint: host-function -- eager host orchestration around the kernels
def table_join_n1(
    probe: Page,
    jt: JoinTable,
    probe_key_exprs,
    build_names: Sequence[str],
    out_build_names: Sequence[str],
    kind: str = "inner",
) -> Page:
    """join_n1 over the hash table (inner | left | semi | anti)."""
    probe_keys = [evaluate(e, probe) for e in probe_key_exprs]
    live = _np_live(probe)
    h = np_hash_rows_values(probe_keys)
    ptag, pdesired = _tag_desired(h, jt.bits)
    prep = _host_prepare_keys(jt, probe_keys)
    matched, brow = _probe_n1(
        jt, ptag, pdesired, live, prep, pallas_join_mode()
    )
    if kind == "semi":
        return _host_compact_page(probe, matched & live)
    if kind == "anti":
        return _host_compact_page(probe, ~matched & live)
    if kind == "inner":
        idx = np.flatnonzero(matched & live)
        blocks = [
            _emit_gather(b, idx, probe.capacity) for b in probe.blocks
        ]
        names = list(probe.names)
        bidx = brow[idx]
        for bname, oname in zip(build_names, out_build_names):
            b = jt.page.block(bname)
            blocks.append(_emit_gather(b, bidx, probe.capacity))
            names.append(oname)
        return Page(tuple(blocks), tuple(names), jnp.int32(len(idx)))
    if kind == "left":
        blocks = list(probe.blocks)
        names = list(probe.names)
        srow = np.where(matched, brow, 0)
        for bname, oname in zip(build_names, out_build_names):
            b = jt.page.block(bname)
            data, valid = _np_block(b)
            out = data[srow]
            v = matched if valid is None else (matched & valid[srow])
            blocks.append(
                Block(jnp.asarray(out), b.type, jnp.asarray(v), b.dict_id)
            )
            names.append(oname)
        return Page(tuple(blocks), tuple(names), probe.count)
    raise ValueError(f"unknown join kind {kind!r}")


# prestolint: host-function -- eager host orchestration around the kernels
def table_semi_mask(probe: Page, jt: JoinTable, probe_key_exprs):
    """semi_match_mask over the hash table (mark-join kernel)."""
    probe_keys = [evaluate(e, probe) for e in probe_key_exprs]
    live = _np_live(probe)
    h = np_hash_rows_values(probe_keys)
    ptag, pdesired = _tag_desired(h, jt.bits)
    prep = _host_prepare_keys(jt, probe_keys)
    matched, _ = _probe_n1(
        jt, ptag, pdesired, live, prep, pallas_join_mode()
    )
    return jnp.asarray(matched & live)


# prestolint: host-function -- eager host orchestration around the kernels
def table_join_expand(
    probe: Page,
    jt: JoinTable,
    probe_key_exprs,
    probe_out: Sequence[str],
    build_out: Sequence[Tuple[str, str]],
    out_capacity: int,
    kind: str = "inner",
) -> Tuple[Page, jnp.ndarray]:
    """join_expand over the hash table: count-then-emit, exact rows.

    Pairs are VERIFIED matches (not hash-range candidates), so overflow
    reports exactly total_matches - out_capacity and one retry always
    suffices."""
    probe_keys = [evaluate(e, probe) for e in probe_key_exprs]
    live = _np_live(probe)
    h = np_hash_rows_values(probe_keys)
    ptag, pdesired = _tag_desired(h, jt.bits)
    prep = _host_prepare_keys(jt, probe_keys)
    probe_idx, build_idx = _host_probe_all(
        jt, ptag, pdesired, live, prep
    )
    if kind == "left":
        # one NULL-extended row for every live probe row with no match
        has = np.zeros(probe.capacity, bool)
        has[probe_idx] = True
        synth = np.flatnonzero(live & ~has)
        probe_idx = np.concatenate([probe_idx, synth])
        build_idx = np.concatenate(
            [build_idx.astype(np.int64), np.full(len(synth), -1, np.int64)]
        )
        order = np.argsort(probe_idx, kind="stable")
        probe_idx, build_idx = probe_idx[order], build_idx[order]
    total = len(probe_idx)
    emit = min(total, out_capacity)
    pidx = probe_idx[:emit]
    bidx = np.maximum(build_idx[:emit], 0)
    bvalid = build_idx[:emit] >= 0
    blocks, names = [], []
    for name in probe_out:
        blocks.append(
            _emit_gather(probe.block(name), pidx, out_capacity)
        )
        names.append(name)
    for bname, oname in build_out:
        blocks.append(
            _emit_gather(
                jt.page.block(bname), bidx, out_capacity,
                extra_valid=bvalid,
            )
        )
        names.append(oname)
    out = Page(tuple(blocks), tuple(names), jnp.int32(emit))
    overflow = jnp.asarray(max(total - out_capacity, 0), jnp.int64)
    return out, overflow


# prestolint: host-function -- eager host orchestration around the kernels
def table_multiway_n1(
    probe: Page,
    specs: Sequence[Tuple[JoinTable, tuple, Sequence[str], Sequence[str]]],
) -> Page:
    """Multiway probe: chain TWO (or more) build tables through ONE pass
    over the probe batch (arXiv:1905.13376's multiway variant — the
    star-join shape where every key lives on the fact side). INNER
    semantics with at-most-one match per side: the batch survives all
    sides' probes before any output page is materialized, replacing
    len(specs) joins' worth of intermediate pages and compactions with
    one emit."""
    keep = _np_live(probe)
    gathered: List[Tuple[np.ndarray, JoinTable, Sequence[str],
                         Sequence[str]]] = []
    mode = pallas_join_mode()
    for jt, key_exprs, build_names, out_names in specs:
        probe_keys = [evaluate(e, probe) for e in key_exprs]
        h = np_hash_rows_values(probe_keys)
        ptag, pdesired = _tag_desired(h, jt.bits)
        prep = _host_prepare_keys(jt, probe_keys)
        matched, brow = _probe_n1(
            jt, ptag, pdesired, keep, prep, mode
        )
        keep &= matched
        gathered.append((brow, jt, build_names, out_names))
    idx = np.flatnonzero(keep)
    blocks = [_emit_gather(b, idx, probe.capacity) for b in probe.blocks]
    names = list(probe.names)
    for brow, jt, build_names, out_names in gathered:
        bidx = brow[idx]
        for bname, oname in zip(build_names, out_names):
            blocks.append(
                _emit_gather(jt.page.block(bname), bidx, probe.capacity)
            )
            names.append(oname)
    return Page(tuple(blocks), tuple(names), jnp.int32(len(idx)))
