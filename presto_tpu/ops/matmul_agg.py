"""Grouped aggregation as one-hot matmuls on the MXU.

The high-NDV middle ground between the small-G Pallas kernel
(ops/pallas_groupby.py, G <= 32) and the general hash-sort strategy
(ops/aggregate.grouped_aggregate_sorted): for dense group ids up to
G = 4096, grouped count/sum/avg is literally a matrix product —

    partials[g, c] = sum_rows onehot[row, g] * channel[row, c]
                   = (onehot^T @ channels)[g, c]

which is exactly what the MXU does at hundreds of TFLOP/s, vs the sort
strategy whose cost is dominated by an O(n log^2 n) XLA sort. The
reference's analog is the dense array-addressed group-by fast path for
small integer keys (presto-main/.../operator/aggregation/
BigintGroupByHash.java:52 — when keys fit a dense range it indexes an
array instead of hashing); the MXU formulation is the TPU-native
equivalent of that dense addressing.

Exactness (this path is EXACT, not approximate): integer inputs are
decomposed into SIGN-SPLIT 7-bit limbs (8 limbs cover |x| < 2^56; the
per-type sum contract sum|x| < 2^63 is the same one the other
strategies rely on). Each limb value (0..127) is exact in bfloat16;
one-hot entries are 0/1; per-chunk dot products accumulate in f32 where
partial sums stay below 127 * CHUNK_ROWS = 2.6e5 << 2^24, so every f32
partial is integral and exact; chunk partials accumulate in int64
outside the dot. Float inputs are NOT eligible (the Pallas or sort
strategies take those).

Group keys: dictionary varchar / boolean (like the Pallas path) plus
dense-range INTEGER keys — the executor host-syncs the key's min/max
(it already syncs per-aggregation for adaptive capacity) and any key
whose value range fits the group budget gets dense codes. NULL keys
form their own group (SQL semantics), encoded as an extra slot per key.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..expr.compiler import evaluate
from ..page import Block, Page
from .aggregate import AggSpec, avg_from_sum_count

MATMUL_MAX_GROUPS = 4096
CHUNK_ROWS = 2048
LIMB_BITS = 7
N_LIMBS = 8  # covers |x| < 2^56
MAX_CHANNELS = 512
_SUPPORTED = {"count", "count_star", "sum", "avg"}


def _limb_channels(x, mask):
    """Sign-split 7-bit limb channels of int64 `x` under `mask`:
    2 * N_LIMBS bf16 columns (positive limbs, then negated-negative)."""
    pos = jnp.where(mask & (x >= 0), x, 0)
    neg = jnp.where(mask & (x < 0), -x, 0)
    cols = []
    for src in (pos, neg):
        for k in range(N_LIMBS):
            cols.append(
                ((src >> (LIMB_BITS * k)) & 0x7F).astype(jnp.bfloat16)
            )
    return cols


def _recombine(s, base):
    """int64 limb sums (G, nch) at channel offset base -> (G,) int64."""
    total = s[:, base]
    for k in range(1, N_LIMBS):
        total = total + (s[:, base + k] << (LIMB_BITS * k))
    return total


def grouped_matmul_partials(gid, channels, G: int):
    """(G, nch) int64 exact channel sums via chunked one-hot matmuls.

    gid: int32 (n,) in [0, G) (dead rows must carry all-zero channels);
    channels: list of (n,) bf16 columns."""
    n = gid.shape[0]
    nch = len(channels)
    pad = -n % CHUNK_ROWS
    if pad:
        gid = jnp.pad(gid, (0, pad))
        channels = [jnp.pad(c, (0, pad)) for c in channels]
        n += pad
    chunks = n // CHUNK_ROWS
    gidm = gid.reshape(chunks, CHUNK_ROWS)
    chm = jnp.stack(channels, axis=-1).reshape(chunks, CHUNK_ROWS, nch)
    garange = jnp.arange(G, dtype=jnp.int32)

    def step(carry, inputs):
        g, ch = inputs
        onehot = (g[:, None] == garange[None, :]).astype(jnp.bfloat16)
        # (G, CHUNK) @ (CHUNK, nch) on the MXU, f32 accumulation
        part = jax.lax.dot_general(
            onehot.T, ch,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return carry + part.astype(jnp.int64), None

    init = jnp.zeros((G, nch), jnp.int64)
    out, _ = jax.lax.scan(step, init, (gidm, chm))
    return out


def plan_matmul_grouped_aggregate(page: Page, group_exprs, aggs, pre_mask):
    """HOST side of eligibility: decide dense domains/bases, syncing key
    min/max where needed. Must run EAGERLY (outside jit) — the resulting
    plan (all python ints) is static, so `_apply` below is traceable.

    Plan = (domains, bases): `bases[i]` is the rebase value for integer
    keys (None otherwise); NULL adds one extra slot per nullable key."""
    if not group_exprs:
        return None
    if any(a.func not in _SUPPORTED for a in aggs):
        return None
    from .aggregate import _masked_live

    live = _masked_live(page, pre_mask)
    domains, bases = [], []
    for e in group_exprs:
        v = evaluate(e, page)
        base = None
        if isinstance(v.type, T.VarcharType) and v.dictionary is not None:
            d = max(len(v.dictionary), 1)
        elif isinstance(v.type, T.BooleanType):
            d = 2
        elif v.data.ndim == 1 and jnp.issubdtype(v.data.dtype, jnp.integer):
            ok = live if v.valid is None else (live & v.valid)
            if not bool(jnp.any(ok)):
                d = 1
            else:
                big = jnp.iinfo(jnp.int64)
                data = v.data.astype(jnp.int64)
                mn = int(jnp.min(jnp.where(ok, data, big.max)))
                mx = int(jnp.max(jnp.where(ok, data, big.min)))
                span = mx - mn + 1
                if span > MATMUL_MAX_GROUPS:
                    return None
                d = int(span)
                base = mn
        else:
            return None
        if v.valid is not None:  # NULL keys get their own group slot
            d += 1
        if d > MATMUL_MAX_GROUPS:
            return None
        domains.append(d)
        bases.append(base)
    total = 1
    for d in domains:
        total *= d
    if not 0 < total <= MATMUL_MAX_GROUPS:
        return None
    return tuple(domains), tuple(bases)


def _key_codes(page: Page, group_exprs, plan):
    """Traceable re-evaluation of keys -> dense codes under a static plan."""
    domains, bases = plan
    keys, codes = [], []
    for e, d, base in zip(group_exprs, domains, bases):
        v = evaluate(e, page)
        d_data = d - (1 if v.valid is not None else 0)  # non-null slots
        if base is not None:
            code = (v.data.astype(jnp.int64) - base).astype(jnp.int32)
        else:
            code = v.data.astype(jnp.int32)
        code = jnp.clip(code, 0, max(d_data - 1, 0))
        if v.valid is not None:
            code = jnp.where(v.valid, code, d - 1)  # null slot = last
        keys.append(v)
        codes.append(code)
    return keys, codes


def maybe_matmul_grouped_aggregate(
    page: Page, group_exprs, group_names, aggs: Sequence[AggSpec], pre_mask,
    plan=None,
) -> Optional[Page]:
    """Route an eligible aggregation through the MXU path; None when not
    eligible (caller falls back to the sort strategy). Pass a
    pre-computed `plan` (plan_matmul_grouped_aggregate) to make this
    call fully traceable under jit."""
    if plan is None:
        plan = plan_matmul_grouped_aggregate(
            page, group_exprs, aggs, pre_mask
        )
    if plan is None:
        return None
    from .aggregate import _masked_live

    live = _masked_live(page, pre_mask)
    keys, codes = _key_codes(page, group_exprs, plan)
    domains, bases = plan
    ins = []
    for a in aggs:
        if a.input is None:
            ins.append(None)
            continue
        v = evaluate(a.input, page)
        if v.data.ndim != 1:
            return None
        if not (
            jnp.issubdtype(v.data.dtype, jnp.integer)
            or isinstance(v.type, T.BooleanType)
        ):
            return None  # floats ride the Pallas / sort strategies
        ins.append(v)

    gid = jnp.zeros(page.capacity, jnp.int32)
    for code, d in zip(codes, domains):
        gid = gid * d + code
    G = 1
    for d in domains:
        G *= d
    gid = jnp.where(live, gid, 0)  # dead rows: gid 0 with zero channels

    # channel plan: (agg idx, role, base channel index)
    channels: List = []
    plan: List[Tuple[int, str, int]] = []
    for ai, (a, v) in enumerate(zip(aggs, ins)):
        m = live if (v is None or v.valid is None) else (live & v.valid)
        if a.func in ("count", "count_star", "avg"):
            plan.append((ai, "count", len(channels)))
            channels.append(m.astype(jnp.bfloat16))
        if a.func in ("sum", "avg"):
            plan.append((ai, "sum", len(channels)))
            channels.extend(_limb_channels(v.data.astype(jnp.int64), m))
    if len(channels) > MAX_CHANNELS:
        return None

    if channels:
        s = grouped_matmul_partials(gid, channels, G)
    else:  # pure GROUP BY / DISTINCT: occupancy only, no dot needed
        s = jnp.zeros((G, 0), jnp.int64)

    def sum_of(base):
        return _recombine(s, base) - _recombine(s, base + N_LIMBS)

    by_agg: dict = {}
    for ai, role, base in plan:
        by_agg.setdefault(ai, {})[role] = base

    # group key columns decoded from the dense gid (mixed radix)
    grange = jnp.arange(G, dtype=jnp.int32)
    rem = grange
    key_codes = []
    for d in reversed(domains):
        key_codes.append(rem % d)
        rem = rem // d
    key_codes = list(reversed(key_codes))
    out_blocks: List[Block] = []
    out_names: List[str] = []
    for v, nm, code, d, base in zip(
        keys, group_names, key_codes, domains, bases
    ):
        valid = None
        if v.valid is not None:  # last slot of this key's radix = NULL
            valid = code < (d - 1)
        if base is not None:
            data = (code.astype(jnp.int64) + base).astype(v.data.dtype)
        else:
            data = code
        out_blocks.append(Block(data, v.type, valid, v.dict_id))
        out_names.append(nm)

    # rows-per-group for empty-group compaction
    group_rows = None
    for ai, a in enumerate(aggs):
        base = by_agg.get(ai, {}).get("count")
        if base is not None:
            group_rows = s[:, base]
            break
    if group_rows is None:
        occ = (
            jnp.zeros(G + 1, jnp.int32)
            .at[jnp.where(live, gid, G)]
            .add(1, mode="drop")
        )
        group_rows = occ[:G].astype(jnp.int64)

    from . import decimal128 as d128

    for ai, a in enumerate(aggs):
        has = group_rows > 0
        roles = by_agg[ai]
        if a.func in ("count", "count_star"):
            out_blocks.append(Block(s[:, roles["count"]], T.BIGINT, None))
        elif a.func == "sum":
            total = sum_of(roles["sum"])
            if isinstance(a.output_type, T.DecimalType) and a.output_type.is_long:
                out_blocks.append(
                    Block(d128.from_int64(total), a.output_type, has)
                )
            else:
                out_blocks.append(
                    Block(
                        total.astype(a.output_type.storage_dtype),
                        a.output_type,
                        has,
                    )
                )
        else:  # avg over ints
            cnt = s[:, roles["count"]]
            data = avg_from_sum_count(
                sum_of(roles["sum"]), cnt, a.output_type, a.input.type
            )
            out_blocks.append(Block(data, a.output_type, cnt > 0))
        out_names.append(a.name)

    out = Page.from_blocks(out_blocks, out_names, count=G)
    from .filter import compact

    return compact(out, group_rows > 0)
