from .aggregate import (  # noqa: F401
    AggSpec,
    global_aggregate,
    grouped_aggregate_direct,
    grouped_aggregate_sorted,
)
from .filter import compact, filter_page, filter_project_page  # noqa: F401
from .hashing import hash_rows  # noqa: F401
from .join import BuildSide, build, build_sorted, join_expand, join_n1  # noqa: F401
from .pallas_join import JoinTable, build_table  # noqa: F401
from .sort import (  # noqa: F401
    SortKey,
    apply_permutation,
    distinct_page,
    limit_page,
    sort_page,
    top_n,
)
