"""Row hashing for group-by / join / repartitioning.

The TPU-native equivalent of the reference's compiled hash strategies
(presto-main/.../sql/gen/JoinCompiler.java hash generation and
operator/InterpretedHashGenerator.java): combine per-column 64-bit hashes into
one row hash with splitmix64-style mixing, fully vectorized. NULLs hash to a
fixed constant and compare equal (SQL GROUP BY/join-on-null semantics are
handled by callers via validity comparison)."""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

# splitmix64 constants; arithmetic in uint64 wraps mod 2^64.
# numpy scalars, NOT jnp arrays: creating a device array at module import
# would force JAX backend initialization during `import presto_tpu`, which
# wedges driver entry points before they can select a platform.
_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_NULL_HASH = np.uint64(0x9AE16A3B2F90404F)


def mix64(x):
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * _C1
    x = (x ^ (x >> 27)) * _C2
    return x ^ (x >> 31)


def hash_column(data, valid: Optional[jnp.ndarray] = None):
    """64-bit hash of one column's storage values (any int/float/bool dtype).
    Multi-lane columns (long decimal, (n, 2) lanes) hash-combine per lane."""
    if data.ndim == 2:
        hs = [hash_column(data[:, i]) for i in range(data.shape[1])]
        h = combine_hashes(hs)
        if valid is not None:
            h = jnp.where(valid, h, _NULL_HASH)
        return h
    if jnp.issubdtype(data.dtype, jnp.floating):
        # canonicalize -0.0 == 0.0 and ALL NaN payloads to one quiet NaN
        # before bitcasting (reference doubleToLongBits semantics: every
        # NaN hashes and groups as the same value)
        data = jnp.where(data == 0, jnp.zeros_like(data), data)
        data = jnp.where(jnp.isnan(data), jnp.full_like(data, jnp.nan), data)
        width = data.dtype.itemsize
        idtype = {4: jnp.uint32, 8: jnp.uint64}[width]
        bits = jnp.asarray(data).view(idtype).astype(jnp.uint64)
    else:
        bits = data.astype(jnp.uint64)
    h = mix64(bits)
    if valid is not None:
        h = jnp.where(valid, h, _NULL_HASH)
    return h


def combine_hashes(hashes: Sequence[jnp.ndarray]):
    """Order-dependent combination (reference CombineHashFunction semantics)."""
    out = jnp.zeros_like(hashes[0])
    for h in hashes:
        out = (out * jnp.uint64(31)) + h
        out = mix64(out + _GOLDEN)
    return out


def hash_rows(columns) -> jnp.ndarray:
    """Hash a sequence of Blocks/Vals (anything with .data/.valid)."""
    hs = [hash_column(c.data, c.valid) for c in columns]
    return combine_hashes(hs) if len(hs) > 1 else hs[0]
