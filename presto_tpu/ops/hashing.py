"""Row hashing for group-by / join / repartitioning.

The TPU-native equivalent of the reference's compiled hash strategies
(presto-main/.../sql/gen/JoinCompiler.java hash generation and
operator/InterpretedHashGenerator.java): combine per-column 64-bit hashes into
one row hash with splitmix64-style mixing, fully vectorized. NULLs hash to a
fixed constant and compare equal (SQL GROUP BY/join-on-null semantics are
handled by callers via validity comparison)."""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

# splitmix64 constants; arithmetic in uint64 wraps mod 2^64.
# numpy scalars, NOT jnp arrays: creating a device array at module import
# would force JAX backend initialization during `import presto_tpu`, which
# wedges driver entry points before they can select a platform.
_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_NULL_HASH = np.uint64(0x9AE16A3B2F90404F)


def mix64(x):
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * _C1
    x = (x ^ (x >> 27)) * _C2
    return x ^ (x >> 31)


def hash_column(data, valid: Optional[jnp.ndarray] = None):
    """64-bit hash of one column's storage values (any int/float/bool dtype).
    Multi-lane columns (long decimal, (n, 2) lanes) hash-combine per lane."""
    if data.ndim == 2:
        hs = [hash_column(data[:, i]) for i in range(data.shape[1])]
        h = combine_hashes(hs)
        if valid is not None:
            h = jnp.where(valid, h, _NULL_HASH)
        return h
    if jnp.issubdtype(data.dtype, jnp.floating):
        # canonicalize -0.0 == 0.0 and ALL NaN payloads to one quiet NaN
        # before bitcasting (reference doubleToLongBits semantics: every
        # NaN hashes and groups as the same value)
        data = jnp.where(data == 0, jnp.zeros_like(data), data)
        data = jnp.where(jnp.isnan(data), jnp.full_like(data, jnp.nan), data)
        width = data.dtype.itemsize
        idtype = {4: jnp.uint32, 8: jnp.uint64}[width]
        bits = jnp.asarray(data).view(idtype).astype(jnp.uint64)
    else:
        bits = data.astype(jnp.uint64)
    h = mix64(bits)
    if valid is not None:
        h = jnp.where(valid, h, _NULL_HASH)
    return h


def combine_hashes(hashes: Sequence[jnp.ndarray]):
    """Order-dependent combination (reference CombineHashFunction semantics)."""
    out = jnp.zeros_like(hashes[0])
    for h in hashes:
        out = (out * jnp.uint64(31)) + h
        out = mix64(out + _GOLDEN)
    return out


def hash_rows(columns) -> jnp.ndarray:
    """Hash a sequence of Blocks/Vals (anything with .data/.valid)."""
    hs = [hash_column(c.data, c.valid) for c in columns]
    return combine_hashes(hs) if len(hs) > 1 else hs[0]


# -- dictionary-VALUE hashing (table-independent varchar keys) ---------------
#
# Dictionary codes are per-table: the same string can carry different codes
# on the two sides of a join, so hashing codes (hash_column above) is only
# safe within one table. For join partitioning / hash-table tags the two
# sides must agree for equal VALUES, so varchar columns rehash through a
# per-dictionary value-hash lookup table: vh[code] = crc-seeded splitmix64
# of the string bytes, computed ONCE per interned dictionary and cached.
# 32-bit crc collisions only create false candidates — true key equality
# (dictionary-unified code compare) always decides matches.
#
# Eager/host contexts only: the lookup table is a host array; embedding it
# in a traced kernel would bake a per-dictionary constant into the
# executable (one recompile per dictionary). Callers (ops/pallas_join.py,
# exec/spill.hash_partition_indices) run eagerly by design.

_VALUE_HASH_BY_DICT: dict = {}

# dictionaries beyond this size skip value hashing (the one-time host pass
# over every entry would dominate the join); callers fall back to their
# code-hash-unsafe routing for such keys. PRESTO_TPU_VALUE_HASH_MAX_DICT
# overrides (docs/tuning.md).
_VALUE_HASH_MAX_DICT_DEFAULT = 1 << 22


def value_hash_max_dict() -> int:
    import os

    try:
        v = int(os.environ.get("PRESTO_TPU_VALUE_HASH_MAX_DICT", "0"))
    except ValueError:
        v = 0
    return v if v > 0 else _VALUE_HASH_MAX_DICT_DEFAULT


# prestolint: host-function -- one-time host pass over an interned
# dictionary; jnp only finishes the mix on the host-built array
def dict_value_hashes(dict_id: int) -> np.ndarray:
    """(len(dictionary),) uint64 value hashes for an interned dictionary,
    cached per dict_id (dictionaries are immutable once interned)."""
    vh = _VALUE_HASH_BY_DICT.get(dict_id)
    if vh is None:
        import zlib

        from ..page import dictionary_by_id

        entries = dictionary_by_id(dict_id)
        raw = np.empty(max(len(entries), 1), np.uint64)
        for i, s in enumerate(entries):
            b = s.encode("utf-8", "surrogatepass")
            raw[i] = np.uint64(zlib.crc32(b)) | (
                np.uint64(len(b) & 0xFFFFFFFF) << np.uint64(32)
            )
        vh = np.asarray(mix64(jnp.asarray(raw)))
        if not len(entries):
            vh = vh[:0]
        _VALUE_HASH_BY_DICT[dict_id] = vh
    return vh


def value_hashable(columns) -> bool:
    """True when every varchar column's dictionary is small enough for the
    one-time value-hash pass (non-varchar columns are always fine)."""
    cap = value_hash_max_dict()
    for c in columns:
        if getattr(c, "dict_id", None) is not None:
            d = c.dictionary
            if d is None or len(d) > cap:
                return False
    return True


def _np_mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, numpy twin of mix64 (uint64 wraps mod 2^64;
    numpy wraps silently for unsigned dtypes)."""
    x = x.astype(np.uint64)
    x = (x ^ (x >> np.uint64(30))) * _C1
    x = (x ^ (x >> np.uint64(27))) * _C2
    return x ^ (x >> np.uint64(31))


def _np_hash_column(data: np.ndarray, valid) -> np.ndarray:
    """hash_column's numpy twin — bit-identical results (the host join
    path hashes probe batches every call; eager jnp dispatch overhead
    was ~40% of the whole probe)."""
    if data.ndim == 2:
        hs = [_np_hash_column(data[:, i], None) for i in range(data.shape[1])]
        h = np_combine_hashes(hs)
        if valid is not None:
            h = np.where(valid, h, _NULL_HASH)
        return h
    if np.issubdtype(data.dtype, np.floating):
        data = np.where(data == 0, np.zeros_like(data), data)
        data = np.where(np.isnan(data), np.full_like(data, np.nan), data)
        idtype = {4: np.uint32, 8: np.uint64}[data.dtype.itemsize]
        bits = data.view(idtype).astype(np.uint64)
    else:
        bits = data.astype(np.uint64)
    h = _np_mix64(bits)
    if valid is not None:
        h = np.where(valid, h, _NULL_HASH)
    return h


def np_combine_hashes(hashes) -> np.ndarray:
    out = np.zeros_like(hashes[0])
    for h in hashes:
        out = (out * np.uint64(31)) + h
        out = _np_mix64(out + _GOLDEN)
    return out


# prestolint: host-function -- host twin of hash_rows_values for the
# eager join/group-by kernels (np.asarray on CPU jax arrays is zero-copy)
def np_hash_rows_values(columns) -> np.ndarray:
    """hash_rows_values computed entirely in numpy — bit-identical to
    the jnp version (both are splitmix64 over the same canonicalized
    bits), for the host kernel paths where per-op jax dispatch dominates."""
    hs = []
    for c in columns:
        valid = None if c.valid is None else np.asarray(c.valid)
        if getattr(c, "dict_id", None) is not None:
            vh = dict_value_hashes(c.dict_id)
            codes = np.asarray(c.data).astype(np.int64)
            np.clip(codes, 0, max(len(vh) - 1, 0), out=codes)
            h = (
                vh[codes]
                if len(vh)
                else np.full(codes.shape, _NULL_HASH)
            )
            if valid is not None:
                h = np.where(valid, h, _NULL_HASH)
        else:
            h = _np_hash_column(np.asarray(c.data), valid)
        hs.append(h)
    return np_combine_hashes(hs) if len(hs) > 1 else hs[0]


# prestolint: host-function -- eager-only by contract (module note):
# gathers host value-hash tables by concrete dictionary codes
def hash_rows_values(columns) -> jnp.ndarray:
    """hash_rows with table-independent varchar hashing: dictionary
    columns hash their VALUES via dict_value_hashes, so build and probe
    sides of a join partition/tag identically for equal strings. Eager
    contexts only (see module note); callers gate on value_hashable()."""
    hs = []
    for c in columns:
        if getattr(c, "dict_id", None) is not None:
            vh = dict_value_hashes(c.dict_id)
            codes = np.asarray(c.data).astype(np.int64)
            np.clip(codes, 0, max(len(vh) - 1, 0), out=codes)
            h = jnp.asarray(
                vh[codes] if len(vh) else np.full(codes.shape, _NULL_HASH)
            )
            if c.valid is not None:
                h = jnp.where(c.valid, h, _NULL_HASH)
            hs.append(h)
        else:
            hs.append(hash_column(c.data, c.valid))
    return combine_hashes(hs) if len(hs) > 1 else hs[0]
