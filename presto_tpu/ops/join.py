"""Join kernels.

Re-designed equivalent of the reference's join stack: HashBuilderOperator →
PagesIndex → JoinCompiler-generated PagesHash + PositionLinks, probed by
LookupJoinOperator/JoinProbe (presto-main/.../operator/JoinHash.java:28,
getJoinPosition :82-89; LookupJoinOperator.java).

TPU-first redesign: the "hash table" is the build side *sorted by key hash* —
a layout XLA produces with one optimized sort and probes with vectorized
binary search (jnp.searchsorted), instead of pointer-chasing collision chains.
Duplicate build keys are contiguous runs, the analog of PositionLinks chains:

  build:  sort by (hash, ...), keep permutation
  probe:  lo = searchsorted(left), hi = searchsorted(right)  -> match ranges
  1:N expansion: static-capacity output; row r of the output maps back to
  probe row via searchsorted over cumulative match counts (cumsum trick), the
  static-shape answer to dynamic join fan-out.

Hash collisions are resolved by verifying actual key equality after gather.
Composite keys hash-combine then verify each part.

Supported: inner, left (probe-outer), semi, anti — the shapes TPC-H needs.
Right/full outer come with the planner's join-side swap in a later round.

PR 11: the sorted-hash layout above is now the FALLBACK. `build()` first
tries the linear-probe hash-table layout in ops/pallas_join.py (Pallas
kernels on TPU, the numpy twin on the CPU engine default) behind the
pallas_join_build / pallas_join_probe circuit breakers; join_n1 /
join_expand / semi_match_mask dispatch on which layout `build()`
produced, and a probe-side kernel fault degrades back to this file's
composition (rebuilding the sorted layout from the table's retained
build page). Traced callers (jitted executors, the shard_map mesh path)
always get the sorted layout — the table path is eager by design.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..expr.compiler import evaluate
from ..expr.functions import Val, and_valid
from ..page import Block, Page
from .hashing import hash_rows, hash_rows_values, value_hashable


def _want_value_hash(keys, count) -> bool:
    """Eager build with varchar keys whose dictionaries admit the
    one-time value pass -> hash by VALUE so cross-dictionary equi-joins
    meet (see BuildSide.value_hashed)."""
    if not any(getattr(k, "dict_id", None) is not None for k in keys):
        return False
    concrete = not any(
        isinstance(a, jax.core.Tracer)
        for a in [count] + [k.data for k in keys]
    )
    return concrete and value_hashable(keys)

# numpy scalar (not a device array) so importing this module does no device work
MAX_HASH = np.uint64(0xFFFFFFFFFFFFFFFF)


@dataclasses.dataclass
class BuildSide:
    """Sorted build-side 'lookup source' (reference LookupSourceFactory
    output). All arrays have the build page's capacity."""

    sorted_hash: jnp.ndarray  # uint64, live rows first by hash, dead at end
    order: jnp.ndarray  # permutation: sorted position -> original row
    page: Page  # original build page (payload gathers go through `order`)
    key_vals: Tuple[Val, ...]  # UNsorted key values (original order)
    count: jnp.ndarray  # live build rows
    # O(1) probe directory: sorted positions of bucket b (the top
    # `bucket_bits` of the hash) span [bucket_start[b], bucket_start[b+1])
    bucket_start: Optional[jnp.ndarray] = None  # int32, (2^bits + 1,)
    bucket_bits: int = 0  # static per build shape
    # True when varchar keys were hashed by dictionary VALUE
    # (hash_rows_values): probes MUST hash the same way or equal strings
    # with different codes never meet (the pre-PR-11 cross-dictionary
    # varchar equi-join wrong-result, now fixed for eager builds). Traced
    # builds keep code hashing — both sides of a traced join share one
    # trace, so they stay consistent (and reach only same-dictionary
    # data in practice: the mesh shards one table's pages).
    value_hashed: bool = False


# The PRESTO_TPU_JOIN_PROBE_HOST pure_callback searchsorted route that
# lived here (PR 3's `_default_host_probe`, measured 4x slower than the
# bucket-directory probe and default-off ever since) is DELETED, not just
# still off: PR 11 re-measured it against the hash-table kernels and the
# numpy linear-probe scan in ops/pallas_join.py beats it ~7x at the
# join_probe_n1 shape (22ms vs ~150ms for 600k probes) while also beating
# the directory probe — so the CPU host route is now the ENGINE DEFAULT
# via build_table(), and the searchsorted callback (plus its
# join_probe_cpu breaker) has no remaining niche.


def _pick_bucket_bits(capacity: int) -> int:
    """Directory of ~2x build capacity: expected bucket occupancy <= 0.5,
    so the unrolled 4-slot collision scan covers nearly every probe."""
    bits = max(1, int(np.ceil(np.log2(max(capacity, 1) * 2))))
    return min(bits, 22)  # cap the directory at 4M entries


def build(page: Page, key_exprs):
    """Prepare a build side for probing. First choice: the linear-probe
    hash-table layout (ops/pallas_join.py — Pallas kernels on TPU, the
    numpy twin as the CPU engine default), behind the pallas_join_build /
    pallas_join_probe breakers. Fallback — and the only path for traced
    operands or cross joins — is the sorted-hash layout of build_sorted."""
    if key_exprs:
        from ..exec.breaker import BREAKERS

        if BREAKERS.allow("pallas_join_build") and BREAKERS.allow(
            "pallas_join_probe"
        ):
            from .pallas_join import build_table

            try:
                jt = build_table(page, key_exprs)
            except Exception as exc:  # noqa: BLE001 — degrade, don't fail
                BREAKERS.record_failure("pallas_join_build", repr(exc))
            else:
                if jt is not None:
                    BREAKERS.record_success("pallas_join_build")
                    return jt
    return build_sorted(page, key_exprs)


def build_sorted(page: Page, key_exprs) -> BuildSide:
    """Sort the build side by key hash (HashBuilderOperator.finish analog).
    Empty key_exprs = all rows in one bucket (cross join support).

    TPU-first probe layout: alongside the sorted hashes we histogram the
    top `bucket_bits` hash bits into a bucket-start directory. Probing is
    then TWO gathers (bucket_start[b], bucket_start[b+1]) instead of
    jnp.searchsorted's ~log2(n) serial gather rounds — binary search is
    the worst memory-access shape for the TPU; a directory lookup is a
    plain vectorized gather. Candidates inside a bucket that carry a
    different hash are rejected by the existing true-key-equality check."""
    keys = [evaluate(e, page) for e in key_exprs]
    live = page.live_mask()
    value_hashed = _want_value_hash(keys, page.count)
    if not keys:
        h = jnp.zeros(page.capacity, jnp.uint64)
    elif value_hashed:
        h = hash_rows_values(keys)
    else:
        h = hash_rows(keys)
    h = jnp.where(live, h, MAX_HASH)  # dead rows cluster at the end
    order = jnp.argsort(h)
    sh = h[order]
    use_directory = (
        os.environ.get("PRESTO_TPU_JOIN_PROBE", "directory") == "directory"
    )
    if use_directory:
        # kernel-fault circuit breaker (exec/breaker.py): a faulting
        # directory build degrades every join in the process to the
        # searchsorted probe until the recovery window elapses
        from ..exec.breaker import BREAKERS

        use_directory = BREAKERS.allow("join_probe")
    if not use_directory:
        # chip-diagnosis escape hatch / open breaker: searchsorted probe
        return BuildSide(
            sh, order, page, tuple(keys), page.count,
            value_hashed=value_hashed,
        )
    bits = _pick_bucket_bits(page.capacity)
    nb = 1 << bits
    bucket = (sh >> np.uint64(64 - bits)).astype(jnp.int32)
    # directory from the SORTED bucket ids via vectorized binary search —
    # pure gather rounds. (A bincount/scatter-add builds the same counts
    # but XLA:TPU lowers large scatters to a serial loop; at a 1.5M-row
    # build side that serialization dominates the whole join.)
    starts = jnp.searchsorted(
        bucket, jnp.arange(nb + 1, dtype=jnp.int32), side="left"
    ).astype(jnp.int32)
    return BuildSide(
        sh, order, page, tuple(keys), page.count, starts, bits,
        value_hashed=value_hashed,
    )


def _probe_ranges(bs: BuildSide, probe_keys: Sequence[Val], capacity: int):
    """For each probe row: [lo, hi) candidate range in the sorted build.

    Via the bucket directory when present (O(1), two gathers); candidate
    ranges then cover the whole hash-prefix bucket — a superset of the
    exact hash run — which downstream consumers must treat as CANDIDATES
    (true key equality + liveness decide membership)."""
    if not probe_keys:  # cross join: every live build row is a candidate
        lo = jnp.zeros(capacity, jnp.int32)
        hi = jnp.broadcast_to(bs.count.astype(jnp.int32), (capacity,))
        return None, lo, hi
    h = (
        hash_rows_values(probe_keys)
        if bs.value_hashed
        else hash_rows(probe_keys)
    )
    if bs.bucket_start is not None:
        b = (h >> np.uint64(64 - bs.bucket_bits)).astype(jnp.int32)
        cnt = bs.count.astype(jnp.int32)
        # live rows occupy sorted positions [0, count): clamping excludes
        # the dead-padding tail from the last bucket (dead rows sort to
        # MAX_HASH), keeping candidates live and the tail bucket short
        lo = jnp.minimum(bs.bucket_start[b], cnt)
        hi = jnp.minimum(bs.bucket_start[b + 1], cnt)
        return h, lo, hi
    lo = jnp.searchsorted(bs.sorted_hash, h, side="left")
    hi = jnp.searchsorted(bs.sorted_hash, h, side="right")
    return h, lo.astype(jnp.int32), hi.astype(jnp.int32)


def _keys_equal(bs: BuildSide, probe_keys: Sequence[Val], build_rows):
    """Verify actual key equality probe[i] == build[build_rows[i]].
    SQL join semantics: NULL keys never match."""
    if not probe_keys:
        return jnp.ones(build_rows.shape, jnp.bool_)
    eq = None
    for pv, bv in zip(probe_keys, bs.key_vals):
        bd = bv.data[build_rows]
        if isinstance(pv.type, T.VarcharType) and pv.dict_id != bv.dict_id:
            from ..expr.functions import unify_dictionaries

            pd_, bd2, _ = unify_dictionaries(
                pv, Val(bd, None, bv.type, bv.dict_id)
            )
            part = pd_ == bd2
        else:
            part = pv.data == bd
            if part.ndim == 2:  # long-decimal lanes: all lanes must match
                part = part.all(axis=-1)
        if pv.valid is not None:
            part = part & pv.valid
        if bv.valid is not None:
            part = part & bv.valid[build_rows]
        eq = part if eq is None else (eq & part)
    return eq


def _collision_scan(bs: BuildSide, probe_keys, lo, hi, max_scan: int = 4):
    """Resolve hash collisions: the first max_scan candidate slots are
    UNROLLED (64-bit hashes make >1 essentially impossible, so this is
    the entire cost in practice), then a lax.while_loop keeps scanning
    for pathological longer runs — a >max_scan-deep run of colliding,
    key-unequal candidates can no longer silently drop matches (round-4
    verdict weak#8). Returns (matched, build_row)."""
    matched = jnp.zeros(lo.shape, jnp.bool_)
    build_row = jnp.zeros(lo.shape, jnp.int32)
    limit = bs.sorted_hash.shape[0] - 1

    def probe_slot(k, matched, build_row):
        cand = lo + k
        in_range = cand < hi
        rows = bs.order[jnp.minimum(cand, limit)].astype(jnp.int32)
        ok = in_range & _keys_equal(bs, probe_keys, rows) & ~matched
        return matched | ok, jnp.where(ok, rows, build_row)

    for k in range(max_scan):
        matched, build_row = probe_slot(k, matched, build_row)

    def cond(state):
        k, m, _ = state
        return jnp.any(~m & (lo + k < hi))

    def body(state):
        k, m, br = state
        m, br = probe_slot(k, m, br)
        return k + 1, m, br

    _, matched, build_row = jax.lax.while_loop(
        cond, body, (jnp.int32(max_scan), matched, build_row)
    )
    return matched, build_row


def _table_dispatch(bs, run_table, run_legacy):
    """Route through the hash-table kernels when build() produced a
    JoinTable; a probe-side kernel fault records on the pallas_join_probe
    breaker and degrades to the sorted-hash composition by rebuilding
    from the table's retained build page (rare: the breaker then opens
    and subsequent build() calls skip the table outright)."""
    from .pallas_join import JoinTable

    if isinstance(bs, JoinTable):
        from ..exec.breaker import BREAKERS

        try:
            out = run_table(bs)
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            BREAKERS.record_failure("pallas_join_probe", repr(exc))
            bs = build_sorted(bs.page, bs.key_exprs)
            try:
                return run_legacy(bs)
            except Exception:
                # the sorted fallback failed the same way: a semantic /
                # data-shape error, not a kernel fault — neutralize the
                # breaker hit so one bad join cannot degrade the kernel
                # path for the whole process (same contract as
                # Executor._kernel_guarded)
                BREAKERS.record_success("pallas_join_probe")
                raise
        else:
            BREAKERS.record_success("pallas_join_probe")
            return out
    return run_legacy(bs)


def join_n1(
    probe: Page,
    bs,
    probe_key_exprs,
    build_names: Sequence[str],
    out_build_names: Sequence[str],
    kind: str = "inner",
) -> Page:
    """Join where each probe row matches at most ONE build row (FK->PK joins;
    also semi/anti). kind: inner | left | semi | anti.

    Output capacity == probe capacity; probe columns pass through, build
    payload columns are gathered (null where unmatched, for `left`)."""
    from .pallas_join import table_join_n1

    return _table_dispatch(
        bs,
        lambda jt: table_join_n1(
            probe, jt, probe_key_exprs, build_names, out_build_names, kind
        ),
        lambda b: _join_n1_sorted(
            probe, b, probe_key_exprs, build_names, out_build_names, kind
        ),
    )


def _join_n1_sorted(
    probe: Page,
    bs: BuildSide,
    probe_key_exprs,
    build_names: Sequence[str],
    out_build_names: Sequence[str],
    kind: str = "inner",
) -> Page:
    probe_keys = [evaluate(e, probe) for e in probe_key_exprs]
    live = probe.live_mask()
    _, lo, hi = _probe_ranges(bs, probe_keys, probe.capacity)
    matched, build_row = _collision_scan(bs, probe_keys, lo, hi)
    matched = matched & live

    from .filter import compact

    if kind == "semi":
        return compact(probe, matched)
    if kind == "anti":
        return compact(probe, ~matched & live)

    blocks = list(probe.blocks)
    names = list(probe.names)
    for bname, oname in zip(build_names, out_build_names):
        b = bs.page.block(bname)
        data = b.data[build_row]
        valid = matched if b.valid is None else (matched & b.valid[build_row])
        blocks.append(Block(data, b.type, valid, b.dict_id))
        names.append(oname)
    out = Page(tuple(blocks), tuple(names), probe.count)
    if kind == "inner":
        return compact(out, matched)
    if kind == "left":
        return out  # unmatched rows keep probe columns, build columns NULL
    raise ValueError(f"unknown join kind {kind!r}")


def semi_match_mask(probe: Page, bs, probe_key_exprs) -> jnp.ndarray:
    """Boolean per-probe-row match membership (the mark-join kernel:
    reference HashSemiJoinOperator's semiJoinOutput channel)."""
    from .pallas_join import table_semi_mask

    return _table_dispatch(
        bs,
        lambda jt: table_semi_mask(probe, jt, probe_key_exprs),
        lambda b: _semi_match_mask_sorted(probe, b, probe_key_exprs),
    )


def _semi_match_mask_sorted(
    probe: Page, bs: BuildSide, probe_key_exprs
) -> jnp.ndarray:
    probe_keys = [evaluate(e, probe) for e in probe_key_exprs]
    live = probe.live_mask()
    _, lo, hi = _probe_ranges(bs, probe_keys, probe.capacity)
    matched, _ = _collision_scan(bs, probe_keys, lo, hi)
    return matched & live


def join_expand(
    probe: Page,
    bs,
    probe_key_exprs,
    probe_out: Sequence[str],
    build_out: Sequence[Tuple[str, str]],  # (build col, output name)
    out_capacity: int,
    kind: str = "inner",
) -> Tuple[Page, jnp.ndarray]:
    """General 1:N join dispatcher — see _join_expand_sorted for the
    contract; the table path emits VERIFIED pairs so its overflow is
    exact rather than a candidate bound."""
    from .pallas_join import table_join_expand

    return _table_dispatch(
        bs,
        lambda jt: table_join_expand(
            probe, jt, probe_key_exprs, probe_out, build_out,
            out_capacity, kind,
        ),
        lambda b: _join_expand_sorted(
            probe, b, probe_key_exprs, probe_out, build_out,
            out_capacity, kind,
        ),
    )


def _join_expand_sorted(
    probe: Page,
    bs: BuildSide,
    probe_key_exprs,
    probe_out: Sequence[str],
    build_out: Sequence[Tuple[str, str]],  # (build col, output name)
    out_capacity: int,
    kind: str = "inner",
) -> Tuple[Page, jnp.ndarray]:
    """General 1:N inner/left join with static output capacity.

    out_capacity bounds total hash-range *candidates* (planner-estimated, like
    the reference sizes lookup join output pages). Returns (page, overflow):
    overflow is the number of candidate rows beyond out_capacity — the host
    must check it is 0 and retry with a larger capacity otherwise (candidates
    that merely fail true key equality are dropped exactly, not counted)."""
    probe_keys = [evaluate(e, probe) for e in probe_key_exprs]
    live = probe.live_mask()
    _, lo, hi = _probe_ranges(bs, probe_keys, probe.capacity)

    # counts per probe row: number of hash-range candidates. Candidates that
    # fail true key equality are dropped at emission (conservative capacity,
    # exact rows). For LEFT joins a probe row with candidates but no TRUE
    # match (NULL keys, hash collisions) must still emit one null row, so
    # we detect real matches with the n1 scan first.
    counts = jnp.where(live, hi - lo, 0)
    if kind == "left":
        has_match, _ = _collision_scan(bs, probe_keys, lo, hi)
        no_match = live & ~has_match
        counts = jnp.where(no_match, 1, counts)  # emit exactly one null row
    offsets = jnp.cumsum(counts)
    total = offsets[-1] if probe.capacity else jnp.asarray(0, jnp.int32)
    starts = offsets - counts

    out_i = jnp.arange(out_capacity, dtype=jnp.int32)
    src = jnp.searchsorted(offsets, out_i, side="right").astype(jnp.int32)
    src = jnp.minimum(src, probe.capacity - 1)
    within = out_i - starts[src]
    in_bounds = out_i < total

    sorted_pos = lo[src] + within
    sorted_pos = jnp.minimum(sorted_pos, bs.sorted_hash.shape[0] - 1)
    build_row = bs.order[sorted_pos].astype(jnp.int32)

    # verify true key equality for emitted pairs
    probe_keys_g = [
        Val(
            v.data[src],
            None if v.valid is None else v.valid[src],
            v.type,
            v.dict_id,
        )
        for v in probe_keys
    ]
    eq = _keys_equal(bs, probe_keys_g, build_row)
    if kind == "left":
        synthetic = no_match[src]  # left-outer null row for match-less probes
        keep = in_bounds & (eq | synthetic)
        build_valid_base = ~synthetic
    else:
        keep = in_bounds & eq
        build_valid_base = jnp.ones(out_capacity, jnp.bool_)

    blocks, names = [], []
    for name in probe_out:
        b = probe.block(name)
        data = b.data[src]
        valid = None if b.valid is None else b.valid[src]
        blocks.append(Block(data, b.type, valid, b.dict_id))
        names.append(name)
    for bname, oname in build_out:
        b = bs.page.block(bname)
        data = b.data[build_row]
        valid = build_valid_base if b.valid is None else (
            build_valid_base & b.valid[build_row]
        )
        blocks.append(Block(data, b.type, valid, b.dict_id))
        names.append(oname)

    out = Page.from_blocks(blocks, names, count=out_capacity)
    from .filter import compact

    overflow = jnp.maximum(total.astype(jnp.int64) - out_capacity, 0)
    return compact(out, keep), overflow
