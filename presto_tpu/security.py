"""Access control: pluggable authorization checks on queries.

Re-designed equivalent of the reference's security stack
(presto-main/.../security/AccessControlManager.java, the
SystemAccessControl SPI in presto-spi, and the file-based rules of
presto-plugin-toolkit's access control helpers). Checks run in the
session layer before planning/execution, so every surface (in-process,
REST, DB-API) is covered by the same gate.

Rule-based implementation mirrors the reference's file-based access
control JSON: first-match-wins rules keyed by user regex, each granting
a privilege level per table regex.

    rules = [
        {"user": "admin", "privileges": "all"},
        {"user": ".*", "table": "secret.*", "privileges": "none"},
        {"user": ".*", "privileges": "select"},
    ]
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Sequence

SELECT = "select"
WRITE = "write"  # insert/delete/create/drop
ALL = "all"
NONE = "none"


class AccessDeniedError(RuntimeError):
    """Reference: AccessDeniedException (spi/security)."""


class AccessControl:
    """SPI: override the checks you enforce. Default allows everything
    (the reference's AllowAllAccessControl)."""

    def check_can_execute_query(self, user: str) -> None:  # noqa: B027
        pass

    def check_can_select_from_table(  # noqa: B027
        self, user: str, table: str
    ) -> None:
        pass

    def check_can_write_table(self, user: str, table: str) -> None:  # noqa: B027
        pass


@dataclasses.dataclass
class AccessRule:
    privileges: str  # all | select | none
    user: str = ".*"
    table: str = ".*"

    def matches(self, user: str, table: Optional[str]) -> bool:
        if not re.fullmatch(self.user, user or ""):
            return False
        if table is not None and not re.fullmatch(self.table, table):
            return False
        return True


class RuleBasedAccessControl(AccessControl):
    """First-match-wins rules (reference FileBasedSystemAccessControl)."""

    def __init__(self, rules: Sequence[dict]):
        self.rules = [AccessRule(**r) for r in rules]

    def _privilege(self, user: str, table: Optional[str]) -> str:
        for r in self.rules:
            if r.matches(user, table):
                return r.privileges
        return NONE

    def check_can_execute_query(self, user: str) -> None:
        # denied only when no rule grants the user anything at all
        if all(not r.matches(user, None) or r.privileges == NONE
               for r in self.rules):
            raise AccessDeniedError(f"user {user!r} cannot execute queries")

    def check_can_select_from_table(self, user: str, table: str) -> None:
        if self._privilege(user, table) not in (SELECT, WRITE, ALL):
            raise AccessDeniedError(
                f"user {user!r} cannot select from {table!r}"
            )

    def check_can_write_table(self, user: str, table: str) -> None:
        if self._privilege(user, table) not in (WRITE, ALL):
            raise AccessDeniedError(f"user {user!r} cannot write {table!r}")

    # -- GRANT / REVOKE (reference execution/GrantTask.java /
    # RevokeTask.java; grants become first-match rules, prepended so they
    # override broader defaults) --

    def check_can_grant(self, user: str, table: str) -> None:
        if self._privilege(user, table) != ALL:
            raise AccessDeniedError(
                f"user {user!r} cannot change grants on {table!r}"
            )

    def grant(self, user: str, table: str, privilege: str) -> None:
        priv = {"insert": WRITE, "update": WRITE, "delete": WRITE}.get(
            privilege, privilege
        )
        if priv not in (SELECT, WRITE, ALL):
            raise ValueError(f"unknown privilege {privilege!r}")
        self.rules.insert(
            0, AccessRule(priv, user=re.escape(user), table=re.escape(table))
        )

    def revoke(self, user: str, table: str, privilege: str) -> None:
        """Drop the user to the highest privilege BELOW the revoked one on
        the ladder none<select<write<all (write implies read here, as in
        check_can_select_from_table), expressed as an explicit first-match
        rule so a broader default cannot silently re-grant."""
        priv = {"insert": WRITE, "update": WRITE, "delete": WRITE}.get(
            privilege, privilege
        )
        eu, et = re.escape(user), re.escape(table)
        self.rules = [
            r for r in self.rules
            if not (r.user == eu and r.table == et)
        ]
        ladder = [NONE, SELECT, WRITE, ALL]
        cur = self._privilege(user, table)
        # revoking ALL or SELECT leaves nothing (write implies read here,
        # so removing read removes everything); revoking WRITE leaves read
        floor = NONE if priv in (ALL, SELECT) else SELECT
        new = ladder[min(ladder.index(cur), ladder.index(floor))]
        self.rules.insert(0, AccessRule(new, user=eu, table=et))


def collect_tables(ast) -> List[str]:
    """Storage-table names referenced anywhere in a statement AST. CTE
    aliases look like tables in FROM clauses but are derived relations and
    are excluded — with the SAME scoping the planner applies
    (sql/planner.py plan_query/plan_table): a CTE name is in scope only
    within the Query that defines it, and a CTE's own definition body does
    NOT see its own name (so `WITH t AS (SELECT * FROM t)` reads the
    physical t and is checked against it)."""
    from .sql import tree as t

    out: List[str] = []
    seen: set = set()
    # a CTE referenced N times re-expands N times (matching the planner),
    # which is exponential for chains that reference the previous CTE twice
    # — memoize on (definition, names-in-scope) and hard-cap expansions so a
    # few-KB statement cannot hang the gate before authorization runs
    expanded: set = set()
    expansions = [0]

    def walk(node, scope: dict):
        # scope: cte name -> WithItem, exactly the planner's `ctes` dict.
        # CTE bodies are expanded LAZILY at the reference site with the
        # referenced name stripped — the planner strips names transitively
        # along an expansion chain, so a mutually-referencing pair
        # (a -> b -> a) bottoms out at the physical table; eager per-item
        # walks would miss that.
        if isinstance(node, t.Table):
            name = node.name.lower()
            if name in scope:
                item = scope[name]
                inner = {k: v for k, v in scope.items() if k != name}
                memo_key = (id(item), frozenset(inner))
                if memo_key in expanded:
                    return
                expanded.add(memo_key)
                expansions[0] += 1
                if expansions[0] > 10_000:
                    raise ValueError(
                        "statement exceeds the CTE expansion limit"
                    )
                walk(item.query, inner)
            elif name not in seen:
                seen.add(name)
                out.append(name)
            return
        if isinstance(node, t.Query) and node.with_items:
            inner = dict(scope)
            for item in node.with_items:
                inner[item.name.lower()] = item
            walk(node.body, inner)
            for child in node.order_by:
                walk(child, inner)
            return
        if not dataclasses.is_dataclass(node):
            return
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, t.Node):
                walk(v, scope)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, t.Node):
                        walk(x, scope)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, t.Node):
                                walk(y, scope)

    walk(ast, {})
    return out


def _names_to_check(name: str) -> List[str]:
    """A table reference is checked under BOTH its written form and its
    bare resolved name, so `default.secret_t` cannot sidestep a rule
    written against `secret_t` (the planner resolves qualified names to
    the bare table; connectors here have one implicit schema).

    Rules must therefore target the BARE resolved name (`secret_t`), the
    canonical form the planner uses: a rule written only against a
    qualified pattern (`default\\.secret_t`) does not protect the bare
    reference, which never produces the qualified form."""
    bare = name.split(".")[-1]
    return [name] if bare == name else [name, bare]


# view SQL text -> underlying table list; enforce() runs per query, so
# the (pure) parse+collect of each referenced view is computed once
_VIEW_TABLES_CACHE: dict = {}


def _view_tables(view_sql: str) -> List[str]:
    tables = _VIEW_TABLES_CACHE.get(view_sql)
    if tables is None:
        from .sql.parser import parse as _parse

        tables = [x.lower() for x in collect_tables(_parse(view_sql))]
        if len(_VIEW_TABLES_CACHE) > 4096:  # bound server memory
            _VIEW_TABLES_CACHE.clear()
        _VIEW_TABLES_CACHE[view_sql] = tables
    return tables


def enforce(access_control: AccessControl, user: str, ast,
            views=None) -> None:
    """Run the checks a statement requires (reference: StatementAnalyzer
    calling AccessControl per relation + DDL tasks checking writes).

    `views` ({name: view SQL}) enables INVOKER-style expansion: a table
    reference that names a view is checked against the view's UNDERLYING
    tables too, so a view cannot launder access to a protected table."""
    from .sql import tree as t

    access_control.check_can_execute_query(user)

    def check_select_closure(tables, seen=None):
        seen = seen if seen is not None else set()
        for table in tables:
            for n in _names_to_check(table):
                access_control.check_can_select_from_table(user, n)
            bare = table.split(".")[-1]
            if views and bare in views and bare not in seen:
                seen.add(bare)
                check_select_closure(_view_tables(views[bare]), seen)

    check_select_closure([x.lower() for x in collect_tables(ast)])
    if isinstance(ast, t.ShowColumns):
        # metadata reveals schema: same privilege as reading the table
        for n in _names_to_check(ast.table.lower()):
            access_control.check_can_select_from_table(user, n)
    if isinstance(ast, t.ShowCreateTable):
        # same metadata surface as SHOW COLUMNS
        for n in _names_to_check(ast.name.lower()):
            access_control.check_can_select_from_table(user, n)
    if isinstance(ast, t.ShowStats):
        # statistics leak DATA values (min/max/NDV): read privilege
        for n in _names_to_check(ast.name.lower()):
            access_control.check_can_select_from_table(user, n)
    if isinstance(ast, (t.CreateTable, t.DropTable)):
        for n in _names_to_check(ast.name.lower()):
            access_control.check_can_write_table(user, n)
    elif isinstance(ast, t.Insert):
        for n in _names_to_check(ast.table.lower()):
            access_control.check_can_write_table(user, n)
    elif isinstance(ast, t.Delete):
        for n in _names_to_check(ast.table.lower()):
            access_control.check_can_write_table(user, n)
    elif isinstance(ast, (t.RenameTable, t.RenameColumn, t.AddColumn,
                          t.DropColumn)):
        target = ast.name if isinstance(ast, t.RenameTable) else ast.table
        for n in _names_to_check(target.lower()):
            access_control.check_can_write_table(user, n)
        if isinstance(ast, t.RenameTable):
            for n in _names_to_check(ast.new_name.lower()):
                access_control.check_can_write_table(user, n)
    elif isinstance(ast, t.CreateView):
        # creating a view is a catalog write on the view name, plus read
        # rights over everything it selects from (INVOKER model)
        for n in _names_to_check(ast.name.lower()):
            access_control.check_can_write_table(user, n)
        check_select_closure(_view_tables(ast.query_sql))
    elif isinstance(ast, t.DropView):
        for n in _names_to_check(ast.name.lower()):
            access_control.check_can_write_table(user, n)
    elif isinstance(ast, (t.CreateSchema, t.DropSchema)):
        for n in _names_to_check(ast.name.lower()):
            access_control.check_can_write_table(user, n)
    elif isinstance(ast, (t.Grant, t.Revoke)):
        # only a user holding ALL on the table may change its grants
        # (reference AccessControl.checkCanGrantTablePrivilege)
        check = getattr(access_control, "check_can_grant", None)
        if check is not None:
            check(user, ast.table.lower())
    elif isinstance(ast, t.ExecutePrepared):
        # the bound statement is enforced again at EXECUTE time in
        # Session (the prepared SQL is an opaque string here)
        pass
