"""Shard-organized native storage engine — the raptor analog.

Re-designed equivalent of presto-raptor (20,941 LoC: RaptorMetadata +
storage/StorageManager + storage/organization/ShardCompactor /
ShardOrganizer + a MySQL shard-metadata DB): the proof that the
connector SPI carries a FULL storage engine, not just file readers.

Design here:
  * a table = a set of immutable parquet SHARD files under one directory
    (reference OrcStorageManager writes ORC shards; parquet is this
    engine's primary columnar format and shares its arrow bridge)
  * shard metadata lives in SQLite (`metadata.db`): table schemas, shard
    row counts, and per-column min/max statistics captured at WRITE time
    (reference ShardStats/ColumnStats persisted to the shards table) —
    scans prune whole shards against predicate hints without opening
    files, and the pruned/read counts surface in EXPLAIN ANALYZE via the
    `last_scan_files_*` counters (same contract as the hive connector)
  * INSERT appends a new shard — never rewrites existing data
  * `organize()` merges runs of small shards into compaction-target-sized
    ones (reference ShardCompactor.compact + ShardOrganizer background
    jobs; `start_organizer()` runs it on a daemon thread)
  * DROP deletes metadata transactionally, then garbage-collects files
"""

from __future__ import annotations

import datetime as pydt
import json
import os
import sqlite3
import threading
import uuid
import zlib
from typing import Dict, List, Optional

import numpy as np

from .. import types as T
from ..page import Page
from .parquet import arrow_table_to_page, build_sorted_dictionary, page_to_arrow
from .spi import DeltaUnavailable, Predicate, WritableConnector, WriteError

# compaction target: merge small shards until ~this many rows
DEFAULT_COMPACT_ROWS = 1 << 20


def _decode_stat(kind: str, txt: str):
    if kind == "str":
        return txt
    if kind == "date":
        return pydt.date.fromisoformat(txt)
    return float(txt)


def _combine_stats(dicts) -> dict:
    """Combine per-shard column stats dicts: min of mins, max of maxes
    per column, ignoring shards with no stats for a column."""
    out: Dict = {}
    for st in dicts:
        for col, (kind, mn, mx) in st.items():
            if kind is None or mn is None:
                out.setdefault(col, (None, None, None))
                continue
            cur = out.get(col)
            if cur is None or cur[0] is None:
                out[col] = (kind, mn, mx)
                continue
            cmn = min(_decode_stat(kind, cur[1]), _decode_stat(kind, mn))
            cmx = max(_decode_stat(kind, cur[2]), _decode_stat(kind, mx))
            enc = (
                (lambda v: v.isoformat()) if kind == "date"
                else (str if kind == "str" else (lambda v: repr(float(v))))
            )
            out[col] = (kind, enc(cmn), enc(cmx))
    return out


def _coerce_hint(value):
    """Predicate-hint python value -> the comparison domain of the stored
    stats (dates stay dates, strings stay strings, numbers -> float)."""
    if isinstance(value, (pydt.date, str)):
        return value
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


class ShardStoreCatalog(WritableConnector):
    """Local shard storage engine implementing the full Catalog + write
    SPI (usable anywhere the memory/hive catalogs are)."""

    name = "shardstore"

    def __init__(self, directory: str,
                 compact_rows: int = DEFAULT_COMPACT_ROWS):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.compact_rows = compact_rows
        self.db = sqlite3.connect(
            os.path.join(directory, "metadata.db"), check_same_thread=False
        )
        self._db_lock = threading.Lock()
        self.db.executescript(
            """
            CREATE TABLE IF NOT EXISTS tables (
                name TEXT PRIMARY KEY, schema_json TEXT NOT NULL);
            CREATE TABLE IF NOT EXISTS shards (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                table_name TEXT NOT NULL, path TEXT NOT NULL,
                rows INTEGER NOT NULL,
                seq REAL NOT NULL,
                max_seq REAL);
            CREATE TABLE IF NOT EXISTS shard_stats (
                shard_id INTEGER NOT NULL, column_name TEXT NOT NULL,
                kind TEXT, min_v TEXT, max_v TEXT,
                PRIMARY KEY (shard_id, column_name));
            CREATE TABLE IF NOT EXISTS table_meta (
                name TEXT PRIMARY KEY,
                created_id INTEGER NOT NULL,
                data_version INTEGER NOT NULL DEFAULT 0,
                nonappend_version INTEGER NOT NULL DEFAULT 0,
                unique_cols TEXT);
            CREATE TABLE IF NOT EXISTS table_ids (
                id INTEGER PRIMARY KEY AUTOINCREMENT, name TEXT);
            CREATE INDEX IF NOT EXISTS idx_shards_table
                ON shards(table_name);
            """
        )
        try:
            # databases created before the delta-scan work lack the
            # max_seq column (CREATE IF NOT EXISTS above is a no-op there)
            self.db.execute("ALTER TABLE shards ADD COLUMN max_seq REAL")
            self.db.commit()
        except sqlite3.OperationalError:
            pass  # column already present (fresh database)
        self.last_scan_files_read = 0
        self.last_scan_files_skipped = 0
        self._dict_cache: Dict = {}  # (table, column, version) -> dict
        self._organizer: Optional[threading.Thread] = None
        self._organizer_stop = threading.Event()
        self.organize_events: List[dict] = []

    # -- metadata ----------------------------------------------------------

    def table_names(self) -> List[str]:
        with self._db_lock:
            rows = self.db.execute("SELECT name FROM tables").fetchall()
        return sorted(r[0] for r in rows)

    def schema(self, table: str) -> Dict[str, T.Type]:
        with self._db_lock:
            row = self.db.execute(
                "SELECT schema_json FROM tables WHERE name = ?", (table,)
            ).fetchone()
        if row is None:
            raise KeyError(f"table {table!r} does not exist")
        return {
            c: T.parse_type(tn) for c, tn in json.loads(row[0]).items()
        }

    def row_count(self, table: str) -> int:
        with self._db_lock:
            row = self.db.execute(
                "SELECT COALESCE(SUM(rows), 0) FROM shards "
                "WHERE table_name = ?",
                (table,),
            ).fetchone()
        return int(row[0])

    def exact_row_count(self, table: str) -> int:
        return self.row_count(table)

    def unique_columns(self, table: str):
        with self._db_lock:
            row = self.db.execute(
                "SELECT unique_cols FROM table_meta WHERE name = ?",
                (table,),
            ).fetchone()
        if row is None or row[0] is None:
            return []
        return [tuple(json.loads(row[0]))]

    def shard_count(self, table: str) -> int:
        with self._db_lock:
            return int(
                self.db.execute(
                    "SELECT COUNT(*) FROM shards WHERE table_name = ?",
                    (table,),
                ).fetchone()[0]
            )

    def _shards(self, table: str):
        """Shards in GLOBAL ROW ORDER. Ordering is by `seq`, not id: a
        compacted shard inherits the seq of the first shard it merged, so
        row offsets stay stable across organize() — a streaming query
        paginating by offset sees the same rows before and after a
        concurrent compaction."""
        with self._db_lock:
            return self.db.execute(
                "SELECT id, path, rows FROM shards WHERE table_name = ? "
                "ORDER BY seq",
                (table,),
            ).fetchall()

    def _version(self, table: str) -> int:
        """Monotone shard-set version for cache invalidation."""
        with self._db_lock:
            row = self.db.execute(
                "SELECT COALESCE(MAX(id), 0), COUNT(*) FROM shards "
                "WHERE table_name = ?",
                (table,),
            ).fetchone()
        return int(row[0]) * 1_000_003 + int(row[1])

    def _ensure_meta_locked(self, table: str):
        """(created_id, data_version, nonappend_version, unique_cols) for
        `table`, creating the row for databases that predate table_meta.
        Caller holds `_db_lock` and owns the transaction/commit."""
        row = self.db.execute(
            "SELECT created_id, data_version, nonappend_version, "
            "unique_cols FROM table_meta WHERE name = ?",
            (table,),
        ).fetchone()
        if row is not None:
            return row
        cid = self.db.execute(
            "INSERT INTO table_ids (name) VALUES (?)", (table,)
        ).lastrowid
        # adopted mid-life (legacy database): seed at 1 so version 0
        # stays the "freshly created, never written" value
        self.db.execute(
            "INSERT INTO table_meta VALUES (?, ?, 1, 1, NULL)",
            (table, cid),
        )
        return (cid, 1, 1, None)

    def _bump_meta_locked(self, table: str, nonappend: bool) -> None:
        """Advance the per-table write counter; `nonappend` marks
        rewrites (replace/upsert) that invalidate old delta cursors."""
        self._ensure_meta_locked(table)
        self.db.execute(
            "UPDATE table_meta SET data_version = data_version + 1, "
            "nonappend_version = CASE WHEN ? THEN data_version + 1 "
            "ELSE nonappend_version END WHERE name = ?",
            (1 if nonappend else 0, table),
        )

    def table_version(self, table: str) -> int:
        """Connector snapshot version (exec/qcache.py): a per-table WRITE
        counter — bumped by append/replace/upsert, NOT by organize(),
        which rewrites shard files without changing data, so compaction
        never invalidates warm caches or forces spurious matview
        refreshes — mixed with a never-reused creation id (DROP +
        re-CREATE cannot resume an old version sequence) and the schema
        hash so a re-CREATE under a different schema can never alias the
        empty-table version."""
        with self._db_lock:
            row = self.db.execute(
                "SELECT schema_json FROM tables WHERE name = ?", (table,)
            ).fetchone()
            if row is None:
                raise KeyError(f"table {table!r} does not exist")
            cid, dv, _nv, _uc = self._ensure_meta_locked(table)
            self.db.commit()
        return ((cid * 1_000_003 + dv) << 32) ^ zlib.crc32(row[0].encode())

    def delta_token(self, table: str):
        """Append-cursor for scan_delta(): (high_seq, data_version,
        nonappend_version). Every row appended after this token lands in
        a shard with seq > high_seq; a later token with a DIFFERENT
        nonappend_version means the table was rewritten in between and
        deltas against this token are meaningless."""
        with self._db_lock:
            if self.db.execute(
                "SELECT 1 FROM tables WHERE name = ?", (table,)
            ).fetchone() is None:
                raise KeyError(f"table {table!r} does not exist")
            row = self.db.execute(
                "SELECT MAX(COALESCE(max_seq, seq)) FROM shards "
                "WHERE table_name = ?",
                (table,),
            ).fetchone()
            _cid, dv, nv, _uc = self._ensure_meta_locked(table)
            self.db.commit()
        return (float(row[0] or 0.0), dv, nv)

    def scan_delta(self, table: str, from_seq: float, to_seq: float,
                   columns=None, _retries: int = 2) -> Page:
        """Rows appended in the seq interval (from_seq, to_seq] — the
        delta between two delta_token() cursors. Raises DeltaUnavailable
        when a shard STRADDLES an endpoint; organize() only merges whole
        seq-adjacent runs and a merged shard keeps the run's [first seq,
        max seq] interval, so compaction of shards that are entirely
        inside (or entirely outside) the range is invisible here."""
        import pyarrow as pa

        schema = self.schema(table)
        names = list(columns) if columns is not None else list(schema)
        with self._db_lock:
            shards = self.db.execute(
                "SELECT id, path, seq, COALESCE(max_seq, seq) FROM shards "
                "WHERE table_name = ? ORDER BY seq",
                (table,),
            ).fetchall()
        kept = []
        for _sid, path, lo, hi in shards:
            if hi <= from_seq or lo > to_seq:
                continue  # fully consumed / fully beyond the range
            if lo <= from_seq or hi > to_seq:
                raise DeltaUnavailable(
                    f"shard seq [{lo}, {hi}] of {table!r} straddles the "
                    f"delta range ({from_seq}, {to_seq}]"
                )
            kept.append(path)
        try:
            pieces = [self._read_shard(p).select(names) for p in kept]
        except FileNotFoundError:
            # concurrent organize() GC'd a file between listing and read;
            # retry against fresh metadata (same contract as scan())
            if _retries <= 0:
                raise
            return self.scan_delta(
                table, from_seq, to_seq, columns=columns,
                _retries=_retries - 1,
            )
        if pieces:
            tb = pa.concat_tables(pieces)
        else:
            from .parquet import _type_to_arrow

            tb = pa.table(
                {n: pa.array([], type=_type_to_arrow(schema[n]))
                 for n in names}
            )
        return arrow_table_to_page(
            tb, names, tb.num_rows, None,
            lambda name: self._dictionary(table, name),
        )

    # -- writes ------------------------------------------------------------

    def create_table(self, table: str, schema: Dict[str, T.Type],
                     unique_columns=None) -> None:
        with self._db_lock:
            if self.db.execute(
                "SELECT 1 FROM tables WHERE name = ?", (table,)
            ).fetchone():
                raise WriteError(f"table {table!r} already exists")
            self.db.execute(
                "INSERT INTO tables VALUES (?, ?)",
                (table, json.dumps({c: str(t) for c, t in schema.items()})),
            )
            # table_ids is never garbage-collected: created_id must not
            # be reused by a DROP + re-CREATE (version aliasing)
            cid = self.db.execute(
                "INSERT INTO table_ids (name) VALUES (?)", (table,)
            ).lastrowid
            self.db.execute(
                "INSERT INTO table_meta VALUES (?, ?, 0, 0, ?)",
                (table, cid,
                 json.dumps([str(c) for c in unique_columns])
                 if unique_columns else None),
            )
            self.db.commit()

    def create_table_from_page(self, table: str, page: Page) -> None:
        self.create_table(
            table, {c: b.type for c, b in zip(page.names, page.blocks)}
        )
        if int(page.count):
            self.append(table, page)

    def _page_stats(self, page: Page):
        """Per-column (kind, min, max) captured at write time."""
        n = int(page.count)
        out = {}
        for name, b in zip(page.names, page.blocks):
            data = np.asarray(b.data[:n])
            valid = None if b.valid is None else np.asarray(b.valid[:n])
            if valid is not None:
                data = data[valid]
            if data.size == 0 or data.ndim != 1:
                out[name] = (None, None, None)
                continue
            if isinstance(b.type, T.VarcharType):
                d = b.dictionary or ()
                codes = data[(data >= 0) & (data < len(d))]
                if codes.size == 0 or not d:
                    out[name] = (None, None, None)
                    continue
                out[name] = ("str", d[int(codes.min())], d[int(codes.max())])
            elif isinstance(b.type, T.DateType):
                epoch = pydt.date(1970, 1, 1)
                out[name] = (
                    "date",
                    (epoch + pydt.timedelta(days=int(data.min()))).isoformat(),
                    (epoch + pydt.timedelta(days=int(data.max()))).isoformat(),
                )
            elif isinstance(b.type, T.DecimalType) and not b.type.is_long:
                sc = 10.0 ** b.type.scale
                out[name] = (
                    "num", repr(float(data.min()) / sc),
                    repr(float(data.max()) / sc),
                )
            elif np.issubdtype(data.dtype, np.number):
                out[name] = (
                    "num", repr(float(data.min())), repr(float(data.max()))
                )
            else:
                out[name] = (None, None, None)
        return out

    def _write_file(self, table: str, arrow_table) -> str:
        import pyarrow.parquet as pq

        path = os.path.join(
            self.directory, f"{table}.{uuid.uuid4().hex}.parquet"
        )
        pq.write_table(arrow_table, path)
        return path

    def _insert_shard_meta(self, table, path, rows, stats, seq=None,
                           max_seq=None, drop_ids=(),
                           drop_table_shards=False, bump=True,
                           nonappend=False) -> None:
        """ONE metadata transaction: optionally drop old shards, insert
        the new one, and (unless `bump` is False — compaction rewrites
        files without changing data) advance the table's write counter.
        seq defaults to the new id (append at the end); `max_seq` records
        the top of a merged shard's seq interval."""
        with self._db_lock:
            if drop_table_shards:
                self.db.execute(
                    "DELETE FROM shard_stats WHERE shard_id IN "
                    "(SELECT id FROM shards WHERE table_name = ?)",
                    (table,),
                )
                self.db.execute(
                    "DELETE FROM shards WHERE table_name = ?", (table,)
                )
            if drop_ids:
                qmarks = ",".join("?" * len(drop_ids))
                self.db.execute(
                    f"DELETE FROM shard_stats WHERE shard_id IN ({qmarks})",
                    tuple(drop_ids),
                )
                self.db.execute(
                    f"DELETE FROM shards WHERE id IN ({qmarks})",
                    tuple(drop_ids),
                )
            cur = self.db.execute(
                "INSERT INTO shards (table_name, path, rows, seq, max_seq)"
                " VALUES (?,?,?,0,?)",
                (table, path, rows,
                 float(max_seq) if max_seq is not None else None),
            )
            sid = cur.lastrowid
            self.db.execute(
                "UPDATE shards SET seq = ? WHERE id = ?",
                (float(seq) if seq is not None else float(sid), sid),
            )
            for col, (kind, mn, mx) in stats.items():
                self.db.execute(
                    "INSERT INTO shard_stats VALUES (?,?,?,?,?)",
                    (sid, col, kind, mn, mx),
                )
            if bump:
                self._bump_meta_locked(table, nonappend)
            self.db.commit()

    def _write_shard(self, table: str, arrow_table, stats) -> None:
        path = self._write_file(table, arrow_table)
        self._insert_shard_meta(table, path, arrow_table.num_rows, stats)

    def append(self, table: str, page: Page) -> None:
        self.schema(table)  # existence check
        if int(page.count) == 0:
            return
        self._write_shard(table, page_to_arrow(page), self._page_stats(page))

    def append_batch(self, table: str, pages) -> int:
        """High-rate ingest: concatenate many small pages into ONE shard
        with ONE metadata transaction and ONE version bump — the
        table's snapshot version moves at ingest-batch rate, not
        per-page. Returns the number of rows appended."""
        import pyarrow as pa

        self.schema(table)  # existence check
        pages = [p for p in pages if int(p.count)]
        if not pages:
            return 0
        if len(pages) == 1:
            self.append(table, pages[0])
            return int(pages[0].count)
        tb = pa.concat_tables([page_to_arrow(p) for p in pages])
        stats = _combine_stats([self._page_stats(p) for p in pages])
        self._write_shard(table, tb, stats)
        return tb.num_rows

    def upsert(self, table: str, page: Page) -> dict:
        """INSERT-or-REPLACE keyed on the table's declared unique
        columns. Fast path: when no incoming key exists yet this is a
        plain append — the table stays append-only and delta cursors
        survive. Slow path: a rewrite — rows matching an incoming key
        are dropped, the shard set swaps in one metadata transaction,
        and the nonappend version bump tells delta consumers their old
        cursors are void. Returns {"appended": n, "updated": m}."""
        import pyarrow as pa

        keys = self.unique_columns(table)
        if not keys:
            raise WriteError(
                f"upsert on {table!r} requires unique columns declared "
                f"at CREATE TABLE time"
            )
        if int(page.count) == 0:
            return {"appended": 0, "updated": 0}
        kcols = list(keys[0])
        missing = [c for c in kcols if c not in page.names]
        if missing:
            raise WriteError(
                f"upsert page for {table!r} lacks key column(s) {missing}"
            )
        tb_new = page_to_arrow(page)
        new_keys = set(
            zip(*[tb_new.column(c).to_pylist() for c in kcols])
        )
        with self._db_lock:
            shards = self.db.execute(
                "SELECT id, path, rows FROM shards WHERE table_name = ? "
                "ORDER BY seq",
                (table,),
            ).fetchall()
        old_tables, hit = [], False
        for _sid, path, _rows in shards:
            t = self._read_shard(path)
            old_tables.append(t)
            if not hit:
                hit = any(
                    k in new_keys
                    for k in zip(*[t.column(c).to_pylist() for c in kcols])
                )
        if not hit:
            self._write_shard(table, tb_new, self._page_stats(page))
            return {"appended": tb_new.num_rows, "updated": 0}
        merged = pa.concat_tables(old_tables)
        keep = [
            k not in new_keys
            for k in zip(*[merged.column(c).to_pylist() for c in kcols])
        ]
        kept_tb = merged.filter(pa.array(keep, type=pa.bool_()))
        if not kept_tb.schema.equals(tb_new.schema):
            tb_new = tb_new.cast(kept_tb.schema)
        final = pa.concat_tables([kept_tb, tb_new])
        path = self._write_file(table, final)
        # drop only the snapshotted shard ids (not drop_table_shards): a
        # shard appended concurrently with this rewrite must survive
        self._insert_shard_meta(
            table, path, final.num_rows, {},
            drop_ids=[sid for sid, _p, _r in shards],
            nonappend=True,
        )
        self._gc([p for _sid, p, _r in shards])
        updated = merged.num_rows - kept_tb.num_rows
        return {"appended": tb_new.num_rows - updated, "updated": updated}

    def replace(self, table: str, page: Page) -> None:
        """Write-new-then-swap in ONE metadata transaction — a crash (or
        concurrent reader) never observes the table without its data."""
        old = self._shards(table)
        arrow = page_to_arrow(page)
        if arrow.num_rows:
            path = self._write_file(table, arrow)
            self._insert_shard_meta(
                table, path, arrow.num_rows, self._page_stats(page),
                drop_table_shards=True, nonappend=True,
            )
        else:
            with self._db_lock:
                self.db.execute(
                    "DELETE FROM shard_stats WHERE shard_id IN "
                    "(SELECT id FROM shards WHERE table_name = ?)",
                    (table,),
                )
                self.db.execute(
                    "DELETE FROM shards WHERE table_name = ?", (table,)
                )
                self._bump_meta_locked(table, nonappend=True)
                self.db.commit()
        self._gc([p for _id, p, _r in old])

    def drop_table(self, table: str) -> None:
        old = self._shards(table)
        with self._db_lock:
            self.db.execute(
                "DELETE FROM shard_stats WHERE shard_id IN "
                "(SELECT id FROM shards WHERE table_name = ?)",
                (table,),
            )
            self.db.execute(
                "DELETE FROM shards WHERE table_name = ?", (table,)
            )
            self.db.execute("DELETE FROM tables WHERE name = ?", (table,))
            # table_ids row intentionally kept: created ids never recycle
            self.db.execute(
                "DELETE FROM table_meta WHERE name = ?", (table,)
            )
            self.db.commit()
        self._gc([p for _id, p, _r in old])

    @staticmethod
    def _gc(paths) -> None:
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass

    # -- reads -------------------------------------------------------------

    def _read_shard(self, path: str):
        import pyarrow.parquet as pq

        return pq.read_table(path)

    def _dictionary(self, table: str, column: str):
        key = (table, column, self._version(table))
        got = self._dict_cache.get(key)
        if got is None:
            import pyarrow as pa

            cols = [
                self._read_shard(p).column(column)
                for _id, p, _r in self._shards(table)
            ]
            if cols:
                merged = pa.chunked_array(
                    [c for col in cols for c in col.chunks]
                )
                got = build_sorted_dictionary(merged)
            else:
                got = ((), np.array([], dtype=object))
            if len(self._dict_cache) > 256:
                self._dict_cache.clear()
            self._dict_cache[key] = got
        return got

    def _refuted(self, sid: int, predicate: Predicate) -> bool:
        """True when the shard's stored min/max refute ANY conjunct
        (reference ShardPredicate.create against the shards table)."""
        with self._db_lock:
            rows = self.db.execute(
                "SELECT column_name, kind, min_v, max_v FROM shard_stats "
                "WHERE shard_id = ?",
                (sid,),
            ).fetchall()
        stats = {
            c: (_decode_stat(k, mn), _decode_stat(k, mx))
            for c, k, mn, mx in rows
            if k is not None and mn is not None
        }
        for col, op, value in predicate:
            st = stats.get(col)
            if st is None:
                continue
            if op == "in":
                if not value:
                    return True  # empty IN-list matches nothing
                mn, mx = st
                try:
                    vals = [_coerce_hint(v) for v in value]
                    vals = [v for v in vals if v is not None]
                    if vals and all(v < mn or v > mx for v in vals):
                        return True
                except TypeError:
                    pass  # incomparable: keep the shard
                continue
            v = _coerce_hint(value)
            if v is None:
                continue
            mn, mx = st
            try:
                if op == "eq" and (v < mn or v > mx):
                    return True
                if op == "lt" and mn >= v:
                    return True
                if op == "le" and mn > v:
                    return True
                if op == "gt" and mx <= v:
                    return True
                if op == "ge" and mx < v:
                    return True
            except TypeError:
                continue  # incomparable: keep the shard
        return False

    def page(self, table: str) -> Page:
        return self.scan(table, 0, self.row_count(table))

    def scan(self, table: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None, _retries: int = 2) -> Page:
        import pyarrow as pa

        schema = self.schema(table)
        names = list(columns) if columns is not None else list(schema)
        stop = min(stop, self.row_count(table))
        kept, skipped = [], 0
        offset = 0
        for sid, path, rows in self._shards(table):
            s0, s1 = offset, offset + rows
            offset = s1
            if s1 <= start or s0 >= stop:
                continue
            if predicate and self._refuted(sid, predicate):
                skipped += 1
                continue
            kept.append((path, max(start - s0, 0), min(stop, s1) - s0))
        self.last_scan_files_read = len(kept)
        self.last_scan_files_skipped = skipped
        try:
            pieces = [
                self._read_shard(p).select(names).slice(lo, hi - lo)
                for p, lo, hi in kept
            ]
        except FileNotFoundError:
            # a concurrent organize() GC'd a file between listing and
            # read; seq-stable offsets make a retry against fresh
            # metadata return the identical rows. Bounded: a PERMANENTLY
            # missing file (external deletion) must surface, not recurse
            if _retries <= 0:
                raise
            return self.scan(
                table, start, stop, pad_to=pad_to, columns=columns,
                predicate=predicate, _retries=_retries - 1,
            )
        if pieces:
            tb = pa.concat_tables(pieces)
        else:
            from .parquet import _type_to_arrow

            tb = pa.table(
                {n: pa.array([], type=_type_to_arrow(schema[n]))
                 for n in names}
            )
        return arrow_table_to_page(
            tb, names, tb.num_rows, pad_to,
            lambda name: self._dictionary(table, name),
        )

    # -- organization (reference storage/organization/ShardCompactor) -----

    def _merged_stats(self, shard_ids) -> dict:
        """Combine the stored stats of `shard_ids`: min of mins, max of
        maxes per column (ignoring shards with no stats for a column)."""
        qmarks = ",".join("?" * len(shard_ids))
        with self._db_lock:
            rows = self.db.execute(
                f"SELECT column_name, kind, min_v, max_v FROM shard_stats "
                f"WHERE shard_id IN ({qmarks})",
                tuple(shard_ids),
            ).fetchall()
        return _combine_stats(
            [{c: (k, mn, mx)} for c, k, mn, mx in rows]
        )

    def organize(self, table: Optional[str] = None) -> dict:
        """Merge CONTIGUOUS runs of small shards into compaction-target-
        sized shards (reference ShardCompactor.compact). The merged shard
        inherits the run's first `seq`, and only seq-adjacent shards
        merge, so the table's global row order — and therefore any
        streaming query's offset pagination — is unchanged by
        compaction. Swap is one metadata transaction; old files are GC'd
        after (a reader mid-swap retries against fresh metadata).
        Returns {table: shards_merged}."""
        import pyarrow as pa

        report = {}
        tables = [table] if table else self.table_names()
        for t in tables:
            with self._db_lock:
                shards = self.db.execute(
                    "SELECT id, path, rows, seq, COALESCE(max_seq, seq) "
                    "FROM shards WHERE table_name = ? ORDER BY seq",
                    (t,),
                ).fetchall()
            merged = 0
            run: List = []
            acc = 0

            def flush(run, _t=t):
                if len(run) < 2:
                    return 0
                tb = pa.concat_tables(
                    [self._read_shard(p) for _i, p, _r, _q, _m in run]
                )
                # the merged shard's stats are the combine of the stored
                # per-shard stats — no dictionary rebuild, no device
                # round-trip (reference ShardCompactor merges ColumnStats
                # the same way)
                stats = self._merged_stats([i for i, *_rest in run])
                path = self._write_file(_t, tb)
                # seq interval [first seq, max covered seq] keeps both
                # offset pagination AND scan_delta() exact across the
                # merge; bump=False because the data is unchanged —
                # compaction must never invalidate caches or matviews
                self._insert_shard_meta(
                    _t, path, tb.num_rows, stats,
                    seq=run[0][3],
                    max_seq=max(m for *_x, m in run),
                    drop_ids=[i for i, *_rest in run],
                    bump=False,
                )
                self._gc([p for _i, p, _r, _q, _m in run])
                return len(run)

            for sid, path, rows, seq, mseq in shards:
                if rows < self.compact_rows and acc + rows <= max(
                    self.compact_rows, rows
                ):
                    run.append((sid, path, rows, seq, mseq))
                    acc += rows
                    if acc >= self.compact_rows:
                        merged += flush(run)
                        run, acc = [], 0
                else:
                    # a large shard (or target reached) ends the
                    # contiguous run — never merge across it
                    merged += flush(run)
                    run, acc = [], 0
                    if rows < self.compact_rows:
                        run.append((sid, path, rows, seq, mseq))
                        acc = rows
            merged += flush(run)
            if merged:
                report[t] = merged
                self.organize_events.append({"table": t, "merged": merged})
        return report

    def start_organizer(self, interval_s: float = 30.0) -> None:
        """Background compaction loop (reference ShardOrganizer's
        periodic organization jobs)."""
        if self._organizer is not None:
            return
        self._organizer_stop.clear()

        def loop():
            while not self._organizer_stop.wait(interval_s):
                try:
                    self.organize()
                except Exception:  # noqa: BLE001 - keep the daemon alive
                    pass

        self._organizer = threading.Thread(target=loop, daemon=True)
        self._organizer.start()

    def stop_organizer(self) -> None:
        if self._organizer is not None:
            self._organizer_stop.set()
            self._organizer.join(timeout=5)
            self._organizer = None
