"""RCFile connector: row-columnar files -> device Pages.

Re-designed equivalent of presto-rcfile (7,271 LoC: RcFileReader/Writer
with text and binary column encodings). RCFile's layout — row groups
holding column-major chunks, a sync marker between groups, per-chunk
lengths — is implemented here directly (no Hadoop): the WRITER produces
files with the classic structure (magic, version, metadata, sync-
delimited row groups of length-prefixed column chunks, binary-encoded
values) and the READER maps row ranges onto row groups by the stored
row counts, decoding only requested columns — the same columnar-skip
property the reference's RcFileReader exploits.

Encodings (the binary/lazy-binary serde subset this engine's types
need): int64/int32 little-endian fixed width, float64, bool bytes,
dates as int32 days, decimals as scaled int64, varchar as utf-8 with
u32 offsets. A JSON header row carries the schema (the reference stores
it in file metadata key/values the same way).
"""

from __future__ import annotations

import json
import os
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page, _pad_block
from .spi import Connector, Predicate, WritableConnector, WriteError

_MAGIC = b"RCF\x01tpu"
_SYNC = b"\xde\xad\xbe\xef\xf0\x0d\xca\xfe" * 2  # 16-byte sync marker
_ROWS_PER_GROUP = 1 << 16


def _type_name(t: T.Type) -> str:
    return str(t)


def _encode_column(blk_data: np.ndarray, typ: T.Type, valid) -> bytes:
    if isinstance(typ, T.VarcharType):
        # blk_data here is a python list of strings ("" for NULL slots)
        blob = b"".join(s.encode("utf-8") for s in blk_data)
        offs = np.zeros(len(blk_data) + 1, np.uint32)
        np.cumsum(
            [len(s.encode("utf-8")) for s in blk_data], out=offs[1:]
        )
        payload = offs.tobytes() + blob
    else:
        payload = np.ascontiguousarray(blk_data).tobytes()
    vbits = (
        np.packbits(np.asarray(valid, bool)).tobytes()
        if valid is not None
        else b""
    )
    return struct.pack("<II", len(payload), len(vbits)) + payload + vbits


def _decode_column(
    buf: bytes, off: int, typ: T.Type, n: int
) -> Tuple[object, Optional[np.ndarray], int]:
    plen, vlen = struct.unpack_from("<II", buf, off)
    off += 8
    payload = buf[off : off + plen]
    off += plen
    valid = None
    if vlen:
        bits = np.frombuffer(buf[off : off + vlen], np.uint8)
        valid = np.unpackbits(bits)[:n].astype(bool)
        off += vlen
    if isinstance(typ, T.VarcharType):
        offs = np.frombuffer(payload[: 4 * (n + 1)], np.uint32)
        blob = payload[4 * (n + 1):]
        vals = [
            blob[offs[i]: offs[i + 1]].decode("utf-8") for i in range(n)
        ]
        return vals, valid, off
    dt = np.dtype(typ.storage_dtype.__name__ if hasattr(typ.storage_dtype, "__name__") else typ.storage_dtype)
    data = np.frombuffer(payload, dt, count=n)
    return data, valid, off


class RcFileCatalog(WritableConnector):
    """tables: {name: rcfile path}; with `directory` set the catalog is
    writable (CTAS/INSERT/DELETE produce .rcf files)."""

    name = "rcfile"
    _ext = "rcf"

    def __init__(self, tables: Dict[str, str],
                 directory: Optional[str] = None):
        self.paths = dict(tables)
        self.directory = directory
        self._meta_cache: Dict[str, dict] = {}

    # -- file structure --

    def _read_header(self, table: str) -> dict:
        got = self._meta_cache.get(table)
        if got is not None:
            return got
        path = self.paths[table]
        with open(path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise WriteError(f"{path}: not an rcfile")
            (hlen,) = struct.unpack("<I", f.read(4))
            header = json.loads(f.read(hlen))
            groups = []
            off = f.tell()
            data = f.read()
        # group directory: scan sync markers (the reference seeks the
        # same way; counts are stored per group right after the sync)
        pos = 0
        while pos < len(data):
            if data[pos : pos + len(_SYNC)] != _SYNC:
                raise WriteError(f"{path}: lost sync at {off + pos}")
            pos += len(_SYNC)
            n, glen = struct.unpack_from("<II", data, pos)
            pos += 8
            groups.append({"rows": n, "offset": off + pos, "length": glen})
            pos += glen
        header["groups"] = groups
        self._meta_cache[table] = header
        return header

    # -- metadata --

    def table_names(self) -> List[str]:
        return list(self.paths)

    def schema(self, table: str) -> Dict[str, T.Type]:
        h = self._read_header(table)
        return {c: T.parse_type(s) for c, s in h["schema"].items()}

    def row_count(self, table: str) -> int:
        return sum(g["rows"] for g in self._read_header(table)["groups"])

    def exact_row_count(self, table: str) -> int:
        return self.row_count(table)

    def unique_columns(self, table: str):
        return []

    # -- reads --

    def page(self, table: str) -> Page:
        return self.scan(table, 0, self.row_count(table))

    def scan(self, table: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None) -> Page:
        h = self._read_header(table)
        schema = self.schema(table)
        names = list(columns) if columns is not None else list(schema)
        col_order = list(schema)
        stop = min(stop, self.row_count(table))
        count = max(stop - start, 0)
        pieces: Dict[str, list] = {c: [] for c in names}
        vpieces: Dict[str, list] = {c: [] for c in names}
        path = self.paths[table]
        with open(path, "rb") as f:
            offset = 0
            for g in h["groups"]:
                g_start, g_stop = offset, offset + g["rows"]
                offset = g_stop
                lo, hi = max(start, g_start), min(stop, g_stop)
                if lo >= hi:
                    continue
                f.seek(g["offset"])
                buf = f.read(g["length"])
                pos = 0
                for c in col_order:
                    # column chunks are length-prefixed: skip unrequested
                    # columns WITHOUT decoding (the row-columnar win)
                    if c not in pieces:
                        plen, vlen = struct.unpack_from("<II", buf, pos)
                        pos += 8 + plen + vlen
                        continue
                    vals, valid, pos = _decode_column(
                        buf, pos, schema[c], g["rows"]
                    )
                    sl = slice(lo - g_start, hi - g_start)
                    pieces[c].append(vals[sl])
                    vpieces[c].append(
                        valid[sl]
                        if valid is not None
                        else np.ones(hi - lo, bool)
                    )
        blocks = []
        for c in names:
            typ = schema[c]
            vs = pieces[c]
            valid = (
                np.concatenate(vpieces[c]) if vpieces[c] else np.ones(0, bool)
            )
            if isinstance(typ, T.VarcharType):
                flat: List[str] = []
                for p in vs:
                    flat.extend(p)
                vals = [
                    s if ok else None for s, ok in zip(flat, valid.tolist())
                ]
                blk = Block.from_strings(vals)
            else:
                data = (
                    np.concatenate(vs)
                    if vs
                    else np.empty(0, np.int64)
                )
                blk = Block.from_numpy(
                    data, typ,
                    valid=None if valid.all() else valid,
                )
            if pad_to is not None and pad_to > count:
                blk = _pad_block(blk, pad_to)
            blocks.append(blk)
        return Page.from_blocks(blocks, names, count=count)

    # -- writes --

    def _write_path(self, table: str) -> str:
        if table in self.paths:
            return self.paths[table]
        if self.directory is None:
            raise WriteError("rcfile catalog is read-only (no directory)")
        path = os.path.join(self.directory, f"{table}.{self._ext}")
        self.paths[table] = path
        return path

    def _page_columns(self, page: Page):
        """(per-column python/numpy values, valid arrays) from a Page."""
        rows = page.to_pylist()
        cols = {}
        for i, (name, blk) in enumerate(zip(page.names, page.blocks)):
            vals = [r[i] for r in rows]
            valid = np.array([v is not None for v in vals], bool)
            cols[name] = (vals, None if valid.all() else valid)
        return cols

    def write_pages(self, table: str, page: Page) -> None:
        import datetime
        import decimal

        path = self._write_path(table)
        schema = {
            n: b.type for n, b in zip(page.names, page.blocks)
        }
        cols = self._page_columns(page)
        n = int(page.count)
        header = {
            "schema": {c: _type_name(t) for c, t in schema.items()},
        }
        hjson = json.dumps(header).encode()
        with open(path, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<I", len(hjson)))
            f.write(hjson)
            for g0 in range(0, max(n, 1), _ROWS_PER_GROUP):
                g1 = min(g0 + _ROWS_PER_GROUP, n)
                if n == 0:
                    g1 = 0
                chunks = []
                for c, t in schema.items():
                    vals, valid = cols[c]
                    gv = vals[g0:g1]
                    gvalid = None if valid is None else valid[g0:g1]
                    if isinstance(t, T.VarcharType):
                        enc = [v if v is not None else "" for v in gv]
                    elif isinstance(t, T.DecimalType):
                        enc = np.array(
                            [
                                int(
                                    (v if isinstance(v, decimal.Decimal)
                                     else decimal.Decimal(str(v)))
                                    .scaleb(t.scale)
                                )
                                if v is not None
                                else 0
                                for v in gv
                            ],
                            np.int64,
                        )
                    elif isinstance(t, T.DateType):
                        epoch = datetime.date(1970, 1, 1)
                        def _days(v):
                            if v is None:
                                return 0
                            if isinstance(v, np.datetime64):
                                return int(
                                    v.astype("datetime64[D]").astype(int)
                                )
                            return (v - epoch).days
                        enc = np.array([_days(v) for v in gv], np.int32)
                    else:
                        dt = np.dtype(t.storage_dtype.__name__ if hasattr(t.storage_dtype, "__name__") else t.storage_dtype)
                        fill = 0 if dt.kind in "iub" else 0.0
                        enc = np.array(
                            [v if v is not None else fill for v in gv], dt
                        )
                    chunks.append(_encode_column(enc, t, gvalid))
                body = b"".join(chunks)
                f.write(_SYNC)
                f.write(struct.pack("<II", g1 - g0, len(body)))
                f.write(body)
                if n == 0:
                    break
        self._meta_cache.pop(table, None)

    def create_table(self, table: str, schema: Dict[str, T.Type]) -> None:
        from ..ops.union import empty_page

        if table in self.paths:
            raise WriteError(f"table {table} exists")
        self.write_pages(table, empty_page(schema))

    def create_table_from_page(self, table: str, page: Page) -> None:
        if table in self.paths:
            raise WriteError(f"table {table} exists")
        self.write_pages(table, page)

    def append(self, table: str, page: Page) -> None:
        from ..ops.union import concat_pages

        cur = self.page(table)
        merged = page if int(cur.count) == 0 else concat_pages([cur, page])
        self.write_pages(table, merged)

    def replace(self, table: str, page: Page) -> None:
        self.write_pages(table, page)

    def drop_table(self, table: str) -> None:
        path = self.paths.pop(table, None)
        if path is None:
            raise WriteError(f"unknown table {table}")
        self._meta_cache.pop(table, None)
        if os.path.exists(path):
            os.remove(path)
