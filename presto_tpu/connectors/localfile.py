"""Local-file connector: query CSV / JSONL files as tables.

Re-designed equivalent of presto-local-file (presto-local-file/src/main/
java/...) combined with the row decoders of presto-record-decoder
(csv/json decoders shared by the kafka/redis connectors). A directory is
a catalog: every *.csv / *.tsv / *.jsonl file is a table named after the
file stem. Schemas are inferred from the data (or supplied explicitly);
columns load once into device Pages and are cached, so repeated queries
scan device-resident data like every other connector.
"""

from __future__ import annotations

import csv
import datetime
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page
from .spi import Connector

_EPOCH = datetime.date(1970, 1, 1)


def _infer_type(values: Sequence[Optional[str]]) -> T.Type:
    """Widest type that parses every non-null sample."""
    seen = [v for v in values if v is not None and v != ""]
    if not seen:
        return T.VARCHAR

    def all_match(fn) -> bool:
        try:
            for v in seen:
                fn(v)
            return True
        except (ValueError, TypeError):
            return False

    if all_match(int):
        return T.BIGINT
    if all_match(float):
        return T.DOUBLE
    if all_match(datetime.date.fromisoformat):
        return T.DATE
    lowered = {str(v).lower() for v in seen}
    if lowered <= {"true", "false"}:
        return T.BOOLEAN
    return T.VARCHAR


def _to_block(values: List, typ: T.Type) -> Block:
    nulls = [v is None or v == "" for v in values]
    any_null = any(nulls)
    valid = None if not any_null else np.array([not x for x in nulls], np.bool_)
    if isinstance(typ, T.VarcharType):
        return Block.from_strings(
            [None if n else str(v) for v, n in zip(values, nulls)]
        )
    if isinstance(typ, T.DateType):
        data = np.array(
            [
                0 if n else (datetime.date.fromisoformat(str(v)) - _EPOCH).days
                for v, n in zip(values, nulls)
            ],
            np.int32,
        )
        return Block.from_numpy(data, typ, valid)
    if isinstance(typ, T.BooleanType):
        data = np.array(
            [False if n else str(v).lower() == "true" for v, n in zip(values, nulls)],
            np.bool_,
        )
        return Block.from_numpy(data, typ, valid)
    if T.is_floating(typ):
        data = np.array(
            [0.0 if n else float(v) for v, n in zip(values, nulls)], np.float64
        )
        return Block.from_numpy(data, typ, valid)
    data = np.array(
        [0 if n else int(v) for v, n in zip(values, nulls)], np.int64
    )
    return Block.from_numpy(data, typ, valid)


def read_csv(path: str, delimiter: Optional[str] = None) -> Tuple[List[str], List[List]]:
    if delimiter is None:
        delimiter = "\t" if path.endswith(".tsv") else ","
    with open(path, newline="") as f:
        reader = csv.reader(f, delimiter=delimiter)
        rows = list(reader)
    if not rows:
        return [], []
    header, data = rows[0], rows[1:]
    cols = [[r[i] if i < len(r) else None for r in data] for i in range(len(header))]
    return header, cols


def read_jsonl(path: str) -> Tuple[List[str], List[List]]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    names: List[str] = []
    for r in records:
        for k in r:
            if k not in names:
                names.append(k)
    cols = [[r.get(k) for r in records] for k in names]
    return names, cols


class LocalFileCatalog(Connector):
    """tables: file stem -> path; schemas inferred at first load and
    overridable via `schemas={'table': {'col': Type}}`."""

    name = "localfile"

    def __init__(self, directory: str, schemas: Optional[Dict] = None):
        self.directory = directory
        self.schemas_override = schemas or {}
        self._paths: Dict[str, str] = {}
        for fname in sorted(os.listdir(directory)):
            stem, ext = os.path.splitext(fname)
            if ext.lower() in (".csv", ".tsv", ".jsonl"):
                key = stem.lower()
                if key in self._paths:
                    raise ValueError(
                        f"duplicate table name {key!r}: "
                        f"{os.path.basename(self._paths[key])} and {fname}"
                    )
                self._paths[key] = os.path.join(directory, fname)
        self._pages: Dict[str, Page] = {}

    def table_names(self) -> List[str]:
        return list(self._paths)

    def _load(self, table: str) -> Page:
        pg = self._pages.get(table)
        if pg is not None:
            return pg
        path = self._paths[table]
        if path.endswith(".jsonl"):
            names, cols = read_jsonl(path)
        else:
            names, cols = read_csv(path)
        override = self.schemas_override.get(table, {})
        blocks = []
        lowered = [n.lower() for n in names]
        for n, c in zip(lowered, cols):
            # values normalize to strings here; JSONL values arrive typed
            strs = [None if v is None else str(v) for v in c]
            typ = override.get(n)
            if typ is None:
                typ = _infer_type(strs[:1000])
            try:
                blocks.append(_to_block(strs, typ))
            except (ValueError, TypeError):
                if n in override:
                    raise  # explicit schema: surface the bad value
                # inference sampled a clean prefix; fall back to varchar
                blocks.append(_to_block(strs, T.VARCHAR))
        pg = Page.from_blocks(blocks, lowered, count=len(cols[0]) if cols else 0)
        self._pages[table] = pg
        return pg

    def schema(self, table: str) -> Dict[str, T.Type]:
        pg = self._load(table)
        return {n: b.type for n, b in zip(pg.names, pg.blocks)}

    def row_count(self, table: str) -> int:
        return int(self._load(table).count)

    def unique_columns(self, table: str):
        return []

    def page(self, table: str) -> Page:
        return self._load(table)
