"""Local-file connector: query CSV / JSONL files as tables.

Re-designed equivalent of presto-local-file (presto-local-file/src/main/
java/...) combined with the row decoders of presto-record-decoder
(csv/json decoders shared by the kafka/redis connectors). A directory is
a catalog: every *.csv / *.tsv / *.jsonl file is a table named after the
file stem. Schemas are inferred from the data (or supplied explicitly);
columns load once into device Pages and are cached, so repeated queries
scan device-resident data like every other connector.
"""

from __future__ import annotations

import csv
import datetime
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page
from .spi import Connector

_EPOCH = datetime.date(1970, 1, 1)


def _infer_type(values: Sequence[Optional[str]]) -> T.Type:
    """Widest type that parses every non-null sample."""
    seen = [v for v in values if v is not None and v != ""]
    if not seen:
        return T.VARCHAR

    def all_match(fn) -> bool:
        try:
            for v in seen:
                fn(v)
            return True
        except (ValueError, TypeError):
            return False

    if all_match(int):
        return T.BIGINT
    if all_match(float):
        return T.DOUBLE
    if all_match(datetime.date.fromisoformat):
        return T.DATE
    lowered = {str(v).lower() for v in seen}
    if lowered <= {"true", "false"}:
        return T.BOOLEAN
    return T.VARCHAR


def _to_block(values: List, typ: T.Type) -> Block:
    nulls = [v is None or v == "" for v in values]
    any_null = any(nulls)
    valid = None if not any_null else np.array([not x for x in nulls], np.bool_)
    if isinstance(typ, T.VarcharType):
        return Block.from_strings(
            [None if n else str(v) for v, n in zip(values, nulls)]
        )
    if isinstance(typ, T.DateType):
        data = np.array(
            [
                0 if n else (datetime.date.fromisoformat(str(v)) - _EPOCH).days
                for v, n in zip(values, nulls)
            ],
            np.int32,
        )
        return Block.from_numpy(data, typ, valid)
    if isinstance(typ, T.BooleanType):
        data = np.array(
            [False if n else str(v).lower() == "true" for v, n in zip(values, nulls)],
            np.bool_,
        )
        return Block.from_numpy(data, typ, valid)
    if T.is_floating(typ):
        data = np.array(
            [0.0 if n else float(v) for v, n in zip(values, nulls)], np.float64
        )
        return Block.from_numpy(data, typ, valid)
    data = np.array(
        [0 if n else int(v) for v, n in zip(values, nulls)], np.int64
    )
    return Block.from_numpy(data, typ, valid)


def read_csv(path: str, delimiter: Optional[str] = None) -> Tuple[List[str], List[List]]:
    if delimiter is None:
        delimiter = "\t" if path.endswith(".tsv") else ","
    with open(path, newline="") as f:
        reader = csv.reader(f, delimiter=delimiter)
        rows = list(reader)
    if not rows:
        return [], []
    header, data = rows[0], rows[1:]
    cols = [[r[i] if i < len(r) else None for r in data] for i in range(len(header))]
    return header, cols


def read_jsonl(path: str) -> Tuple[List[str], List[List]]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    names: List[str] = []
    for r in records:
        for k in r:
            if k not in names:
                names.append(k)
    cols = [[r.get(k) for r in records] for k in names]
    return names, cols




# ---------------------------------------------------------------------------
# Avro object-container files (reference presto-record-decoder
# AvroRowDecoder / avro-tools): from-scratch binary codec — zigzag
# varints, [null, T] unions, null/deflate block codecs — no avro library
# in the image, same from-scratch policy as native/lz4.cpp
# ---------------------------------------------------------------------------

import struct as _st  # noqa: E402 - avro/raw binary codecs below

_AVRO_MAGIC = b"Obj\x01"


def _zz_encode(n: int) -> bytes:
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class _AvroReader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def long(self) -> int:
        u = shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            u |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (u >> 1) ^ -(u & 1)

    def raw(self, n: int) -> bytes:
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def string(self) -> str:
        return self.raw(self.long()).decode()

    def map(self) -> dict:
        out = {}
        while True:
            n = self.long()
            if n == 0:
                return out
            if n < 0:
                self.long()  # block byte size (unused)
                n = -n
            for _ in range(n):
                k = self.string()
                out[k] = self.raw(self.long())


def _avro_read_value(r: "_AvroReader", typ):
    if isinstance(typ, list):  # union: [null, T] nullable convention
        idx = r.long()
        branch = typ[idx]
        if branch == "null":
            return None
        return _avro_read_value(r, branch)
    if isinstance(typ, dict):
        typ = typ.get("type", typ)
        return _avro_read_value(r, typ)
    if typ == "null":
        return None
    if typ == "boolean":
        return r.raw(1) != b"\x00"
    if typ in ("int", "long"):
        return r.long()
    if typ == "float":
        return _st.unpack("<f", r.raw(4))[0]
    if typ == "double":
        return _st.unpack("<d", r.raw(8))[0]
    if typ == "bytes":
        # binary rides the string layer as hex (engine-wide policy)
        return r.raw(r.long()).hex()
    if typ == "string":
        return r.string()
    raise ValueError(f"unsupported avro type {typ!r}")


def read_avro(path: str) -> Tuple[List[str], List[List]]:
    """Avro OCF -> (names, columns). Primitive record fields + nullable
    unions; null/deflate codecs."""
    import zlib as _zlib

    with open(path, "rb") as f:
        buf = f.read()
    if buf[:4] != _AVRO_MAGIC:
        raise ValueError(f"{path}: not an avro object container file")
    r = _AvroReader(buf)
    r.pos = 4
    meta = r.map()
    sync = r.raw(16)
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if schema.get("type") != "record":
        raise ValueError("avro schema must be a record")
    fields = schema["fields"]
    names = [f["name"] for f in fields]
    cols: List[List] = [[] for _ in names]
    while r.pos < len(buf):
        count = r.long()
        size = r.long()
        block = r.raw(size)
        if r.raw(16) != sync:
            raise ValueError(f"{path}: bad avro sync marker")
        if codec == "deflate":
            block = _zlib.decompress(block, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        br = _AvroReader(block)
        for _ in range(count):
            for i, fd in enumerate(fields):
                cols[i].append(_avro_read_value(br, fd["type"]))
    return names, cols


def write_avro(path: str, names: Sequence[str], cols: Sequence[List],
               codec: str = "deflate") -> None:
    """Columns -> Avro OCF (the writer twin; nullable primitive fields,
    types inferred from python values)."""
    import zlib as _zlib

    def typ_of(values):
        for v in values:
            if v is None:
                continue
            if isinstance(v, bool):
                return "boolean"
            if isinstance(v, int):
                return "long"
            if isinstance(v, float):
                return "double"
            if isinstance(v, bytes):
                return "bytes"
            return "string"
        return "string"

    types = [typ_of(c) for c in cols]
    schema = {
        "type": "record",
        "name": "row",
        "fields": [
            {"name": n, "type": ["null", t]}
            for n, t in zip(names, types)
        ],
    }

    def enc_value(v, t) -> bytes:
        if v is None:
            return _zz_encode(0)
        out = _zz_encode(1)
        if t == "boolean":
            return out + (b"\x01" if v else b"\x00")
        if t == "long":
            return out + _zz_encode(int(v))
        if t == "double":
            return out + _st.pack("<d", float(v))
        if t == "bytes":
            return out + _zz_encode(len(v)) + v
        b = str(v).encode()
        return out + _zz_encode(len(b)) + b

    n_rows = len(cols[0]) if cols else 0
    body = b"".join(
        enc_value(cols[i][row], types[i])
        for row in range(n_rows)
        for i in range(len(names))
    )
    if codec == "deflate":
        comp = _zlib.compressobj(wbits=-15)
        body = comp.compress(body) + comp.flush()
    sync = b"\x07" * 16
    meta_entries = {
        b"avro.schema": json.dumps(schema).encode(),
        b"avro.codec": codec.encode(),
    }
    out = bytearray(_AVRO_MAGIC)
    out += _zz_encode(len(meta_entries))
    for k, v in meta_entries.items():
        out += _zz_encode(len(k)) + k + _zz_encode(len(v)) + v
    out += _zz_encode(0)
    out += sync
    if n_rows:
        out += _zz_encode(n_rows) + _zz_encode(len(body)) + body + sync
    with open(path, "wb") as f:
        f.write(bytes(out))


def read_raw(path: str, fields: Sequence[dict]) -> Tuple[List[str], List[List]]:
    """Fixed-width binary records (reference presto-record-decoder
    RawRowDecoder): `fields` = [{name, type, start, end}] byte slices per
    record; big-endian ints/doubles, space-padded varchar. The field
    spec lives in a sidecar `<table>.rawschema` JSON."""
    import struct as _st

    rec_size = max(int(f["end"]) for f in fields)
    names = [f["name"] for f in fields]
    cols: List[List] = [[] for _ in fields]
    with open(path, "rb") as fh:
        data = fh.read()
    if len(data) % rec_size:
        raise ValueError(
            f"{path}: size {len(data)} is not a multiple of the "
            f"record size {rec_size}"
        )
    for off in range(0, len(data), rec_size):
        rec = data[off:off + rec_size]
        for i, fd in enumerate(fields):
            chunk = rec[int(fd["start"]):int(fd["end"])]
            t = fd["type"].lower()
            if t in ("bigint", "long"):
                cols[i].append(_st.unpack(">q", chunk)[0])
            elif t in ("integer", "int"):
                cols[i].append(_st.unpack(">i", chunk)[0])
            elif t == "smallint":
                cols[i].append(_st.unpack(">h", chunk)[0])
            elif t == "tinyint":
                cols[i].append(_st.unpack(">b", chunk)[0])
            elif t == "double":
                cols[i].append(_st.unpack(">d", chunk)[0])
            elif t == "boolean":
                cols[i].append(chunk[0] != 0)
            else:  # varchar: space-padded bytes
                cols[i].append(chunk.decode().rstrip(" \x00"))
    return names, cols


class LocalFileCatalog(Connector):
    """tables: file stem -> path; schemas inferred at first load and
    overridable via `schemas={'table': {'col': Type}}`."""

    name = "localfile"

    def __init__(self, directory: str, schemas: Optional[Dict] = None):
        self.directory = directory
        self.schemas_override = schemas or {}
        self._paths: Dict[str, str] = {}
        for fname in sorted(os.listdir(directory)):
            stem, ext = os.path.splitext(fname)
            if ext.lower() in (".csv", ".tsv", ".jsonl", ".avro", ".raw"):
                key = stem.lower()
                if key in self._paths:
                    raise ValueError(
                        f"duplicate table name {key!r}: "
                        f"{os.path.basename(self._paths[key])} and {fname}"
                    )
                self._paths[key] = os.path.join(directory, fname)
        self._pages: Dict[str, Page] = {}

    def table_names(self) -> List[str]:
        return list(self._paths)

    def _load(self, table: str) -> Page:
        pg = self._pages.get(table)
        if pg is not None:
            return pg
        path = self._paths[table]
        low = path.lower()  # registration is case-insensitive; match it
        if low.endswith(".jsonl"):
            names, cols = read_jsonl(path)
        elif low.endswith(".avro"):
            names, cols = read_avro(path)
        elif low.endswith(".raw"):
            with open(path[:-4] + ".rawschema") as f:
                names, cols = read_raw(path, json.load(f))
        else:
            names, cols = read_csv(path)
        override = self.schemas_override.get(table, {})
        blocks = []
        lowered = [n.lower() for n in names]
        for n, c in zip(lowered, cols):
            # values normalize to strings here; JSONL values arrive typed
            strs = [None if v is None else str(v) for v in c]
            typ = override.get(n)
            if typ is None:
                typ = _infer_type(strs[:1000])
            try:
                blocks.append(_to_block(strs, typ))
            except (ValueError, TypeError):
                if n in override:
                    raise  # explicit schema: surface the bad value
                # inference sampled a clean prefix; fall back to varchar
                blocks.append(_to_block(strs, T.VARCHAR))
        pg = Page.from_blocks(blocks, lowered, count=len(cols[0]) if cols else 0)
        self._pages[table] = pg
        return pg

    def schema(self, table: str) -> Dict[str, T.Type]:
        pg = self._load(table)
        return {n: b.type for n, b in zip(pg.names, pg.blocks)}

    def row_count(self, table: str) -> int:
        return int(self._load(table).count)

    def unique_columns(self, table: str):
        return []

    def page(self, table: str) -> Page:
        return self._load(table)
