"""TPC-H data generator — columnar, vectorized, deterministic.

Re-designed equivalent of the reference's presto-tpch connector
(presto-tpch/src/main/java/com/facebook/presto/tpch/, which wraps the
io.airlift.tpch dbgen port; presto-tpch/pom.xml:20). Like the reference it is
the engine's primary benchmark/test data source (BenchmarkQueryRunner.java:55).

Differences from classic dbgen, on purpose:
* Generation is vectorized numpy (single pass per column) instead of the
  per-row C-style RNG streams, so SF10 generates in seconds on the host.
  Distributions, domains, cardinalities and referential rules follow the
  TPC-H spec (sizes §4.2.5, pricing formulas §4.2.3); text columns come from
  spec word lists but with a bounded combinatorial pool so they stay
  dictionary-friendly. Checksums therefore match OUR oracle, not Java dbgen —
  cross-engine checksum parity is tracked in BASELINE.md.
* Strings are born dictionary-encoded. Per-row-unique formatted strings
  (c_name, phones, clerks …) use LazyDict subclasses so we never materialize
  millions of python strings.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..page import Block, LazyDict, Page, intern_dictionary

# ---------------------------------------------------------------------------
# spec constants
# ---------------------------------------------------------------------------

STARTDATE = 8035  # 1992-01-01
CURRENTDATE = 9298  # 1995-06-17
ENDDATE = 10591  # 1998-12-31

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
INSTRUCTIONS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
CONTAINERS = [
    f"{a} {b}"
    for a in ["JUMBO", "LG", "MED", "SM", "WRAP"]
    for b in ["BAG", "BOX", "CAN", "CASE", "DRUM", "JAR", "PACK", "PKG"]
]
TYPES = [
    f"{a} {b} {c}"
    for a in ["ECONOMY", "LARGE", "MEDIUM", "PROMO", "SMALL", "STANDARD"]
    for b in ["ANODIZED", "BRUSHED", "BURNISHED", "PLATED", "POLISHED"]
    for c in ["BRASS", "COPPER", "NICKEL", "STEEL", "TIN"]
]
COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn", "lemon",
    "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
    "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
    "orchid", "pale", "papaya", "peach", "peru", "pink", "plum", "powder",
    "puff", "purple", "red", "rose", "rosy", "royal", "saddle", "salmon",
    "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
    "steel", "tan", "thistle", "tomato", "turquoise", "violet", "wheat", "white",
    "yellow",
]

_COMMENT_VERBS = ["sleep", "wake", "haggle", "nag", "cajole", "detect", "integrate", "boost", "promise", "solve"]
_COMMENT_ADJS = ["furious", "sly", "careful", "blithe", "quick", "fluffy", "slow", "quiet", "ruthless", "thin"]
_COMMENT_NOUNS = ["packages", "requests", "accounts", "deposits", "foxes", "ideas", "theodolites", "pinto beans", "instructions", "dependencies"]
_COMMENT_ADVS = ["quickly", "slowly", "blithely", "carefully", "furiously", "silently", "daringly", "evenly", "finally", "especially"]

COMMENT_POOL = tuple(
    sorted(
        {
            f"{adv} {adj} {noun} {verb} about the {adj2} {noun2}"
            for adv in _COMMENT_ADVS[:6]
            for adj in _COMMENT_ADJS[:6]
            for noun in _COMMENT_NOUNS[:6]
            for verb in ["haggle", "nag", "sleep", "wake"]
            for adj2, noun2 in [("furious", "packages"), ("special", "requests"),
                                ("express", "deposits"), ("regular", "accounts")]
        }
    )
)

# supplier comments for Q16: some contain 'Customer...Complaints'
SUPP_COMMENT_POOL = tuple(
    sorted(
        set(COMMENT_POOL[:2048])
        | {f"Customer {w} Complaints" for w in _COMMENT_ADVS}
    )
)


# ---------------------------------------------------------------------------
# lazy dictionaries for per-row-unique formatted strings
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FormatDict(LazyDict):
    """Entry i = f'{prefix}{i+1:0{width}d}' — zero-padded, so entry order is
    lexicographic order (is_sorted=True)."""

    prefix: str
    width: int
    count: int
    is_sorted: bool = True

    def __len__(self):
        return self.count

    def __getitem__(self, i):
        if isinstance(i, (int, np.integer)):
            if i < 0 or i >= self.count:
                raise IndexError(i)
            return f"{self.prefix}{i + 1:0{self.width}d}"
        raise TypeError(i)


@dataclasses.dataclass(frozen=True)
class PhoneDict(LazyDict):
    """Entry i = phone for key i+1: 'CC-LLL-LLL-LLLL' with country code
    10+nationkey. Deterministic mix of the index; NOT lexicographically
    sorted across nations (is_sorted=False)."""

    seed: int
    count: int
    nation_seed: int  # regenerate nationkeys from this seed
    is_sorted: bool = False

    def _nation(self, i):
        # must match the table's nationkey column: same generator, same seed
        if not hasattr(self, "_nations"):
            rng = np.random.default_rng(self.nation_seed)
            object.__setattr__(self, "_nations", rng.integers(0, 25, self.count))
        return self._nations[i]

    def __len__(self):
        return self.count

    def __getitem__(self, i):
        if not isinstance(i, (int, np.integer)):
            raise TypeError(i)
        n = self._nation(int(i))
        a = (i * 7919 + self.seed) % 900 + 100
        b = (i * 104729 + self.seed) % 900 + 100
        c = (i * 1299709 + self.seed) % 9000 + 1000
        return f"{10 + n}-{a}-{b}-{c}"


@dataclasses.dataclass(frozen=True)
class AddressDict(LazyDict):
    """Pseudo-random alphanumeric addresses, deterministic in the index."""

    seed: int
    count: int
    is_sorted: bool = False

    _CHARS = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 ,"

    def __len__(self):
        return self.count

    def __getitem__(self, i):
        if not isinstance(i, (int, np.integer)):
            raise TypeError(i)
        x = (int(i) + 1) * 2654435761 + self.seed
        n = 10 + x % 16
        out = []
        for _ in range(n):
            x = (x * 6364136223846793005 + 1442695040888963407) % (1 << 64)
            out.append(self._CHARS[(x >> 33) % len(self._CHARS)])
        return "".join(out)


# ---------------------------------------------------------------------------
# columnar table container
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Column:
    data: np.ndarray
    type: T.Type
    dictionary: Optional[object] = None  # tuple or LazyDict
    valid: Optional[np.ndarray] = None  # bool mask; None = all valid


@dataclasses.dataclass
class Table:
    name: str
    columns: Dict[str, Column]

    @property
    def num_rows(self) -> int:
        return len(next(iter(self.columns.values())).data)

    def to_page(self, start: int = 0, stop: Optional[int] = None, pad_to=None) -> Page:
        stop = self.num_rows if stop is None else min(stop, self.num_rows)
        blocks, names = [], []
        for name, c in self.columns.items():
            arr = c.data[start:stop]
            v = None if c.valid is None else c.valid[start:stop]
            blk = Block.from_numpy(arr, c.type, valid=v, dictionary=c.dictionary)
            blocks.append(blk)
            names.append(name)
        n = stop - start
        if pad_to is not None and pad_to > n:
            from ..page import _pad_block

            blocks = [_pad_block(b, pad_to) for b in blocks]
        return Page.from_blocks(blocks, names, count=n)


# ---------------------------------------------------------------------------
# generators
# ---------------------------------------------------------------------------


def _pool_col(rng, n, pool) -> Column:
    pool = tuple(pool) if not isinstance(pool, tuple) else pool
    codes = rng.integers(0, len(pool), n).astype(np.int32)
    return Column(codes, T.VARCHAR, pool)


def _dec(arr, scale=2, precision=12) -> Column:
    return Column(arr.astype(np.int64), T.DecimalType(precision, scale))


def gen_region() -> Table:
    rng = np.random.default_rng(1001)
    n = 5
    return Table(
        "region",
        {
            "r_regionkey": Column(np.arange(n, dtype=np.int64), T.BIGINT),
            "r_name": Column(np.arange(n, dtype=np.int32), T.VARCHAR, tuple(REGIONS)),
            "r_comment": _pool_col(rng, n, COMMENT_POOL),
        },
    )


def gen_nation() -> Table:
    rng = np.random.default_rng(1002)
    n = len(NATIONS)
    names = [x[0] for x in NATIONS]
    order = np.argsort(names)  # dictionary must be sorted; codes remap
    sorted_names = tuple(np.array(names)[order])
    code_of = {name: i for i, name in enumerate(sorted_names)}
    codes = np.array([code_of[name] for name in names], np.int32)
    return Table(
        "nation",
        {
            "n_nationkey": Column(np.arange(n, dtype=np.int64), T.BIGINT),
            "n_name": Column(codes, T.VARCHAR, sorted_names),
            "n_regionkey": Column(
                np.array([x[1] for x in NATIONS], np.int64), T.BIGINT
            ),
            "n_comment": _pool_col(rng, n, COMMENT_POOL),
        },
    )


def gen_supplier(sf: float) -> Table:
    n = int(10_000 * sf)
    rng = np.random.default_rng(2001)
    nation_seed = 2002
    nations = np.random.default_rng(nation_seed).integers(0, 25, n)
    return Table(
        "supplier",
        {
            "s_suppkey": Column(np.arange(1, n + 1, dtype=np.int64), T.BIGINT),
            "s_name": Column(
                np.arange(n, dtype=np.int32), T.VARCHAR, FormatDict("Supplier#", 9, n)
            ),
            "s_address": Column(
                np.arange(n, dtype=np.int32), T.VARCHAR, AddressDict(7, n)
            ),
            "s_nationkey": Column(nations.astype(np.int64), T.BIGINT),
            "s_phone": Column(
                np.arange(n, dtype=np.int32), T.VARCHAR, PhoneDict(17, n, nation_seed)
            ),
            "s_acctbal": _dec(rng.integers(-99999, 999999, n)),
            "s_comment": _pool_col(rng, n, SUPP_COMMENT_POOL),
        },
    )


def retail_price_cents(partkey: np.ndarray) -> np.ndarray:
    """p_retailprice = 90000 + ((partkey/10) mod 20001) + 100*(partkey mod 1000),
    in cents (spec §4.2.3)."""
    pk = partkey.astype(np.int64)
    return 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)


def gen_part(sf: float) -> Table:
    n = int(200_000 * sf)
    rng = np.random.default_rng(3001)
    pk = np.arange(1, n + 1, dtype=np.int64)
    # p_name: concatenation of 5 color words; bounded pool of pairs for the
    # dictionary, full 5-word names would explode it. Q9/Q16-style predicates
    # use LIKE '%green%', which works over any pool containing the colors.
    name_pool = tuple(
        sorted(
            {
                f"{a} {b} {c}"
                for a in COLORS[:24]
                for b in COLORS[24:48]
                for c in COLORS[48:60]
            }
        )
    )
    mfgr = rng.integers(1, 6, n)
    sub = rng.integers(1, 6, n)
    # sorted pools are Brand#11..Brand#55 / Manufacturer#1..5 in order, so
    # codes are computable arithmetically (no python loop over rows)
    brand_pool = tuple(sorted({f"Brand#{m}{x}" for m in range(1, 6) for x in range(1, 6)}))
    brand_codes = ((mfgr - 1) * 5 + (sub - 1)).astype(np.int32)
    mfgr_pool = tuple(sorted({f"Manufacturer#{m}" for m in range(1, 6)}))
    mfgr_codes = (mfgr - 1).astype(np.int32)
    return Table(
        "part",
        {
            "p_partkey": Column(pk, T.BIGINT),
            "p_name": _pool_col(rng, n, name_pool),
            "p_mfgr": Column(mfgr_codes, T.VARCHAR, mfgr_pool),
            "p_brand": Column(brand_codes, T.VARCHAR, brand_pool),
            "p_type": _pool_col(rng, n, tuple(sorted(TYPES))),
            "p_size": Column(rng.integers(1, 51, n).astype(np.int64), T.BIGINT),
            "p_container": _pool_col(rng, n, tuple(sorted(CONTAINERS))),
            "p_retailprice": _dec(retail_price_cents(pk)),
            "p_comment": _pool_col(rng, n, COMMENT_POOL),
        },
    )


def _partsupp_suppkey(partkey: np.ndarray, i: np.ndarray, s: int) -> np.ndarray:
    """Spec §4.2.5.4: ps_suppkey = (ps_partkey + (i * (S/4 + (ps_partkey-1)/S))) % S + 1"""
    pk = partkey.astype(np.int64)
    return (pk + i * (s // 4 + (pk - 1) // s)) % s + 1


def gen_partsupp(sf: float) -> Table:
    p = int(200_000 * sf)
    s = int(10_000 * sf)
    rng = np.random.default_rng(4001)
    partkey = np.repeat(np.arange(1, p + 1, dtype=np.int64), 4)
    i = np.tile(np.arange(4, dtype=np.int64), p)
    return Table(
        "partsupp",
        {
            "ps_partkey": Column(partkey, T.BIGINT),
            "ps_suppkey": Column(_partsupp_suppkey(partkey, i, s), T.BIGINT),
            "ps_availqty": Column(rng.integers(1, 10_000, 4 * p).astype(np.int64), T.BIGINT),
            "ps_supplycost": _dec(rng.integers(100, 100_001, 4 * p)),
            "ps_comment": _pool_col(rng, 4 * p, COMMENT_POOL),
        },
    )


def gen_customer(sf: float) -> Table:
    n = int(150_000 * sf)
    rng = np.random.default_rng(5001)
    nation_seed = 5002
    nations = np.random.default_rng(nation_seed).integers(0, 25, n)
    return Table(
        "customer",
        {
            "c_custkey": Column(np.arange(1, n + 1, dtype=np.int64), T.BIGINT),
            "c_name": Column(
                np.arange(n, dtype=np.int32), T.VARCHAR, FormatDict("Customer#", 9, n)
            ),
            "c_address": Column(
                np.arange(n, dtype=np.int32), T.VARCHAR, AddressDict(11, n)
            ),
            "c_nationkey": Column(nations.astype(np.int64), T.BIGINT),
            "c_phone": Column(
                np.arange(n, dtype=np.int32), T.VARCHAR, PhoneDict(23, n, nation_seed)
            ),
            "c_acctbal": _dec(rng.integers(-99999, 999999, n)),
            "c_mktsegment": _pool_col(rng, n, tuple(SEGMENTS)),
            "c_comment": _pool_col(rng, n, COMMENT_POOL),
        },
    )


def gen_orders_and_lineitem(sf: float) -> Tuple[Table, Table]:
    n_orders = int(1_500_000 * sf)
    n_cust = int(150_000 * sf)
    n_part = int(200_000 * sf)
    n_supp = int(10_000 * sf)
    rng = np.random.default_rng(6001)

    orderkey = np.arange(1, n_orders + 1, dtype=np.int64)
    # spec: only customers with custkey % 3 != 0 place orders
    raw = rng.integers(1, max(n_cust, 2), n_orders).astype(np.int64)
    custkey = raw + (raw % 3 == 0)  # bump multiples of 3
    custkey = np.where(custkey > n_cust, np.maximum(custkey - 3, 1), custkey)
    orderdate = rng.integers(STARTDATE, ENDDATE - 151 + 1, n_orders).astype(np.int32)

    # lineitems: 1..7 per order
    lines = rng.integers(1, 8, n_orders)
    total_lines = int(lines.sum())
    starts = np.concatenate([[0], np.cumsum(lines)[:-1]])
    l_orderkey = np.repeat(orderkey, lines)
    l_linenumber = (np.arange(total_lines) - np.repeat(starts, lines) + 1).astype(np.int64)
    l_partkey = rng.integers(1, n_part + 1, total_lines).astype(np.int64)
    supp_i = rng.integers(0, 4, total_lines).astype(np.int64)
    l_suppkey = _partsupp_suppkey(l_partkey, supp_i, n_supp)
    qty = rng.integers(1, 51, total_lines).astype(np.int64)
    l_quantity = qty * 100  # decimal(12,2)
    l_extendedprice = qty * retail_price_cents(l_partkey)
    l_discount = rng.integers(0, 11, total_lines).astype(np.int64)  # cents: 0.00-0.10
    l_tax = rng.integers(0, 9, total_lines).astype(np.int64)
    l_orderdate = np.repeat(orderdate, lines).astype(np.int64)
    l_shipdate = (l_orderdate + rng.integers(1, 122, total_lines)).astype(np.int32)
    l_commitdate = (l_orderdate + rng.integers(30, 91, total_lines)).astype(np.int32)
    l_receiptdate = (l_shipdate + rng.integers(1, 31, total_lines)).astype(np.int32)

    returned = l_receiptdate <= CURRENTDATE
    rf = np.where(returned, np.where(rng.random(total_lines) < 0.5, 0, 2), 1)
    rf_pool = ("A", "N", "R")  # codes 0,1,2 — sorted
    shipped = l_shipdate > CURRENTDATE
    ls_pool = ("F", "O")
    l_linestatus = shipped.astype(np.int32)  # O if shipped after current date

    # per-order rollups
    net = l_extendedprice * (100 - l_discount) // 100
    gross = net * (100 + l_tax) // 100
    o_totalprice = np.add.reduceat(gross, starts)
    o_count = lines
    o_f = np.add.reduceat((l_linestatus == 0).astype(np.int64), starts)
    o_status = np.where(o_f == o_count, 0, np.where(o_f == 0, 1, 2))
    status_pool = ("F", "O", "P")

    orders = Table(
        "orders",
        {
            "o_orderkey": Column(orderkey, T.BIGINT),
            "o_custkey": Column(custkey, T.BIGINT),
            "o_orderstatus": Column(o_status.astype(np.int32), T.VARCHAR, status_pool),
            "o_totalprice": _dec(o_totalprice),
            "o_orderdate": Column(orderdate, T.DATE),
            "o_orderpriority": _pool_col(rng, n_orders, tuple(PRIORITIES)),
            "o_clerk": Column(
                rng.integers(0, max(int(1000 * sf), 1), n_orders).astype(np.int32),
                T.VARCHAR,
                FormatDict("Clerk#", 9, max(int(1000 * sf), 1)),
            ),
            "o_shippriority": Column(np.zeros(n_orders, np.int64), T.BIGINT),
            "o_comment": _pool_col(rng, n_orders, COMMENT_POOL),
        },
    )
    lineitem = Table(
        "lineitem",
        {
            "l_orderkey": Column(l_orderkey, T.BIGINT),
            "l_partkey": Column(l_partkey, T.BIGINT),
            "l_suppkey": Column(l_suppkey, T.BIGINT),
            "l_linenumber": Column(l_linenumber, T.BIGINT),
            "l_quantity": _dec(l_quantity),
            "l_extendedprice": _dec(l_extendedprice),
            "l_discount": _dec(l_discount, scale=2, precision=4),
            "l_tax": _dec(l_tax, scale=2, precision=4),
            "l_returnflag": Column(rf.astype(np.int32), T.VARCHAR, rf_pool),
            "l_linestatus": Column(l_linestatus, T.VARCHAR, ls_pool),
            "l_shipdate": Column(l_shipdate, T.DATE),
            "l_commitdate": Column(l_commitdate, T.DATE),
            "l_receiptdate": Column(l_receiptdate, T.DATE),
            "l_shipinstruct": _pool_col(rng, total_lines, tuple(INSTRUCTIONS)),
            "l_shipmode": _pool_col(rng, total_lines, tuple(SHIPMODES)),
            "l_comment": _pool_col(rng, total_lines, COMMENT_POOL),
        },
    )
    return orders, lineitem


_CACHE: Dict[Tuple[str, float], Table] = {}


def table(name: str, sf: float = 1.0) -> Table:
    """Generate (and cache) a TPC-H table at the given scale factor."""
    key = (name, sf)
    if key in _CACHE:
        return _CACHE[key]
    if name == "region":
        t = gen_region()
    elif name == "nation":
        t = gen_nation()
    elif name == "supplier":
        t = gen_supplier(sf)
    elif name == "part":
        t = gen_part(sf)
    elif name == "partsupp":
        t = gen_partsupp(sf)
    elif name == "customer":
        t = gen_customer(sf)
    elif name in ("orders", "lineitem"):
        o, l = gen_orders_and_lineitem(sf)
        _CACHE[("orders", sf)] = o
        _CACHE[("lineitem", sf)] = l
        return _CACHE[key]
    else:
        raise KeyError(f"unknown tpch table {name!r}")
    _CACHE[key] = t
    return t


TABLE_NAMES = [
    "region", "nation", "supplier", "part", "partsupp",
    "customer", "orders", "lineitem",
]


def schema(name: str, sf: float = 1.0):
    """Column name -> Type mapping without forcing full generation for the
    big tables (generates small ones; uses a cached prototype otherwise)."""
    t = table(name, sf if name in ("region", "nation") else min(sf, 0.01))
    return {cname: c.type for cname, c in t.columns.items()}


# base cardinality per unit scale factor (spec §4.2.5); lineitem is ~6M/sf
_BASE_ROWS = {
    "region": 5,
    "nation": 25,
    "supplier": 10_000,
    "part": 200_000,
    "partsupp": 800_000,
    "customer": 150_000,
    "orders": 1_500_000,
    "lineitem": 6_000_000,
}

_UNIQUE_COLUMNS = {
    "region": [("r_regionkey",)],
    "nation": [("n_nationkey",)],
    "supplier": [("s_suppkey",)],
    "part": [("p_partkey",)],
    "partsupp": [("ps_partkey", "ps_suppkey")],
    "customer": [("c_custkey",)],
    "orders": [("o_orderkey",)],
    "lineitem": [("l_orderkey", "l_linenumber")],
}


class TpchCatalog:
    """Catalog + runtime data provider for the embedded TPC-H connector
    (reference presto-tpch: TpchMetadata + statistics provider). Implements
    the planner's Catalog protocol and serves device-resident Pages to the
    executor, cached per table."""

    name = "tpch"

    def __init__(self, sf: float = 1.0):
        self.sf = sf
        self._pages: Dict[str, "Page"] = {}
        self._tables: Dict[str, Table] = {}

    def table_names(self):
        return list(TABLE_NAMES)

    def schema(self, tname: str):
        return schema(tname, self.sf)

    def row_count(self, tname: str) -> int:
        if tname in ("region", "nation"):
            return _BASE_ROWS[tname]
        return int(_BASE_ROWS[tname] * self.sf)

    def unique_columns(self, tname: str):
        return _UNIQUE_COLUMNS.get(tname, [])

    def table_version(self, tname: str) -> int:
        """Generated data is immutable: a constant snapshot version makes
        every tpch read cacheable forever (exec/qcache.py)."""
        if tname not in TABLE_NAMES:
            raise KeyError(f"table {tname!r} does not exist")
        return 0

    def page(self, tname: str) -> "Page":
        """Full-table Page with SOURCE column names (executor renames to
        plan channels). Cached: repeated queries reuse device arrays."""
        pg = self._pages.get(tname)
        if pg is None:
            pg = self.host_table(tname).to_page()
            self._pages[tname] = pg
        return pg

    def host_table(self, tname: str) -> Table:
        """Host-resident (numpy) table, cached — the streaming scan source
        (reference ConnectorPageSource: data stays off-device until a split
        batch is requested)."""
        tb = self._tables.get(tname)
        if tb is None:
            tb = table(tname, self.sf)
            self._tables[tname] = tb
        return tb

    def exact_row_count(self, tname: str) -> int:
        return self.host_table(tname).num_rows

    def column_stats(self, tname: str, column: str):
        """Exact per-column statistics from the host-resident generator
        data (reference presto-tpch statistics provider), cached."""
        from ..plan.stats import stats_from_column

        cache = getattr(self, "_stats_cache", None)
        if cache is None:
            cache = self._stats_cache = {}
        key = (tname, column)
        if key not in cache:
            col = self.host_table(tname).columns[column]
            cache[key] = stats_from_column(
                col.data,
                getattr(col, "valid", None),
                col.type,
                col.dictionary,
                self.exact_row_count(tname),
            )
        return cache[key]

    def scan(self, tname: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None) -> "Page":
        """One batch of rows [start, stop) as a device Page — the split/
        morsel read path (reference BackgroundHiveSplitLoader splits +
        ConnectorPageSource.getNextPage). Honors column pushdown; the
        in-memory generator has no row-group statistics to prune by."""
        tb = self.host_table(tname)
        if columns is not None:
            tb = Table(tb.name, {c: tb.columns[c] for c in columns})
        return tb.to_page(start, stop, pad_to=pad_to)
