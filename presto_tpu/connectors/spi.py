"""Connector SPI — the contract between the engine and data sources.

Re-designed equivalent of the reference's connector SPI
(presto-spi/src/main/java/com/facebook/presto/spi/connector/ —
ConnectorMetadata, ConnectorSplitManager, ConnectorPageSource). TPU-first
reduction: a connector is ONE object serving both metadata and device
Pages; the "split" is a row range [start, stop) of a table (the morsel the
streaming driver schedules), and predicate/column pushdown arrives as
plain arguments instead of TupleDomain objects.

Metadata methods (planner-facing, reference ConnectorMetadata):
  table_names() -> [str]
  schema(table) -> {column: Type}
  row_count(table) -> int                  # statistics estimate
  unique_columns(table) -> [tuple]         # declared keys (n:1 joins)

Data methods (executor-facing, reference ConnectorPageSource):
  page(table) -> Page                      # whole table, device-resident
  exact_row_count(table) -> int            # TRUE row count (not the
      row_count estimate). Required for predicate pruning: the streaming
      driver otherwise detects end-of-table by a short batch, which a
      pruned batch would fake — without exact_row_count the engine drops
      the pruning hint entirely.
  scan(table, start, stop, pad_to=None, columns=None, predicate=None)
      -> Page                              # one batched split; MUST clamp
      stop to the true row count and may over-deliver rows that fail
      `predicate` (it is a pruning hint, not a filter — the engine always
      re-applies the real Filter)

`predicate` is a list of (column, op, value) conjuncts with op in
{'lt','le','gt','ge','eq','in'} and `value` a LOGICAL Python value
(datetime.date for DATE, float/Decimal for decimals, str for varchar,
int for integers — matching what file-format statistics expose, NOT the
engine's scaled storage units) — enough to prune row groups / partitions
by min-max statistics (reference TupleDomainOrcPredicate / Parquet
predicate pushdown). The 'in' op carries a tuple of logical values (from
IN-lists, OR-of-equals rewrites, and small-domain dynamic filters —
exec/dynfilter.py); a reader refutes it when NO value can fall inside the
unit's min/max range (and, where dictionary/value metadata is present,
when no value is actually in the unit).

The base class supplies scan() by slicing page() so minimal connectors
only implement metadata + page().
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .. import types as T
from ..page import Block, Page, _pad_block
from ..sql.planner import Catalog

Predicate = List[Tuple[str, str, object]]


class Connector(Catalog):
    """Base connector: metadata protocol from planner.Catalog + default
    batched scan over the materialized page."""

    def page(self, table: str) -> Page:
        raise NotImplementedError

    def exact_row_count(self, table: str) -> int:
        return int(self.page(table).count)

    def table_version(self, table: str) -> Optional[int]:
        """Monotonic snapshot version for `table`, bumped by every
        INSERT/DELETE/DDL through this connector, or None when the
        connector cannot observe data changes. The plan/result caches
        (exec/qcache.py) treat None as UNCACHEABLE — a connector without
        versioning can never serve a stale result. Immutable connectors
        (tpch/tpcds generators) return a constant."""
        return None

    # -- statistics (reference ConnectorMetadata.getTableStatistics /
    # spi/statistics/TableStatistics) --

    STATS_SAMPLE_ROWS = 1 << 18

    def column_stats(self, table: str, column: str):
        """NDV / logical min / logical max / null fraction for one column,
        computed from a bounded sample of the table and cached. NDV scales
        up linearly when the sample looks key-like (>50% distinct), the
        standard low/high-cardinality split; file connectors override this
        with format metadata where available."""
        cache = getattr(self, "_column_stats_cache", None)
        if cache is None:
            cache = self._column_stats_cache = {}
        key = (table, column)
        if key not in cache:
            cache[key] = self._compute_column_stats(table, column)
        return cache[key]

    def _compute_column_stats(self, table: str, column: str):
        import numpy as np

        from ..plan.stats import ColumnStats, stats_from_column

        total = self.exact_row_count(table)
        n = min(total, self.STATS_SAMPLE_ROWS)
        if n == 0:
            return ColumnStats(ndv=0.0, null_fraction=0.0)
        # STRIDED ranges, not a prefix: tables are often stored sorted by
        # key/date, and a prefix sample would systematically miss the top
        # of the range (wrecking range-selectivity estimates)
        pieces, vpieces = [], []
        n_ranges = 8 if total > n else 1
        span = max(n // n_ranges, 1)
        any_valid = False
        for start in np.linspace(0, max(total - span, 0), n_ranges).astype(
            np.int64
        ):
            page = self.scan(table, int(start), int(start) + span,
                             columns=[column])
            b = page.block(column)
            m = int(page.count)
            pieces.append(np.asarray(b.data[:m]))
            if b.valid is not None:
                any_valid = True
                vpieces.append(np.asarray(b.valid[:m]))
            else:
                vpieces.append(np.ones((m,), np.bool_))
        data = np.concatenate(pieces)
        valid = np.concatenate(vpieces) if any_valid else None
        return stats_from_column(data, valid, b.type, b.dictionary, total)

    def scan(
        self,
        table: str,
        start: int,
        stop: int,
        pad_to: Optional[int] = None,
        columns: Optional[List[str]] = None,
        predicate: Optional[Predicate] = None,
    ) -> Page:
        src = self.page(table)
        n = int(src.count)
        stop = min(stop, n)
        count = max(stop - start, 0)
        names = list(src.names) if columns is None else list(columns)
        blocks = []
        for name in names:
            b = src.block(name)
            data = b.data[start:stop]
            valid = None if b.valid is None else b.valid[start:stop]
            blk = Block(data, b.type, valid, b.dict_id)
            if pad_to is not None and pad_to > count:
                blk = _pad_block(blk, pad_to)
            blocks.append(blk)
        return Page.from_blocks(blocks, names, count=count)


class WriteError(RuntimeError):
    pass


class DeltaUnavailable(RuntimeError):
    """A connector's scan_delta() cannot reconstruct the requested seq
    range exactly (e.g. compaction merged already-consumed rows with
    unconsumed ones into a single shard). Callers treat this as "delta
    maintenance not possible right now" and fall back to full
    recompute — it is never a data-loss signal."""


class WritableConnector(Connector):
    """Write protocol (reference ConnectorPageSink / ConnectorMetadata
    beginCreateTable/beginInsert, presto-spi/.../spi/ConnectorPageSink.java).
    The engine's DDL/DML tasks (session.py) call these; read-only
    connectors simply don't subclass this and get a clean error."""

    def create_table(self, table: str, schema: Dict[str, T.Type]) -> None:
        raise NotImplementedError

    def create_table_from_page(self, table: str, page: Page) -> None:
        raise NotImplementedError

    def drop_table(self, table: str) -> None:
        raise NotImplementedError

    def append(self, table: str, page: Page) -> None:
        raise NotImplementedError

    def replace(self, table: str, page: Page) -> None:
        raise NotImplementedError
