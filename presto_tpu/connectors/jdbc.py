"""JDBC-federation connector framework over SQLite.

Re-designed equivalent of the reference's presto-base-jdbc (3,676 LoC:
BaseJdbcClient metadata/splits/SQL generation, QueryBuilder pushdown)
with presto-sqlite standing in for the thin vendor subclasses
(presto-mysql/-postgresql/-redshift/-sqlserver are ~150-320 LoC each on
top of the base). The external system here is a SQLite database file —
the one relational engine baked into this image — which exercises the
full federation surface:

* metadata from the remote catalog (sqlite_master + PRAGMA table_info);
* PROJECTION pushdown: only requested columns appear in generated SQL;
* PREDICATE pushdown: SPI hint conjuncts compile into the remote WHERE
  (reference QueryBuilder.buildSql); the engine still applies the full
  filter to delivered batches, so pushdown is a pure row-volume win;
* batched scans as LIMIT/OFFSET windows over a rowid-stable order (the
  reference's split ranges).

`MultiCatalog` federates several catalogs into one session namespace so
remote tables join against native ones (the reference achieves this with
per-catalog connector instances inside one metadata manager).
"""

from __future__ import annotations

import sqlite3
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page, _pad_block
from .spi import Connector, Predicate


_AFFINITY = {
    "INTEGER": T.BIGINT,
    "INT": T.BIGINT,
    "BIGINT": T.BIGINT,
    "SMALLINT": T.BIGINT,
    "TINYINT": T.BIGINT,
    "REAL": T.DOUBLE,
    "DOUBLE": T.DOUBLE,
    "FLOAT": T.DOUBLE,
    "NUMERIC": T.DOUBLE,
    "DECIMAL": T.DOUBLE,
    "TEXT": T.VARCHAR,
    "VARCHAR": T.VARCHAR,
    "CHAR": T.VARCHAR,
    "CLOB": T.VARCHAR,
    "BOOLEAN": T.BOOLEAN,
    "DATE": T.DATE,
}


def _decl_to_type(decl: Optional[str]) -> T.Type:
    if not decl:
        return T.VARCHAR
    head = decl.split("(")[0].strip().upper()
    for key, t in _AFFINITY.items():
        if key in head:
            return t
    return T.VARCHAR


class SqliteCatalog(Connector):
    """path: SQLite database file (or ':memory:' with an existing
    connection via `conn`)."""

    name = "sqlite"

    def __init__(self, path: str = ":memory:",
                 conn: Optional[sqlite3.Connection] = None):
        self.conn = conn or sqlite3.connect(path)
        self.query_log: List[str] = []  # generated remote SQL (tests/EXPLAIN)
        self._schemas: Dict[str, Dict[str, T.Type]] = {}
        self._dicts: Dict[Tuple[str, str], tuple] = {}

    def _exec(self, sql: str, params=()):
        self.query_log.append(sql)
        return self.conn.execute(sql, params)

    # -- metadata (reference BaseJdbcClient.getTableNames/getColumns) --

    def table_names(self) -> List[str]:
        cur = self._exec(
            "SELECT name FROM sqlite_master WHERE type = 'table' "
            "AND name NOT LIKE 'sqlite_%' ORDER BY name"
        )
        return [r[0] for r in cur.fetchall()]

    def schema(self, table: str) -> Dict[str, T.Type]:
        s = self._schemas.get(table)
        if s is None:
            cur = self._exec(f'PRAGMA table_info("{table}")')
            s = {r[1]: _decl_to_type(r[2]) for r in cur.fetchall()}
            if not s:
                raise KeyError(f"unknown remote table {table!r}")
            self._schemas[table] = s
        return dict(s)

    def row_count(self, table: str) -> int:
        return self._exec(f'SELECT count(*) FROM "{table}"').fetchone()[0]

    def exact_row_count(self, table: str) -> int:
        return self.row_count(table)

    def unique_columns(self, table: str):
        out = []
        # INTEGER PRIMARY KEY is the rowid alias — present in table_info's
        # pk column but absent from index_list
        pk = [
            r[1]
            for r in self._exec(f'PRAGMA table_info("{table}")').fetchall()
            if r[5]
        ]
        if len(pk) == 1:
            out.append((pk[0],))
        for r in self._exec(f'PRAGMA index_list("{table}")').fetchall():
            if r[2]:  # unique index
                cols = [
                    c[2]
                    for c in self._exec(
                        f'PRAGMA index_info("{r[1]}")'
                    ).fetchall()
                ]
                out.append(tuple(cols))
        return out

    # -- SQL generation (reference QueryBuilder) --

    @staticmethod
    def _compile_predicate(
        predicate: Optional[Predicate], schema: Dict[str, T.Type]
    ) -> Tuple[str, list]:
        if not predicate:
            return "", []
        ops = {"eq": "=", "lt": "<", "le": "<=", "gt": ">", "ge": ">="}
        clauses, params = [], []
        for col, op, v in predicate:
            if col not in schema or op not in ops:
                continue
            if hasattr(v, "isoformat"):  # datetime.date
                v = v.isoformat()
            if not isinstance(v, (int, float, str)):
                continue
            clauses.append(f'"{col}" {ops[op]} ?')
            params.append(v)
        return (" WHERE " + " AND ".join(clauses), params) if clauses else ("", [])

    def _dictionary(self, table: str, column: str):
        key = (table, column)
        d = self._dicts.get(key)
        if d is None:
            cur = self._exec(
                f'SELECT DISTINCT "{column}" FROM "{table}" '
                f'WHERE "{column}" IS NOT NULL'
            )
            entries = tuple(sorted(str(r[0]) for r in cur.fetchall()))
            d = (entries, np.array(entries, object))
            self._dicts[key] = d
        return d

    # -- data --

    def page(self, table: str) -> Page:
        return self.scan(table, 0, self.row_count(table))

    def scan(
        self,
        table: str,
        start: int,
        stop: int,
        pad_to: Optional[int] = None,
        columns: Optional[List[str]] = None,
        predicate: Optional[Predicate] = None,
    ) -> Page:
        schema = self.schema(table)
        names = list(columns) if columns is not None else list(schema)
        where, params = self._compile_predicate(predicate, schema)
        col_sql = ", ".join(f'"{c}"' for c in names)
        limit = max(stop - start, 0)
        sql = (
            f'SELECT {col_sql} FROM "{table}"{where} '
            f"ORDER BY rowid LIMIT {limit} OFFSET {start}"
        )
        rows = self._exec(sql, params).fetchall()
        return self._rows_to_page(table, rows, names, schema, pad_to)

    def supports_index(self, table: str, column: str) -> bool:
        """True when the remote side can serve point lookups on `column`
        (the ConnectorIndex capability, reference spi ConnectorResolvedIndex
        + operator/index/IndexLoader): any indexed or primary-key column."""
        for cols in self.unique_columns(table):
            if cols == (column,):
                return True
        for r in self._exec(f'PRAGMA index_list("{table}")').fetchall():
            cols = [
                c[2]
                for c in self._exec(f'PRAGMA index_info("{r[1]}")').fetchall()
            ]
            if cols == [column]:
                return True
        return False

    def index_lookup(self, table: str, column: str, keys, columns):
        """Rows whose `column` is in `keys` — the index-join fetch
        (reference IndexLoader.streamIndexDataForSingleKey): generated SQL
        uses IN batches instead of a full scan."""
        schema = self.schema(table)
        names = list(columns) if columns is not None else list(schema)
        col_sql = ", ".join(f'"{c}"' for c in names)
        rows = []
        ks = list(keys)
        for i in range(0, len(ks), 500):  # SQLite bind-parameter budget
            chunk = ks[i : i + 500]
            marks = ", ".join("?" * len(chunk))
            rows.extend(
                self._exec(
                    f'SELECT {col_sql} FROM "{table}" '
                    f'WHERE "{column}" IN ({marks})',
                    [k.item() if hasattr(k, "item") else k for k in chunk],
                ).fetchall()
            )
        return self._rows_to_page(table, rows, names, schema, None)

    def _rows_to_page(self, table, rows, names, schema, pad_to):
        n = len(rows)
        blocks = []
        for i, c in enumerate(names):
            t = schema[c]
            vals = [r[i] for r in rows]
            valid = np.array([v is not None for v in vals], bool)
            if isinstance(t, T.VarcharType):
                strs = np.array(
                    [str(v) if v is not None else "" for v in vals], object
                )
                for attempt in (0, 1):
                    sorted_d, d_arr = self._dictionary(table, c)
                    data = np.searchsorted(d_arr, strs).astype(np.int32)
                    data = np.clip(data, 0, max(len(sorted_d) - 1, 0))
                    miss = valid & (
                        d_arr[data] != strs
                        if len(sorted_d)
                        else np.ones(len(strs), bool)
                    )
                    if not miss.any():
                        break
                    # the cached dictionary predates remotely-inserted
                    # values: rebuild once rather than silently assigning
                    # a wrong code (round-4 advisor)
                    self._dicts.pop((table, c), None)
                    if attempt:
                        raise LookupError(
                            f"varchar value absent from {table}.{c} "
                            "dictionary after rebuild"
                        )
                blk = Block.from_numpy(
                    data, t,
                    valid=None if valid.all() else valid,
                    dictionary=sorted_d or ("",),
                )
            elif isinstance(t, T.DateType):
                import datetime as pydt

                days = np.array(
                    [
                        (pydt.date.fromisoformat(v) - pydt.date(1970, 1, 1)).days
                        if isinstance(v, str)
                        else (v if v is not None else 0)
                        for v in vals
                    ],
                    np.int32,
                )
                blk = Block.from_numpy(
                    days, t, valid=None if valid.all() else valid
                )
            elif isinstance(t, T.DoubleType):
                data = np.array(
                    [float(v) if v is not None else 0.0 for v in vals],
                    np.float64,
                )
                blk = Block.from_numpy(
                    data, t, valid=None if valid.all() else valid
                )
            elif isinstance(t, T.BooleanType):
                data = np.array(
                    [bool(v) if v is not None else False for v in vals], bool
                )
                blk = Block.from_numpy(
                    data, t, valid=None if valid.all() else valid
                )
            else:
                data = np.array(
                    [int(v) if v is not None else 0 for v in vals], np.int64
                )
                blk = Block.from_numpy(
                    data, t, valid=None if valid.all() else valid
                )
            if pad_to is not None and pad_to > n:
                blk = _pad_block(blk, pad_to)
            blocks.append(blk)
        return Page.from_blocks(blocks, names, count=n)


class MultiCatalog(Connector):
    """Federates member catalogs into one flat session namespace
    (collisions resolve to the FIRST member; the reference mounts each
    connector under its own catalog name inside MetadataManager —
    flat-name federation is the minimal equivalent for joins across
    systems)."""

    name = "federated"

    def __init__(self, members: List[Connector]):
        self.members = list(members)

    def _owner(self, table: str) -> Connector:
        for m in self.members:
            if table in m.table_names():
                return m
        raise KeyError(f"unknown table {table!r}")

    def table_names(self) -> List[str]:
        out: List[str] = []
        for m in self.members:
            for t in m.table_names():
                if t not in out:
                    out.append(t)
        return out

    def schema(self, table: str):
        return self._owner(table).schema(table)

    def row_count(self, table: str) -> int:
        return self._owner(table).row_count(table)

    def exact_row_count(self, table: str) -> int:
        return self._owner(table).exact_row_count(table)

    def unique_columns(self, table: str):
        return self._owner(table).unique_columns(table)

    def column_stats(self, table: str, column: str):
        return self._owner(table).column_stats(table, column)

    def page(self, table: str) -> Page:
        return self._owner(table).page(table)

    def scan(self, table: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None):
        return self._owner(table).scan(
            table, start, stop, pad_to=pad_to, columns=columns,
            predicate=predicate,
        )

    def supports_index(self, table: str, column: str) -> bool:
        m = self._owner(table)
        fn = getattr(m, "supports_index", None)
        return bool(fn and fn(table, column))

    def index_lookup(self, table: str, column: str, keys, columns):
        return self._owner(table).index_lookup(table, column, keys, columns)
