"""ORC connector: stripe-batched reads -> device Pages.

Re-designed equivalent of the reference's ORC reader stack (presto-orc/
OrcReader + StripeReader + per-column StreamReaders,
orc/OrcRecordReader.java:70) collapsed the same way as the parquet
connector: pyarrow.orc decodes stripes on the host, the shared
arrow_table_to_page maps them onto the engine's Block layout (dictionary
strings over a cached file-level sorted dictionary, decimal128 as two
lanes). The scan maps row ranges onto stripes (the stripe is the ORC
row-group analog); pyarrow exposes no per-stripe column statistics, so
predicate hints are accepted but not used for pruning.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..page import Page
from .parquet import (FileWriteMixin, _arrow_to_type,
                      arrow_table_to_page)
from .spi import Connector, Predicate, WritableConnector, WriteError


class OrcCatalog(FileWriteMixin, WritableConnector):
    """tables: {name: orc file path}. With `directory` set, the catalog is
    WRITABLE: CREATE TABLE / CTAS / INSERT / DELETE produce ORC files
    under it (reference: presto-orc writer + OrcWriteValidation — pyarrow
    is the bootstrap encoder, matching the read path)."""

    name = "orc"
    _ext = "orc"

    def __init__(self, tables: Dict[str, str],
                 unique: Optional[Dict[str, list]] = None,
                 directory: Optional[str] = None):
        from pyarrow import orc

        self.paths = dict(tables)
        self.unique = unique or {}
        self.directory = directory
        self._files: Dict[str, object] = {}
        self._dicts: Dict[Tuple[str, str], tuple] = {}
        self._orc = orc

    def _file(self, table: str):
        f = self._files.get(table)
        if f is None:
            f = self._orc.ORCFile(self.paths[table])
            self._files[table] = f
        return f

    def _encode_write(self, arrow_table, path: str) -> None:
        self._orc.write_table(arrow_table, path)

    def _read_all(self, table: str):
        return self._file(table).read()

    # -- metadata --

    def table_names(self) -> List[str]:
        return list(self.paths)

    def schema(self, table: str) -> Dict[str, T.Type]:
        sch = self._file(table).schema
        return {f.name: _arrow_to_type(f.type) for f in sch}

    def row_count(self, table: str) -> int:
        return self._file(table).nrows

    def exact_row_count(self, table: str) -> int:
        return self._file(table).nrows

    def unique_columns(self, table: str):
        return self.unique.get(table, [])

    # -- dictionaries (file-level, sorted, cached) --

    def _dictionary(self, table: str, column: str):
        from .parquet import build_sorted_dictionary

        key = (table, column)
        d = self._dicts.get(key)
        if d is None:
            col = self._file(table).read(columns=[column]).column(0)
            d = build_sorted_dictionary(col)
            self._dicts[key] = d
        return d

    # -- data --

    def page(self, table: str) -> Page:
        return self.scan(table, 0, self.row_count(table))

    def scan(
        self,
        table: str,
        start: int,
        stop: int,
        pad_to: Optional[int] = None,
        columns: Optional[List[str]] = None,
        predicate: Optional[Predicate] = None,
    ) -> Page:
        import pyarrow as pa

        f = self._file(table)
        stop = min(stop, f.nrows)
        names = columns or [fld.name for fld in f.schema]
        if start >= stop:  # out-of-range split: nothing to decode
            tb = f.schema.empty_table().select(names)
            return arrow_table_to_page(
                tb, names, 0, pad_to,
                lambda name: self._dictionary(table, name),
            )
        # map [start, stop) onto stripes
        pieces = []
        offset = 0
        for s in range(f.nstripes):
            if offset >= stop:
                break
            # pyarrow exposes stripe boundaries only by reading; stripes
            # before `start` are read and dropped (no stripe metadata API)
            st = f.read_stripe(s, columns=names)
            s_start, s_stop = offset, offset + st.num_rows
            offset = s_stop
            if s_stop <= start:
                continue
            lo = max(start - s_start, 0)
            hi = min(stop - s_start, st.num_rows)
            if hi > lo:
                pieces.append(st.slice(lo, hi - lo))
        if pieces:
            tb = pa.Table.from_batches(pieces)
        else:
            tb = f.read(columns=names).slice(0, 0)
        return arrow_table_to_page(
            tb, names, tb.num_rows, pad_to,
            lambda name: self._dictionary(table, name),
        )


def write_table_orc(page, path: str, stripe_size: int = 1 << 16):
    """Engine Page -> ORC file (test fixture / writer seed)."""
    from pyarrow import orc

    from .parquet import page_to_arrow

    orc.write_table(page_to_arrow(page), path, stripe_size=stripe_size)
