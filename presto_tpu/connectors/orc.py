"""ORC connector: stripe-batched reads -> device Pages.

Re-designed equivalent of the reference's ORC reader stack (presto-orc/
OrcReader + StripeReader + per-column StreamReaders,
orc/OrcRecordReader.java:70) collapsed the same way as the parquet
connector: pyarrow.orc decodes stripes on the host, the shared
arrow_table_to_page maps them onto the engine's Block layout (dictionary
strings over a cached file-level sorted dictionary, decimal128 as two
lanes).

Stripe statistics + pruning (reference TupleDomainOrcPredicate +
StripeReader's row-group index): pyarrow's Python API exposes stripe
COUNTS but not their column statistics, so the connector maintains a
`<file>.stats.json` SIDECAR — per-stripe row counts and column min/max,
written alongside files this catalog writes and derived once (then
cached) for foreign files. scan() uses it twice: stripe offsets come
from the sidecar (no decode of pre-range stripes), and stripes whose
min/max refute a predicate hint are skipped entirely.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..page import Page
from .parquet import (FileWriteMixin, _arrow_to_type,
                      arrow_table_to_page)
from .spi import Connector, Predicate, WritableConnector, WriteError


class OrcCatalog(FileWriteMixin, WritableConnector):
    """tables: {name: orc file path}. With `directory` set, the catalog is
    WRITABLE: CREATE TABLE / CTAS / INSERT / DELETE produce ORC files
    under it (reference: presto-orc writer + OrcWriteValidation — pyarrow
    is the bootstrap encoder, matching the read path)."""

    name = "orc"
    _ext = "orc"

    def __init__(self, tables: Dict[str, str],
                 unique: Optional[Dict[str, list]] = None,
                 directory: Optional[str] = None):
        from pyarrow import orc

        self.paths = dict(tables)
        self.unique = unique or {}
        self.directory = directory
        self._files: Dict[str, object] = {}
        self._dicts: Dict[Tuple[str, str], tuple] = {}
        self._orc = orc

    def _file(self, table: str):
        f = self._files.get(table)
        if f is None:
            f = self._orc.ORCFile(self.paths[table])
            self._files[table] = f
        return f

    def _invalidate(self, table: str) -> None:
        super()._invalidate(table)
        cache = getattr(self, "_stripe_stats_cache", None)
        if cache is not None:
            cache.pop(table, None)

    def _encode_write(self, arrow_table, path: str) -> None:
        self._orc.write_table(arrow_table, path)
        # emit the stripe-statistics sidecar with the file, so readers
        # never pay the derive-by-reading pass for files we wrote
        import json

        try:
            stats = _derive_stripe_stats(self._orc.ORCFile(path))
            with open(path + ".stats.json", "w") as fh:
                json.dump(stats, fh)
        except OSError:
            pass

    def _read_all(self, table: str):
        return self._file(table).read()

    # -- metadata --

    def table_names(self) -> List[str]:
        return list(self.paths)

    def schema(self, table: str) -> Dict[str, T.Type]:
        sch = self._file(table).schema
        return {f.name: _arrow_to_type(f.type) for f in sch}

    def row_count(self, table: str) -> int:
        return self._file(table).nrows

    def exact_row_count(self, table: str) -> int:
        return self._file(table).nrows

    def unique_columns(self, table: str):
        return self.unique.get(table, [])

    # -- dictionaries (file-level, sorted, cached) --

    def _dictionary(self, table: str, column: str):
        from .parquet import build_sorted_dictionary

        key = (table, column)
        d = self._dicts.get(key)
        if d is None:
            col = self._file(table).read(columns=[column]).column(0)
            d = build_sorted_dictionary(col)
            self._dicts[key] = d
        return d

    # -- data --

    def page(self, table: str) -> Page:
        return self.scan(table, 0, self.row_count(table))

    def _stats_path(self, table: str) -> str:
        return self.paths[table] + ".stats.json"

    def stripe_stats(self, table: str) -> List[dict]:
        """[{rows, min: {col: v}, max: {col: v}}, ...] per stripe, from
        the sidecar (written by our writer / derived once for foreign
        files). Values are JSON-native; dates serialize as ISO strings,
        which order correctly under string comparison."""
        cache = getattr(self, "_stripe_stats_cache", None)
        if cache is None:
            cache = self._stripe_stats_cache = {}
        got = cache.get(table)
        if got is not None:
            return got
        import json
        import os

        path = self.paths[table]
        side = self._stats_path(table)
        if os.path.exists(side) and os.path.getmtime(side) >= os.path.getmtime(path):
            with open(side) as fh:
                got = json.load(fh)
        else:
            got = _derive_stripe_stats(self._orc.ORCFile(path))
            try:
                with open(side, "w") as fh:
                    json.dump(got, fh)
            except OSError:
                pass  # read-only location: keep in memory only
        cache[table] = got
        return got

    @staticmethod
    def _stripe_refuted(st: dict, predicate: Predicate) -> bool:
        """True when the stripe's min/max refute ANY conjunct (reference
        TupleDomainOrcPredicate.matches)."""
        import decimal as _dec

        def numeric_bound(b, v):
            # decimal bounds are stored as exact strings; re-parse them
            # when compared against a numeric hint value
            if isinstance(b, str) and isinstance(
                v, (int, float, _dec.Decimal)
            ):
                try:
                    return _dec.Decimal(b)
                except _dec.InvalidOperation:
                    return b
            return b

        def canon(v):
            if hasattr(v, "isoformat"):
                return v.isoformat()
            if isinstance(v, bool):
                return int(v)
            return v

        for col, op, value in predicate:
            mn = st["min"].get(col)
            mx = st["max"].get(col)
            if mn is None or mx is None:
                continue
            if op == "in":
                if not value:
                    return True  # empty IN-list matches nothing
                try:
                    vals = [canon(v) for v in value]
                    if vals and all(
                        v < numeric_bound(mn, v) or v > numeric_bound(mx, v)
                        for v in vals
                    ):
                        return True
                except TypeError:
                    pass  # incomparable: keep the stripe
                continue
            value = canon(value)
            mn = numeric_bound(mn, value)
            mx = numeric_bound(mx, value)
            try:
                if op == "eq" and (value < mn or value > mx):
                    return True
                if op == "lt" and mn >= value:
                    return True
                if op == "le" and mn > value:
                    return True
                if op == "gt" and mx <= value:
                    return True
                if op == "ge" and mx < value:
                    return True
            except TypeError:
                continue  # incomparable: keep the stripe
        return False

    def scan(
        self,
        table: str,
        start: int,
        stop: int,
        pad_to: Optional[int] = None,
        columns: Optional[List[str]] = None,
        predicate: Optional[Predicate] = None,
    ) -> Page:
        import pyarrow as pa

        f = self._file(table)
        stop = min(stop, f.nrows)
        names = columns or [fld.name for fld in f.schema]
        if start >= stop:  # out-of-range split: nothing to decode
            tb = f.schema.empty_table().select(names)
            return arrow_table_to_page(
                tb, names, 0, pad_to,
                lambda name: self._dictionary(table, name),
            )
        stats = self.stripe_stats(table)
        pieces = []
        offset = 0
        read = skipped = 0
        for s, st in enumerate(stats):
            s_start, s_stop = offset, offset + st["rows"]
            offset = s_stop
            if s_stop <= start or s_start >= stop:
                continue
            if predicate and self._stripe_refuted(st, predicate):
                skipped += 1
                continue
            read += 1
            tbl = f.read_stripe(s, columns=names)
            lo = max(start - s_start, 0)
            hi = min(stop - s_start, tbl.num_rows)
            if hi > lo:
                pieces.append(tbl.slice(lo, hi - lo))
        # pruning observability (stream executor surfaces these counters
        # in EXPLAIN ANALYZE; units here are STRIPES)
        self.last_scan_files_read = read
        self.last_scan_files_skipped = skipped
        if pieces:
            tb = pa.Table.from_batches(pieces)
        else:
            # every overlapping stripe pruned: schema-only empty table
            tb = f.schema.empty_table().select(names)
        return arrow_table_to_page(
            tb, names, tb.num_rows, pad_to,
            lambda name: self._dictionary(table, name),
        )


def _derive_stripe_stats(f) -> List[dict]:
    """Read each stripe once and record rows + per-column min/max for
    primitive columns (the sidecar payload)."""
    import pyarrow.compute as pc

    out = []
    for s in range(f.nstripes):
        tbl = f.read_stripe(s)
        mins: Dict[str, object] = {}
        maxs: Dict[str, object] = {}
        for name in tbl.schema.names:
            col = tbl.column(name) if hasattr(tbl, "column") else None
            try:
                mm = pc.min_max(col)
                mn = mm["min"].as_py()
                mx = mm["max"].as_py()
            except Exception:  # noqa: BLE001 - non-orderable column
                continue
            for label, v in (("min", mn), ("max", mx)):
                if v is None:
                    continue
                if hasattr(v, "isoformat"):
                    v = v.isoformat()
                elif str(type(v).__name__) == "Decimal":
                    # floats would round the bound and could prune stripes
                    # containing boundary rows — keep decimals exact; the
                    # comparator re-parses (hints carry Decimal values)
                    v = str(v)
                elif isinstance(v, (bytes, bytearray)):
                    continue
                (mins if label == "min" else maxs)[name] = v
        out.append({"rows": tbl.num_rows, "min": mins, "max": maxs})
    return out


def write_table_orc(page, path: str, stripe_size: int = 1 << 16):
    """Engine Page -> ORC file (test fixture / writer seed)."""
    from pyarrow import orc

    from .parquet import page_to_arrow

    orc.write_table(page_to_arrow(page), path, stripe_size=stripe_size)
