"""Blackhole connector — the write-sink / benchmarking catalog.

Re-designed equivalent of presto-blackhole (BlackHoleMetadata +
BlackHolePageSinkProvider): INSERT/CTAS accept and DISCARD rows at full
speed (the standard sink for write-path benchmarking), reads return
empty pages, and tables are metadata-only. Optionally a table can be
configured to SYNTHESIZE rows on scan (the reference's split/page/row
properties collapsed to one `rows` knob) so read benchmarks need no
storage either: columns are zeros/empty strings generated on device.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import types as T
from ..page import Block, Page, intern_dictionary
from .spi import WritableConnector, WriteError


class BlackHoleCatalog(WritableConnector):
    name = "blackhole"

    def __init__(self, synthetic_rows: Optional[Dict[str, int]] = None):
        self._schemas: Dict[str, Dict[str, T.Type]] = {}
        self.rows_written: Dict[str, int] = {}
        # table -> row count to synthesize on scan (0 = plain sink)
        self.synthetic_rows = dict(synthetic_rows or {})

    # -- metadata --

    def table_names(self) -> List[str]:
        return sorted(self._schemas)

    def schema(self, table: str) -> Dict[str, T.Type]:
        try:
            return dict(self._schemas[table])
        except KeyError:
            raise KeyError(f"table {table!r} does not exist")

    def row_count(self, table: str) -> int:
        return self.synthetic_rows.get(table, 0)

    def exact_row_count(self, table: str) -> int:
        return self.row_count(table)

    def unique_columns(self, table: str):
        return []

    # -- reads: empty (or synthesized zeros) --

    def page(self, table: str) -> Page:
        schema = self.schema(table)
        n = self.synthetic_rows.get(table, 0)
        blocks = {}
        for c, t in schema.items():
            if isinstance(t, T.VarcharType):
                did = intern_dictionary(("",))
                blocks[c] = Block(
                    np.zeros(max(n, 1), np.int32), t, None, did
                )
            else:
                blocks[c] = Block(
                    np.zeros(
                        (max(n, 1), 2) if (
                            isinstance(t, T.DecimalType) and t.is_long
                        ) else max(n, 1),
                        t.storage_dtype,
                    ),
                    t,
                    None,
                )
        pg = Page.from_dict(blocks)
        return Page(pg.blocks, pg.names, n)

    # -- writes: discard --

    def create_table(self, table: str, schema: Dict[str, T.Type]) -> None:
        if table in self._schemas:
            raise WriteError(f"table {table!r} already exists")
        self._schemas[table] = dict(schema)
        self.rows_written[table] = 0

    def create_table_from_page(self, table: str, page: Page) -> None:
        self.create_table(
            table, {c: b.type for c, b in zip(page.names, page.blocks)}
        )
        self.append(table, page)

    def append(self, table: str, page: Page) -> None:
        self.schema(table)
        self.rows_written[table] = (
            self.rows_written.get(table, 0) + int(page.count)
        )

    def replace(self, table: str, page: Page) -> None:
        self.schema(table)
        self.rows_written[table] = int(page.count)

    def drop_table(self, table: str) -> None:
        self._schemas.pop(table, None)
        self.rows_written.pop(table, None)
        self.synthetic_rows.pop(table, None)
