"""system.runtime tables: cluster introspection via SQL.

Re-designed equivalent of the reference's system connector
(presto-main/.../connector/system/ — SystemTablesMetadata,
QuerySystemTable, NodeSystemTable; `select * from system.runtime.queries`).
A wrapper catalog routes `system.runtime.*` names to live snapshots built
from the coordinator's QueryManager / cluster NodeManager, and everything
else to the wrapped user catalog.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page
from .spi import Connector

QUERIES = "system.runtime.queries"
NODES = "system.runtime.nodes"


def _varchar(values: List[Optional[str]]) -> Block:
    return Block.from_strings(values if values else [None])


def _queries_page(manager) -> Page:
    infos = sorted(manager.list_queries(), key=lambda i: i.query_id)
    n = len(infos)
    if n == 0:
        from ..ops.union import empty_page

        return empty_page(_QUERIES_SCHEMA)
    now = __import__("time").time()
    return Page.from_dict(
        {
            "query_id": _varchar([i.query_id for i in infos]),
            "state": _varchar([i.state for i in infos]),
            "user": _varchar([i.user for i in infos]),
            "source": _varchar([i.source for i in infos]),
            "query": _varchar([i.sql for i in infos]),
            "elapsed_s": (
                np.array(
                    [(i.finished_at or now) - i.created_at for i in infos],
                    np.float64,
                ),
                T.DOUBLE,
            ),
            "output_rows": (
                np.array(
                    [
                        len(i.rows) if i.rows is not None else -1
                        for i in infos
                    ],
                    np.int64,
                ),
                T.BIGINT,
            ),
            "error": _varchar(
                [
                    i.error.strip().split("\n")[-1][:200] if i.error else None
                    for i in infos
                ]
            ),
        }
    )


def _nodes_page(node_manager, self_uri: Optional[str]) -> Page:
    rows: List[Tuple[str, str, str]] = []
    if self_uri is not None:
        rows.append((self_uri, "ACTIVE", "true"))
    if node_manager is not None:
        for uri, state in node_manager.workers.items():
            rows.append((uri, state["state"], "false"))
    if not rows:
        rows.append(("unknown", "ACTIVE", "true"))
    return Page.from_dict(
        {
            "node_id": _varchar([r[0] for r in rows]),
            "state": _varchar([r[1] for r in rows]),
            "coordinator": _varchar([r[2] for r in rows]),
        }
    )


_QUERIES_SCHEMA: Dict[str, T.Type] = {
    "query_id": T.VARCHAR, "state": T.VARCHAR, "user": T.VARCHAR,
    "source": T.VARCHAR, "query": T.VARCHAR, "elapsed_s": T.DOUBLE,
    "output_rows": T.BIGINT, "error": T.VARCHAR,
}
_NODES_SCHEMA: Dict[str, T.Type] = {
    "node_id": T.VARCHAR, "state": T.VARCHAR, "coordinator": T.VARCHAR,
}


class SystemCatalog(Connector):
    """Routes system.runtime.* to live snapshots, everything else to the
    wrapped catalog. `manager`/`node_manager` are late-bound attributes —
    the coordinator sets them after construction (QueryManager needs a
    session, whose catalog is this object)."""

    def __init__(self, wrapped, manager=None, node_manager=None,
                 self_uri: Optional[str] = None):
        self.wrapped = wrapped
        self.manager = manager
        self.node_manager = node_manager
        self.self_uri = self_uri

    @property
    def name(self):
        return getattr(self.wrapped, "name", "catalog")

    # -- metadata --

    def table_names(self) -> List[str]:
        return list(self.wrapped.table_names()) + [QUERIES, NODES]

    def schema(self, table: str):
        if table == QUERIES:
            return dict(_QUERIES_SCHEMA)
        if table == NODES:
            return dict(_NODES_SCHEMA)
        return self.wrapped.schema(table)

    def row_count(self, table: str) -> int:
        if table == QUERIES:
            return len(self.manager.list_queries()) if self.manager else 0
        if table == NODES:
            return 1
        return self.wrapped.row_count(table)

    def unique_columns(self, table: str):
        if table in (QUERIES, NODES):
            return []
        return self.wrapped.unique_columns(table)

    # -- data --

    def page(self, table: str) -> Page:
        if table == QUERIES:
            return _queries_page(self.manager)
        if table == NODES:
            return _nodes_page(self.node_manager, self.self_uri)
        return self.wrapped.page(table)

    def exact_row_count(self, table: str) -> int:
        if table in (QUERIES, NODES):
            return int(self.page(table).count)
        return self.wrapped.exact_row_count(table)

    def scan(self, table: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None) -> Page:
        if table in (QUERIES, NODES):
            return Connector.scan(
                self, table, start, stop, pad_to=pad_to, columns=columns
            )
        return self.wrapped.scan(
            table, start, stop, pad_to=pad_to, columns=columns,
            predicate=predicate,
        )

    # -- write passthrough (DDL/DML on the user catalog) --

    def __getattr__(self, item):
        # create_table/append/... delegate when the wrapped catalog is
        # writable; AttributeError otherwise, as for any read-only catalog
        return getattr(self.wrapped, item)
