"""system.runtime tables: cluster introspection via SQL.

Re-designed equivalent of the reference's system connector
(presto-main/.../connector/system/ — SystemTablesMetadata,
QuerySystemTable, NodeSystemTable; `select * from system.runtime.queries`).
A wrapper catalog routes `system.runtime.*` names to live snapshots built
from the coordinator's QueryManager / cluster NodeManager, and everything
else to the wrapped user catalog.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page
from .spi import Connector

QUERIES = "system.runtime.queries"
NODES = "system.runtime.nodes"
MATERIALIZED_VIEWS = "system.runtime.materialized_views"
# the unified observability plane (presto_tpu/obs/): every metric
# sample the /v1/metrics scrape would return, and every span of the
# recently kept query traces, queryable as SQL
METRICS = "system.runtime.metrics"
TASKS = "system.runtime.tasks"
# jmx-analog runtime metrics (reference presto-jmx connector exposing
# the JVM's Runtime/Memory/OperatingSystem MBeans as tables): the
# process table is this interpreter's runtime MBean, the memory table
# the device/host pool gauges a JVM would publish per memory pool
JMX_PROCESS = "system.jmx.process"
JMX_MEMORY = "system.jmx.memory"
# history-based adaptive execution (plan/history.py): every live
# feedback-store entry — semantic plan-frame fingerprint, observed vs
# estimated cardinality, hybrid-join partition memory — as a table
PLAN_HISTORY = "system.runtime.plan_history"


def _varchar(values: List[Optional[str]]) -> Block:
    return Block.from_strings(values if values else [None])


def _queries_page(manager) -> Page:
    infos = sorted(manager.list_queries(), key=lambda i: i.query_id)
    n = len(infos)
    if n == 0:
        from ..ops.union import empty_page

        return empty_page(_QUERIES_SCHEMA)
    now = __import__("time").time()
    return Page.from_dict(
        {
            "query_id": _varchar([i.query_id for i in infos]),
            "state": _varchar([i.state for i in infos]),
            "user": _varchar([i.user for i in infos]),
            "source": _varchar([i.source for i in infos]),
            "query": _varchar([i.sql for i in infos]),
            "elapsed_s": (
                np.array(
                    [(i.finished_at or now) - i.created_at for i in infos],
                    np.float64,
                ),
                T.DOUBLE,
            ),
            "output_rows": (
                np.array(
                    [
                        len(i.rows) if i.rows is not None else -1
                        for i in infos
                    ],
                    np.int64,
                ),
                T.BIGINT,
            ),
            "error": _varchar(
                [
                    i.error.strip().split("\n")[-1][:200] if i.error else None
                    for i in infos
                ]
            ),
        }
    )


def _nodes_page(node_manager, self_uri: Optional[str]) -> Page:
    rows: List[Tuple[str, str, str]] = []
    if self_uri is not None:
        rows.append((self_uri, "ACTIVE", "true"))
    if node_manager is not None:
        for uri, state in node_manager.workers.items():
            rows.append((uri, state["state"], "false"))
    if not rows:
        rows.append(("unknown", "ACTIVE", "true"))
    return Page.from_dict(
        {
            "node_id": _varchar([r[0] for r in rows]),
            "state": _varchar([r[1] for r in rows]),
            "coordinator": _varchar([r[2] for r in rows]),
        }
    )


def _process_page() -> Page:
    import os
    import resource
    import threading
    import time as _t

    ru = resource.getrusage(resource.RUSAGE_SELF)
    import jax

    backend = jax.default_backend()
    return Page.from_dict(
        {
            "pid": (np.array([os.getpid()], np.int64), T.BIGINT),
            "rss_bytes": (
                np.array([ru.ru_maxrss * 1024], np.int64), T.BIGINT,
            ),
            "user_time_s": (
                np.array([ru.ru_utime], np.float64), T.DOUBLE,
            ),
            "system_time_s": (
                np.array([ru.ru_stime], np.float64), T.DOUBLE,
            ),
            "threads": (
                np.array([threading.active_count()], np.int64), T.BIGINT,
            ),
            "backend": _varchar([backend]),
            "devices": (
                np.array([len(jax.devices())], np.int64), T.BIGINT,
            ),
            "uptime_hint_s": (
                np.array([_t.process_time()], np.float64), T.DOUBLE,
            ),
        }
    )


def _memory_page(memory_manager, node_manager) -> Page:
    """One row per known memory pool: the coordinator's cluster view
    (worker /v1/memory polls) or, standalone, this process's pool."""
    rows = []
    snap = None
    if memory_manager is not None:
        snap = getattr(memory_manager, "last_snapshot", None)
    if snap:
        for uri, info in snap.items():
            rows.append(
                (
                    uri,
                    int(info.get("reserved", 0)),
                    int(info.get("limit", 0) or 0),
                    int(info.get("blocked", 0)),
                )
            )
    if not rows:
        rows.append(("local", 0, 0, 0))
    return Page.from_dict(
        {
            "pool": _varchar([r[0] for r in rows]),
            "reserved_bytes": (
                np.array([r[1] for r in rows], np.int64), T.BIGINT,
            ),
            "max_bytes": (
                np.array([r[2] for r in rows], np.int64), T.BIGINT,
            ),
            "blocked": (
                np.array([r[3] for r in rows], np.int64), T.BIGINT,
            ),
        }
    )


_JMX_PROCESS_SCHEMA: Dict[str, T.Type] = {
    "pid": T.BIGINT, "rss_bytes": T.BIGINT, "user_time_s": T.DOUBLE,
    "system_time_s": T.DOUBLE, "threads": T.BIGINT, "backend": T.VARCHAR,
    "devices": T.BIGINT, "uptime_hint_s": T.DOUBLE,
}
_JMX_MEMORY_SCHEMA: Dict[str, T.Type] = {
    "pool": T.VARCHAR, "reserved_bytes": T.BIGINT, "max_bytes": T.BIGINT,
    "blocked": T.BIGINT,
}


_QUERIES_SCHEMA: Dict[str, T.Type] = {
    "query_id": T.VARCHAR, "state": T.VARCHAR, "user": T.VARCHAR,
    "source": T.VARCHAR, "query": T.VARCHAR, "elapsed_s": T.DOUBLE,
    "output_rows": T.BIGINT, "error": T.VARCHAR,
}
_NODES_SCHEMA: Dict[str, T.Type] = {
    "node_id": T.VARCHAR, "state": T.VARCHAR, "coordinator": T.VARCHAR,
}
_METRICS_SCHEMA: Dict[str, T.Type] = {
    "name": T.VARCHAR, "type": T.VARCHAR, "labels": T.VARCHAR,
    "value": T.DOUBLE,
}
_TASKS_SCHEMA: Dict[str, T.Type] = {
    "trace_id": T.VARCHAR, "span_id": T.VARCHAR, "parent_id": T.VARCHAR,
    "name": T.VARCHAR, "status": T.VARCHAR, "start_s": T.DOUBLE,
    "wall_ms": T.DOUBLE, "rows_out": T.BIGINT, "bytes_out": T.BIGINT,
    "attrs": T.VARCHAR,
}
_PLAN_HISTORY_SCHEMA: Dict[str, T.Type] = {
    "fingerprint": T.VARCHAR, "kind": T.VARCHAR, "rows": T.DOUBLE,
    "est_rows": T.DOUBLE, "observations": T.BIGINT,
    "mispredicts": T.BIGINT, "hybrid_parts": T.BIGINT,
    "hybrid_depth": T.BIGINT, "tables": T.VARCHAR,
}
_MATVIEWS_SCHEMA: Dict[str, T.Type] = {
    "name": T.VARCHAR, "base_tables": T.VARCHAR, "incremental": T.VARCHAR,
    "reason": T.VARCHAR, "staleness_versions": T.BIGINT,
    "last_refresh_at": T.DOUBLE, "last_mode": T.VARCHAR,
    "rows_patched": T.BIGINT, "refreshes": T.BIGINT,
}


def _mat_views_page(mgr) -> Page:
    rows = mgr.rows() if mgr is not None else []
    if not rows:
        from ..ops.union import empty_page

        return empty_page(_MATVIEWS_SCHEMA)
    return Page.from_dict(
        {
            "name": _varchar([r["name"] for r in rows]),
            "base_tables": _varchar([r["base_tables"] for r in rows]),
            "incremental": _varchar(
                ["true" if r["incremental"] else "false" for r in rows]
            ),
            "reason": _varchar([r["reason"] or None for r in rows]),
            "staleness_versions": (
                np.array(
                    [r["staleness_versions"] for r in rows], np.int64
                ),
                T.BIGINT,
            ),
            "last_refresh_at": (
                np.array([r["last_refresh_at"] for r in rows], np.float64),
                T.DOUBLE,
            ),
            "last_mode": _varchar([r["last_mode"] for r in rows]),
            "rows_patched": (
                np.array([r["rows_patched"] for r in rows], np.int64),
                T.BIGINT,
            ),
            "refreshes": (
                np.array([r["refreshes"] for r in rows], np.int64),
                T.BIGINT,
            ),
        }
    )


def _metrics_page() -> Page:
    from ..obs.metrics import METRICS as REGISTRY

    samples = REGISTRY.collect()
    if not samples:
        from ..ops.union import empty_page

        return empty_page(_METRICS_SCHEMA)
    return Page.from_dict(
        {
            "name": _varchar([s[0] for s in samples]),
            "type": _varchar([s[1] for s in samples]),
            "labels": _varchar(
                [
                    ",".join(f"{k}={v}" for k, v in s[2]) or None
                    for s in samples
                ]
            ),
            "value": (
                np.array([float(s[3]) for s in samples], np.float64),
                T.DOUBLE,
            ),
        }
    )


def _plan_history_page() -> Page:
    """One row per live feedback-store entry (plan/history.py). The
    fingerprint is the semantic frame key the planner looks up, so a
    `rows` column here IS what the next plan of the same frame will use."""
    from ..plan.history import HISTORY

    entries = HISTORY.rows_snapshot()
    if not entries:
        from ..ops.union import empty_page

        return empty_page(_PLAN_HISTORY_SCHEMA)
    return Page.from_dict(
        {
            "fingerprint": _varchar([fp for fp, _ in entries]),
            "kind": _varchar([e.kind or None for _, e in entries]),
            "rows": (
                np.array(
                    [-1.0 if e.rows is None else float(e.rows)
                     for _, e in entries],
                    np.float64,
                ),
                T.DOUBLE,
            ),
            "est_rows": (
                np.array(
                    [-1.0 if e.est_rows is None else float(e.est_rows)
                     for _, e in entries],
                    np.float64,
                ),
                T.DOUBLE,
            ),
            "observations": (
                np.array([e.n for _, e in entries], np.int64), T.BIGINT,
            ),
            "mispredicts": (
                np.array([e.mispredicts for _, e in entries], np.int64),
                T.BIGINT,
            ),
            "hybrid_parts": (
                np.array([e.hybrid_parts for _, e in entries], np.int64),
                T.BIGINT,
            ),
            "hybrid_depth": (
                np.array([e.hybrid_depth for _, e in entries], np.int64),
                T.BIGINT,
            ),
            "tables": _varchar(
                [",".join(e.tables) or None for _, e in entries]
            ),
        }
    )


def _tasks_page() -> Page:
    """One row per span over the trace store's kept traces — the merged
    fleet trees, so a cluster query's worker task spans appear here."""
    from ..obs.span import TRACES

    spans = [s for tr in TRACES.recent() for s in tr.spans()]
    if not spans:
        from ..ops.union import empty_page

        return empty_page(_TASKS_SCHEMA)

    def _intattr(span, key) -> int:
        try:
            return int(span.attrs.get(key, -1))
        except (TypeError, ValueError):
            return -1

    return Page.from_dict(
        {
            "trace_id": _varchar([s.trace_id for s in spans]),
            "span_id": _varchar([s.span_id for s in spans]),
            "parent_id": _varchar([s.parent_id for s in spans]),
            "name": _varchar([s.name for s in spans]),
            "status": _varchar([s.status for s in spans]),
            "start_s": (
                np.array([s.start for s in spans], np.float64), T.DOUBLE,
            ),
            "wall_ms": (
                np.array([s.wall_s * 1e3 for s in spans], np.float64),
                T.DOUBLE,
            ),
            "rows_out": (
                np.array([_intattr(s, "rows") for s in spans], np.int64),
                T.BIGINT,
            ),
            "bytes_out": (
                np.array([_intattr(s, "bytes") for s in spans], np.int64),
                T.BIGINT,
            ),
            "attrs": _varchar(
                [
                    ",".join(
                        f"{k}={v}" for k, v in sorted(s.attrs.items())
                        if k not in ("rows", "bytes")
                    ) or None
                    for s in spans
                ]
            ),
        }
    )


class SystemCatalog(Connector):
    """Routes system.runtime.* to live snapshots, everything else to the
    wrapped catalog. `manager`/`node_manager` are late-bound attributes —
    the coordinator sets them after construction (QueryManager needs a
    session, whose catalog is this object)."""

    def __init__(self, wrapped, manager=None, node_manager=None,
                 self_uri: Optional[str] = None, memory_manager=None):
        self.wrapped = wrapped
        self.manager = manager
        self.node_manager = node_manager
        self.self_uri = self_uri
        self.memory_manager = memory_manager
        # set explicitly (not via late getattr) so __getattr__ never
        # delegates the name to the wrapped catalog
        self.matview_manager = None

    @property
    def name(self):
        return getattr(self.wrapped, "name", "catalog")

    # -- metadata --

    _SYSTEM_TABLES = (
        QUERIES, NODES, JMX_PROCESS, JMX_MEMORY, MATERIALIZED_VIEWS,
        METRICS, TASKS, PLAN_HISTORY,
    )

    def table_names(self) -> List[str]:
        return list(self.wrapped.table_names()) + list(self._SYSTEM_TABLES)

    def schema(self, table: str):
        if table == QUERIES:
            return dict(_QUERIES_SCHEMA)
        if table == NODES:
            return dict(_NODES_SCHEMA)
        if table == JMX_PROCESS:
            return dict(_JMX_PROCESS_SCHEMA)
        if table == JMX_MEMORY:
            return dict(_JMX_MEMORY_SCHEMA)
        if table == MATERIALIZED_VIEWS:
            return dict(_MATVIEWS_SCHEMA)
        if table == METRICS:
            return dict(_METRICS_SCHEMA)
        if table == TASKS:
            return dict(_TASKS_SCHEMA)
        if table == PLAN_HISTORY:
            return dict(_PLAN_HISTORY_SCHEMA)
        return self.wrapped.schema(table)

    def row_count(self, table: str) -> int:
        if table == QUERIES:
            return len(self.manager.list_queries()) if self.manager else 0
        if table in (
            NODES, JMX_PROCESS, JMX_MEMORY, METRICS, TASKS, PLAN_HISTORY,
        ):
            return 1  # planner estimate; exact counts come from the page
        if table == MATERIALIZED_VIEWS:
            mgr = self.matview_manager
            return len(mgr.views) if mgr is not None else 0
        return self.wrapped.row_count(table)

    def unique_columns(self, table: str):
        if table in self._SYSTEM_TABLES:
            return []
        return self.wrapped.unique_columns(table)

    def table_version(self, table: str):
        # system.runtime.* are live views of server state: NEVER cacheable
        if table in self._SYSTEM_TABLES:
            return None
        fn = getattr(self.wrapped, "table_version", None)
        return None if fn is None else fn(table)

    # -- data --

    def page(self, table: str) -> Page:
        if table == QUERIES:
            return _queries_page(self.manager)
        if table == NODES:
            return _nodes_page(self.node_manager, self.self_uri)
        if table == JMX_PROCESS:
            return _process_page()
        if table == JMX_MEMORY:
            return _memory_page(self.memory_manager, self.node_manager)
        if table == MATERIALIZED_VIEWS:
            return _mat_views_page(self.matview_manager)
        if table == METRICS:
            return _metrics_page()
        if table == TASKS:
            return _tasks_page()
        if table == PLAN_HISTORY:
            return _plan_history_page()
        return self.wrapped.page(table)

    def exact_row_count(self, table: str) -> int:
        if table in self._SYSTEM_TABLES:
            return int(self.page(table).count)
        return self.wrapped.exact_row_count(table)

    def scan(self, table: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None) -> Page:
        if table in self._SYSTEM_TABLES:
            return Connector.scan(
                self, table, start, stop, pad_to=pad_to, columns=columns
            )
        return self.wrapped.scan(
            table, start, stop, pad_to=pad_to, columns=columns,
            predicate=predicate,
        )

    # -- write passthrough (DDL/DML on the user catalog) --

    def __getattr__(self, item):
        # create_table/append/... delegate when the wrapped catalog is
        # writable; AttributeError otherwise, as for any read-only catalog
        return getattr(self.wrapped, item)
