"""Parquet connector: columnar files -> device Pages.

Re-designed equivalent of the reference's Parquet reader stack
(presto-parquet/ ParquetReader + column readers, wired through
presto-hive's HivePageSourceProvider) collapsed TPU-first: pyarrow does
the host-side decode (decompression, encodings), this connector maps
arrow buffers onto the engine's device Block layout —

  int/float/bool/date/timestamp -> storage arrays, zero-copy where arrow
  allows; validity bitmaps -> bool masks
  decimal(p<=18)  -> int64 scaled units
  decimal(p>18)   -> two int64 lanes (ops/decimal128.py layout)
  string          -> int32 codes over a file-level sorted dictionary
                     (built once per column, cached — the engine's
                     DictionaryBlock-only string representation)

Pushdown (reference TupleDomain row-group pruning): `scan(...)` maps a row
range onto parquet row groups, skips groups whose min/max statistics
refute the predicate hint, and reads only the requested columns.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page, _pad_block
from .spi import Connector, Predicate, WritableConnector, WriteError


def _type_to_arrow(typ: T.Type):
    """Engine type -> arrow type (writer-side inverse of _arrow_to_type)."""
    import pyarrow as pa

    if isinstance(typ, T.VarcharType):
        return pa.string()
    if isinstance(typ, T.DecimalType):
        return pa.decimal128(typ.precision, typ.scale)
    if isinstance(typ, T.DateType):
        return pa.date32()
    if isinstance(typ, T.TimestampType):
        return pa.timestamp("us")
    if isinstance(typ, T.BooleanType):
        return pa.bool_()
    if isinstance(typ, T.DoubleType):
        return pa.float64()
    if isinstance(typ, T.RealType):
        return pa.float32()
    if isinstance(typ, T.IntegerType):
        return pa.int32()
    if isinstance(typ, T.SmallintType):
        return pa.int16()
    if isinstance(typ, T.TinyintType):
        return pa.int8()
    return pa.int64()


def _arrow_to_type(at) -> T.Type:
    import pyarrow as pa

    if pa.types.is_dictionary(at):
        at = at.value_type
    if pa.types.is_int64(at):
        return T.BIGINT
    if pa.types.is_int32(at):
        return T.INTEGER
    if pa.types.is_int16(at):
        return T.SMALLINT
    if pa.types.is_int8(at):
        return T.TINYINT
    if pa.types.is_float64(at):
        return T.DOUBLE
    if pa.types.is_float32(at):
        return T.REAL
    if pa.types.is_boolean(at):
        return T.BOOLEAN
    if pa.types.is_date32(at):
        return T.DATE
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_decimal(at):
        return T.DecimalType(at.precision, at.scale)
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return T.VARCHAR
    raise NotImplementedError(f"unsupported parquet type {at}")


def _decimal_ints(arr) -> np.ndarray:
    """Arrow decimal128 column -> numpy int128 pair (hi, lo_unsigned) of
    the 2^64-radix little-endian storage."""
    import pyarrow as pa

    combined = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    parts = combined.chunks if isinstance(combined, pa.ChunkedArray) else [combined]
    his, los = [], []
    for chunk in parts:
        buf = chunk.buffers()[1]
        raw = np.frombuffer(buf, dtype=np.uint64)
        off = chunk.offset
        lo = raw[0::2][off : off + len(chunk)]
        hi = raw[1::2][off : off + len(chunk)].view(np.int64)
        his.append(hi)
        los.append(lo)
    return np.concatenate(his), np.concatenate(los)


class FileWriteMixin:
    """Shared write protocol for single-file-per-table catalogs
    (reference ConnectorPageSink; INSERT rewrites table = existing +
    appended rows). Subclasses define `_ext`, `_encode_write(arrow_table,
    path)`, and `_read_all(table) -> arrow Table`."""

    def _write_path(self, table: str) -> str:
        if table in self.paths:
            return self.paths[table]
        if self.directory is None:
            raise WriteError(
                f"{self.name} catalog is read-only (no directory configured)"
            )
        import os

        return os.path.join(self.directory, f"{table}.{self._ext}")

    def _invalidate(self, table: str) -> None:
        self._files.pop(table, None)
        for key in [k for k in self._dicts if k[0] == table]:
            self._dicts.pop(key)

    def _write(self, table: str, arrow_table) -> None:
        path = self._write_path(table)
        self._encode_write(arrow_table, path)
        self.paths[table] = path
        self._invalidate(table)

    def create_table(self, table: str, schema: Dict[str, T.Type]) -> None:
        import pyarrow as pa

        self._write(table, pa.table(
            {name: pa.array([], type=_type_to_arrow(typ))
             for name, typ in schema.items()}
        ))

    def create_table_from_page(self, table: str, page: Page) -> None:
        self._write(table, page_to_arrow(page))

    def append(self, table: str, page: Page) -> None:
        import pyarrow as pa

        existing = self._read_all(table)
        new = page_to_arrow(page)
        # unify: cast appended columns to the file schema's types
        new = new.select(existing.column_names).cast(existing.schema)
        self._write(table, pa.concat_tables([existing, new]))

    def replace(self, table: str, page: Page) -> None:
        self._write(table, page_to_arrow(page))

    def drop_table(self, table: str) -> None:
        import os

        path = self.paths.pop(table)
        self._invalidate(table)
        if os.path.exists(path):
            os.remove(path)


class ParquetCatalog(FileWriteMixin, WritableConnector):
    """tables: {name: parquet file path}. With `directory` set, the
    catalog is WRITABLE: CREATE TABLE / CTAS / INSERT / DELETE produce
    parquet files under it (reference: HivePageSink + ParquetWriter —
    pyarrow is the bootstrap encoder, matching the read path)."""

    name = "parquet"
    _ext = "parquet"

    def __init__(self, tables: Dict[str, str],
                 unique: Optional[Dict[str, list]] = None,
                 directory: Optional[str] = None):
        import pyarrow.parquet as pq

        self.paths = dict(tables)
        self.unique = unique or {}
        self.directory = directory
        self._files: Dict[str, object] = {}
        self._dicts: Dict[Tuple[str, str], tuple] = {}
        self._pq = pq

    def _encode_write(self, arrow_table, path: str) -> None:
        self._pq.write_table(arrow_table, path, row_group_size=1 << 17)

    def _read_all(self, table: str):
        return self._file(table).read()

    # -- metadata --

    def _file(self, table: str):
        f = self._files.get(table)
        if f is None:
            f = self._pq.ParquetFile(self.paths[table])
            self._files[table] = f
        return f

    def table_names(self) -> List[str]:
        return list(self.paths)

    def schema(self, table: str) -> Dict[str, T.Type]:
        sch = self._file(table).schema_arrow
        return {f.name: _arrow_to_type(f.type) for f in sch}

    def row_count(self, table: str) -> int:
        return self._file(table).metadata.num_rows

    def exact_row_count(self, table: str) -> int:
        return self._file(table).metadata.num_rows

    def unique_columns(self, table: str):
        return self.unique.get(table, [])

    # -- string dictionaries (file-level, sorted, cached) --

    def _dictionary(self, table: str, column: str):
        """(sorted tuple, numpy object array of the same entries) — the
        array form feeds vectorized np.searchsorted encodes per batch."""
        key = (table, column)
        d = self._dicts.get(key)
        if d is None:
            col = self._file(table).read(columns=[column]).column(0)
            d = build_sorted_dictionary(col)
            self._dicts[key] = d
        return d

    # -- data --

    def page(self, table: str) -> Page:
        n = self.row_count(table)
        return self.scan(table, 0, n)

    def scan(
        self,
        table: str,
        start: int,
        stop: int,
        pad_to: Optional[int] = None,
        columns: Optional[List[str]] = None,
        predicate: Optional[Predicate] = None,
    ) -> Page:
        pf = self._file(table)
        md = pf.metadata
        stop = min(stop, md.num_rows)
        names = columns or [f.name for f in pf.schema_arrow]

        # map [start, stop) onto row groups; prune by statistics
        groups = []
        offset = 0
        for g in range(md.num_row_groups):
            rg = md.row_group(g)
            g_start, g_stop = offset, offset + rg.num_rows
            offset = g_stop
            if g_stop <= start or g_start >= stop:
                continue
            if predicate and self._refuted(rg, pf, predicate):
                continue
            groups.append((g, g_start))

        if not groups:
            tb = pf.schema_arrow.empty_table().select(names)
            return self._to_page(table, tb, names, 0, pad_to)

        tb = pf.read_row_groups([g for g, _ in groups], columns=names)
        # slice the requested range out of the concatenated kept groups.
        # With pruning, skipped groups shift positions; deliver whatever
        # kept rows fall in [start, stop) of the ORIGINAL coordinates by
        # assembling per-group slices.
        import pyarrow as pa

        pieces = []
        pos = 0
        for (g, g_start) in groups:
            g_rows = md.row_group(g).num_rows
            lo = max(start - g_start, 0)
            hi = min(stop - g_start, g_rows)
            if hi > lo:
                pieces.append(tb.slice(pos + lo, hi - lo))
            pos += g_rows
        tb = pa.concat_tables(pieces) if pieces else tb.slice(0, 0)
        return self._to_page(table, tb, names, tb.num_rows, pad_to)

    @staticmethod
    def _refuted(rg, pf, predicate: Predicate) -> bool:
        """True if the row group's min/max statistics refute ANY conjunct
        (reference TupleDomainParquetPredicate.matches)."""
        stats_by_col = {}
        for i in range(rg.num_columns):
            c = rg.column(i)
            if c.statistics is not None and c.statistics.has_min_max:
                stats_by_col[c.path_in_schema] = c.statistics
        for col, op, value in predicate:
            st = stats_by_col.get(col)
            if st is None:
                continue
            mn, mx = st.min, st.max
            try:
                if op == "in":
                    # refuted when NO candidate value can be in the group:
                    # all outside [mn, mx] (and for low-NDV groups where
                    # the page dictionary is the whole row group, the
                    # min==max case degenerates to exact membership)
                    if all(v < mn or v > mx for v in value):
                        return True
                    continue
                if op == "eq" and (value < mn or value > mx):
                    return True
                if op in ("lt",) and mn >= value:
                    return True
                if op in ("le",) and mn > value:
                    return True
                if op in ("gt",) and mx <= value:
                    return True
                if op in ("ge",) and mx < value:
                    return True
            except TypeError:
                continue  # incomparable statistics: keep the group
        return False

    def _to_page(self, table, tb, names, count, pad_to) -> Page:
        return arrow_table_to_page(
            tb, names, count, pad_to,
            lambda name: self._dictionary(table, name),
        )


def build_sorted_dictionary(col):
    """Distinct non-null strings of an arrow column, sorted:
    (tuple, numpy object array) — shared by the parquet and ORC readers."""
    import pyarrow.compute as pc

    uniq = pc.unique(
        col.cast(col.type.value_type)
        if hasattr(col.type, "value_type")
        else col
    )
    entries = tuple(sorted(s for s in uniq.to_pylist() if s is not None))
    return entries, np.array(entries, dtype=object)


def arrow_table_to_page(tb, names, count, pad_to, dictionary_provider) -> Page:
    """Arrow table -> engine Page (shared by the parquet and ORC readers).
    dictionary_provider(column) -> (sorted tuple, numpy object array)."""
    import pyarrow as pa

    blocks = []
    for name in names:
        col = tb.column(name)
        typ = _arrow_to_type(col.type)
        valid = None
        if col.null_count:
            valid = ~np.asarray(col.is_null().combine_chunks())
        if isinstance(typ, T.VarcharType):
            d, d_arr = dictionary_provider(name)
            arr = col.combine_chunks()
            if pa.types.is_dictionary(arr.type):
                arr = arr.cast(arr.type.value_type)
            vals = np.asarray(arr.to_pylist(), dtype=object)
            if valid is not None and len(d):
                vals = np.where(valid, vals, d[0])
            # dictionary is sorted: one vectorized binary search encodes
            data = np.searchsorted(d_arr, vals).astype(np.int32)
            blk = Block.from_numpy(data, typ, valid, dictionary=d)
        elif isinstance(typ, T.DecimalType):
            hi64, lo64 = _decimal_ints(col)
            if typ.is_long:
                # 2^64-radix -> engine 2^32-radix lanes
                our_hi = (hi64 << 32) | (lo64 >> 32).astype(np.int64)
                our_lo = (lo64 & np.uint64(0xFFFFFFFF)).astype(np.int64)
                data = np.stack([our_hi, our_lo], axis=-1)
            else:
                data = lo64.view(np.int64)
            blk = Block.from_numpy(data, typ, valid)
        elif isinstance(typ, T.TimestampType):
            us = col.cast(pa.timestamp("us")).combine_chunks()
            data = np.asarray(us.cast(pa.int64()))
            blk = Block.from_numpy(data, typ, valid)
        else:
            arr = col.combine_chunks()
            if pa.types.is_dictionary(arr.type):
                arr = arr.cast(arr.type.value_type)
            if isinstance(typ, T.DateType):
                data = np.asarray(arr.cast(pa.int32()))
            else:
                data = np.asarray(arr, dtype=typ.storage_dtype)
            blk = Block.from_numpy(data, typ, valid)
        if pad_to is not None and pad_to > count:
            blk = _pad_block(blk, pad_to)
        blocks.append(blk)
    return Page.from_blocks(blocks, names, count=count)


def write_table_parquet(page_or_table, path: str, row_group_size: int = 1 << 17):
    """Write engine data back to parquet (test fixture + the seed of a
    writer path; reference presto-hive ParquetPageSink analog)."""
    import pyarrow.parquet as pq

    pq.write_table(page_to_arrow(page_or_table), path,
                   row_group_size=row_group_size)


def page_to_arrow(page):
    """Engine Page -> in-memory pyarrow Table (shared by file writers)."""
    import pyarrow as pa

    n = int(page.count)
    cols = {}
    for name, b in zip(page.names, page.blocks):
        valid = None if b.valid is None else np.asarray(b.valid[:n])
        if isinstance(b.type, T.VarcharType):
            d = b.dictionary or ()
            codes = np.asarray(b.data[:n])
            vals = [
                None if (valid is not None and not valid[i]) else d[int(codes[i])]
                for i in range(n)
            ]
            cols[name] = pa.array(vals, type=pa.string())
        elif isinstance(b.type, T.DecimalType):
            import decimal as _dec

            raw = np.asarray(b.data[:n])
            out = []
            for i in range(n):
                if valid is not None and not valid[i]:
                    out.append(None)
                    continue
                if b.type.is_long:
                    v = int(raw[i][0]) * (1 << 32) + int(raw[i][1])
                else:
                    v = int(raw[i])
                out.append(_dec.Decimal(v).scaleb(-b.type.scale))
            cols[name] = pa.array(
                out, type=pa.decimal128(b.type.precision, b.type.scale)
            )
        elif isinstance(b.type, T.DateType):
            arr = np.asarray(b.data[:n])
            mask = None if valid is None else ~valid
            cols[name] = pa.array(arr, type=pa.date32(), mask=mask)
        else:
            arr = np.asarray(b.data[:n])
            mask = None if valid is None else ~valid
            cols[name] = pa.array(arr, mask=mask)
    return pa.table(cols)
