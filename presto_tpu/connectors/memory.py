"""In-memory connector: query device Pages registered at runtime.

Re-designed equivalent of the reference's memory connector
(presto-memory/src/main/java/com/facebook/presto/plugin/memory/ —
MemoryPagesStore holding pages per table, MemoryMetadata). Here a table IS
a device-resident Page, so scans are free and tests/notebooks can query
arbitrary arrays with zero I/O.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .. import types as T
from ..page import Page
from .spi import WritableConnector


class MemoryCatalog(WritableConnector):
    """tables: {name: Page}; unique: {table: [key column sets]} lets the
    planner use n:1 joins (the analog of declared primary keys)."""

    name = "memory"

    def __init__(
        self,
        tables: Dict[str, Page],
        unique: Optional[Dict[str, List[Tuple[str, ...]]]] = None,
    ):
        self.tables = dict(tables)
        self.unique = unique or {}
        # per-table monotonic snapshot versions (plan/result cache
        # invalidation, exec/qcache.py): bumped BY NAME on every write so
        # a re-created table never resumes an old version sequence
        self._versions: Dict[str, int] = {}

    def _bump(self, table: str) -> None:
        self._versions[table] = self._versions.get(table, 0) + 1

    def table_version(self, table: str) -> int:
        if table not in self.tables:
            # unknown names must not look like a constant version 0 —
            # wrappers (SystemCatalog) probe through this
            raise KeyError(f"table {table!r} does not exist")
        return self._versions.get(table, 0)

    def add(self, name: str, page: Page) -> None:
        self.tables[name] = page
        self._bump(name)

    def table_names(self) -> List[str]:
        return list(self.tables)

    def schema(self, table: str) -> Dict[str, T.Type]:
        page = self.tables[table]
        return {n: b.type for n, b in zip(page.names, page.blocks)}

    def row_count(self, table: str) -> int:
        return int(self.tables[table].count)

    def unique_columns(self, table: str) -> List[Tuple[str, ...]]:
        return self.unique.get(table, [])

    def page(self, table: str) -> Page:
        # scan() and exact_row_count() come from the Connector base: the
        # default device-side slicing IS this connector's batched read path
        return self.tables[table]

    # -- writes (reference MemoryPagesStore.add / MemoryMetadata DDL) --

    def create_table(self, table: str, schema: Dict[str, T.Type]) -> None:
        from ..ops.union import empty_page

        self.tables[table] = empty_page(schema)
        self._bump(table)

    def create_table_from_page(self, table: str, page: Page) -> None:
        self.tables[table] = page
        self._bump(table)

    def drop_table(self, table: str) -> None:
        del self.tables[table]
        self.unique.pop(table, None)
        self._bump(table)

    def append(self, table: str, page: Page) -> None:
        from ..ops.union import concat_pages

        base = self.tables[table]
        if int(base.count) == 0:
            self.tables[table] = page
        elif int(page.count) > 0:
            self.tables[table] = concat_pages([base, page])
        self._bump(table)

    def replace(self, table: str, page: Page) -> None:
        self.tables[table] = page
        self._bump(table)
