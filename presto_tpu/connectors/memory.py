"""In-memory connector: query device Pages registered at runtime.

Re-designed equivalent of the reference's memory connector
(presto-memory/src/main/java/com/facebook/presto/plugin/memory/ —
MemoryPagesStore holding pages per table, MemoryMetadata). Here a table IS
a device-resident Page, so scans are free and tests/notebooks can query
arbitrary arrays with zero I/O.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .. import types as T
from ..page import Page
from ..sql.planner import Catalog


class MemoryCatalog(Catalog):
    """tables: {name: Page}; unique: {table: [key column sets]} lets the
    planner use n:1 joins (the analog of declared primary keys)."""

    name = "memory"

    def __init__(
        self,
        tables: Dict[str, Page],
        unique: Optional[Dict[str, List[Tuple[str, ...]]]] = None,
    ):
        self.tables = dict(tables)
        self.unique = unique or {}

    def add(self, name: str, page: Page) -> None:
        self.tables[name] = page

    def table_names(self) -> List[str]:
        return list(self.tables)

    def schema(self, table: str) -> Dict[str, T.Type]:
        page = self.tables[table]
        return {n: b.type for n, b in zip(page.names, page.blocks)}

    def row_count(self, table: str) -> int:
        return int(self.tables[table].count)

    def unique_columns(self, table: str) -> List[Tuple[str, ...]]:
        return self.unique.get(table, [])

    def page(self, table: str) -> Page:
        return self.tables[table]

    def scan(self, table: str, start: int, stop: int, pad_to=None) -> Page:
        """Batched read path: slice of the stored page (device-side slice —
        the table already lives in HBM for this connector)."""
        from ..page import Block, _pad_block

        src = self.tables[table]
        n = int(src.count)
        stop = min(stop, n)
        count = max(stop - start, 0)
        blocks = []
        for b in src.blocks:
            data = b.data[start:stop]
            valid = None if b.valid is None else b.valid[start:stop]
            blk = Block(data, b.type, valid, b.dict_id)
            if pad_to is not None and pad_to > count:
                blk = _pad_block(blk, pad_to)
            blocks.append(blk)
        return Page.from_blocks(blocks, src.names, count=count)
