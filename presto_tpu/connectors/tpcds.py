"""TPC-DS data generator — columnar, vectorized, deterministic.

Re-designed equivalent of the reference's presto-tpcds connector
(presto-tpcds/src/main/java/com/facebook/presto/tpcds/ — TpcdsMetadata,
TpcdsRecordSet over the teradata dsdgen port, with statistics under
tpcds/statistics/). Follows the same approach as connectors/tpch.py: all 24
spec tables with spec column names/types and spec-shaped distributions,
generated as single-pass numpy columns. Values match OUR SQLite oracle (the
oracle loads the same generated data), not binary dsdgen output — that is
the correctness contract for engine tests, exactly as with the TPC-H
generator (see tpch.py module docstring).

Sizing follows the spec's SF1 row counts (§3.2 scaling), scaled linearly;
fixed-size dimensions (date_dim, time_dim, ship_mode, income_band) stay
fixed except the two demographics cross-product tables, which are sampled
down at small SF so tests stay fast.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .. import types as T
from .tpch import Column, Table

D72 = T.DecimalType(7, 2)
D52 = T.DecimalType(5, 2)

# ---------------------------------------------------------------------------
# calendar: date_dim is a REAL calendar (queries filter d_year/d_moy/d_dow)
# ---------------------------------------------------------------------------

_D_BASE = np.datetime64("1900-01-01")
_D_END = np.datetime64("2100-01-01")
_N_DATES = int((_D_END - _D_BASE).astype(int)) + 1  # 73050 days; spec 73049
_EPOCH = np.datetime64("1970-01-01")

# sales activity window: date_sks for 1998-01-01 .. 2002-12-31 (spec §5)
_SALES_LO = int((np.datetime64("1998-01-01") - _D_BASE).astype(int))
_SALES_HI = int((np.datetime64("2003-01-01") - _D_BASE).astype(int))

_DAY_NAMES = (
    "Friday", "Monday", "Saturday", "Sunday", "Thursday", "Tuesday",
    "Wednesday",
)
_DAY_CODE = {
    name: i for i, name in enumerate(_DAY_NAMES)
}  # dictionary sorted
_WEEKDAY_TO_NAME = [
    "Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday",
    "Sunday",
]

_CATEGORIES = (
    "Books", "Children", "Electronics", "Home", "Jewelry", "Men", "Music",
    "Shoes", "Sports", "Women",
)
_CLASSES = tuple(
    sorted(
        {
            f"{c.lower()} class {i:02d}"
            for c in _CATEGORIES
            for i in range(1, 6)
        }
    )
)
_STATES = (
    "AL", "AR", "AZ", "CA", "CO", "FL", "GA", "IA", "IL", "IN", "KS", "KY",
    "LA", "MI", "MN", "MO", "MS", "NC", "ND", "NE", "NJ", "NM", "NY", "OH",
    "OK", "OR", "PA", "SC", "SD", "TN", "TX", "UT", "VA", "WA", "WI", "WV",
)
_CITIES = tuple(
    sorted(
        {
            f"{a} {b}"
            for a in ("Oak", "Cedar", "Pine", "Maple", "Spring", "Center",
                      "Fair", "Green", "River", "Union")
            for b in ("Grove", "Hill", "Ridge", "Creek", "Park", "View",
                      "town", "ville", "dale", "field")
        }
    )
)
_COUNTIES = tuple(sorted({f"{c} County" for c in _CITIES[:60]}))
_STREET_TYPES = ("Ave", "Blvd", "Cir", "Ct", "Dr", "Ln", "Pkwy", "RD",
                 "ST", "Way")
_STREET_NAMES = tuple(
    sorted(
        {
            f"{a} {b}"
            for a in ("First", "Second", "Third", "Fourth", "Fifth", "Main",
                      "Park", "Lake", "Hill", "Elm")
            for b in ("", "North", "South", "East", "West")
        }
    )
)
_GENDERS = ("F", "M")
_MARITAL = ("D", "M", "S", "U", "W")
_EDUCATION = (
    "2 yr Degree", "4 yr Degree", "Advanced Degree", "College", "Primary",
    "Secondary", "Unknown",
)
_CREDIT = ("Good", "High Risk", "Low Risk", "Unknown")
_BUY_POTENTIAL = (
    "0-500", "1001-5000", "501-1000", "5001-10000", ">10000", "Unknown",
)
_SALUTATIONS = ("Dr.", "Miss", "Mr.", "Mrs.", "Ms.", "Sir")
_FIRST_NAMES = tuple(
    sorted(
        {
            "James", "Mary", "John", "Patricia", "Robert", "Jennifer",
            "Michael", "Linda", "William", "Barbara", "David", "Susan",
            "Richard", "Jessica", "Joseph", "Sarah", "Thomas", "Karen",
            "Charles", "Nancy", "Daniel", "Lisa", "Matthew", "Betty",
            "Anthony", "Helen", "Mark", "Sandra", "Paul", "Donna",
        }
    )
)
_LAST_NAMES = tuple(
    sorted(
        {
            "Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
            "Miller", "Davis", "Rodriguez", "Martinez", "Hernandez",
            "Lopez", "Gonzalez", "Wilson", "Anderson", "Thomas", "Taylor",
            "Moore", "Jackson", "Martin", "Lee", "Perez", "Thompson",
            "White", "Harris", "Sanchez", "Clark", "Ramirez", "Lewis",
            "Robinson",
        }
    )
)
_COUNTRIES = ("United States",)
_COLORS = (
    "almond", "azure", "beige", "black", "blue", "brown", "coral", "cream",
    "cyan", "forest", "gold", "green", "grey", "indigo", "ivory", "khaki",
    "lace", "lime", "maroon", "metallic", "navy", "olive", "orange",
    "orchid", "pale", "peach", "pink", "plum", "powder", "puff", "purple",
    "red", "rose", "royal", "salmon", "sienna", "sky", "slate", "smoke",
    "snow", "spring", "steel", "tan", "thistle", "tomato", "turquoise",
    "violet", "wheat", "white", "yellow",
)
_UNITS = ("Box", "Bunch", "Bundle", "Carton", "Case", "Cup", "Dozen",
          "Dram", "Each", "Gram", "Gross", "Lb", "N/A", "Ounce", "Oz",
          "Pallet", "Pound", "Tbl", "Ton", "Unknown")
_SHIP_MODE_TYPES = ("EXPRESS", "LIBRARY", "NEXT DAY", "OVERNIGHT",
                    "REGULAR", "TWO DAY")
_SHIP_MODE_CODES = ("AIR", "GROUND", "SEA")
_CARRIERS = ("AIRBORNE", "ALLIANCE", "BARIAN", "BOXBUNDLES", "DHL",
             "DIAMOND", "FEDEX", "GERMA", "GREAT EASTERN", "HARMSTORF",
             "LATVIAN", "MSC", "ORIENTAL", "PRIVATECARRIER", "RUPEKSA",
             "TBS", "UPS", "USPS", "ZHOU", "ZOUROS")
_REASONS = tuple(
    sorted(
        {
            "Did not fit", "Did not get it on time", "Did not like the color",
            "Did not like the model", "Did not like the warranty",
            "Found a better price", "Gift exchange", "Item was damaged",
            "Lost my job", "No longer needed", "Not the product that was "
            "ordred", "Parts missing", "Stopped working", "Wrong size",
            "unauthoized purchase", "duplicate purchase", "its is a boy",
            "its is a girl",
        }
    )
)
_MEALS = ("breakfast", "dinner", "lunch", "")
_SHIFTS = ("first", "second", "third")
_AMPM = ("AM", "PM")


def _ids(prefix: str, n: int, width: int = 16):
    """Business-key id strings ('AAAAAAAA...'-style in dsdgen; here a
    zero-padded sorted pool so codes==order)."""
    dictionary = tuple(f"{prefix}{i:0{width}d}" for i in range(n))
    return Column(np.arange(n, dtype=np.int32), T.VARCHAR, dictionary)


def _pool(rng, n, pool) -> Column:
    pool = tuple(pool)
    return Column(rng.integers(0, len(pool), n).astype(np.int32), T.VARCHAR, pool)


def _dec(arr, scale=2, precision=7) -> Column:
    return Column(
        np.asarray(arr).astype(np.int64), T.DecimalType(precision, scale)
    )


def _sk(arr, valid=None) -> Column:
    return Column(np.asarray(arr).astype(np.int64), T.BIGINT, None, valid)


def _sk_nullable(arr, rng, frac=0.04) -> Column:
    """Fact FK with a NULL fraction (dsdgen leaves a few % of fact foreign
    keys null; Q76 aggregates exactly those rows)."""
    a = np.asarray(arr)
    return _sk(a, valid=rng.random(len(a)) >= frac)


def _int(arr) -> Column:
    return Column(np.asarray(arr).astype(np.int64), T.BIGINT)


def _scaled(base: int, sf: float, lo: int = 1) -> int:
    return max(int(base * sf), lo)


# ---------------------------------------------------------------------------
# dimensions
# ---------------------------------------------------------------------------


def gen_date_dim() -> Table:
    n = _N_DATES
    dates = _D_BASE + np.arange(n)
    days_since_epoch = (dates - _EPOCH).astype(int)
    y = dates.astype("datetime64[Y]").astype(int) + 1970
    month0 = dates.astype("datetime64[M]").astype(int)
    moy = month0 % 12 + 1
    dom = (dates - dates.astype("datetime64[M]")).astype(int) + 1
    qoy = (moy - 1) // 3 + 1
    # numpy weekday: day 0 (1970-01-01) was Thursday; dsdgen d_dow has
    # Sunday=0 — any fixed convention works, the oracle sees the same data
    weekday = (days_since_epoch + 3) % 7  # 0=Monday .. 6=Sunday
    dow = (weekday + 1) % 7  # 0=Sunday .. 6=Saturday
    day_codes = np.array(
        [_DAY_CODE[_WEEKDAY_TO_NAME[w]] for w in range(7)], np.int32
    )[weekday]
    month_seq = month0 - (1900 - 1970) * 12
    week_seq = (days_since_epoch - (int((_D_BASE - _EPOCH).astype(int)))) // 7
    quarter_names = tuple(
        sorted({f"{yy}Q{q}" for yy in range(1900, 2101) for q in (1, 2, 3, 4)})
    )
    qname_index = {s: i for i, s in enumerate(quarter_names)}
    qname_codes = np.array(
        [qname_index[f"{yy}Q{qq}"] for yy, qq in zip(y, qoy)], np.int32
    )
    first_dom = days_since_epoch - (dom - 1)
    month_len = np.array(
        (
            (dates.astype("datetime64[M]") + 1).astype("datetime64[D]")
            - dates.astype("datetime64[M]").astype("datetime64[D]")
        ).astype(int)
    )
    last_dom = first_dom + month_len - 1
    holiday = ((moy == 12) & (dom == 25)) | ((moy == 7) & (dom == 4)) | (
        (moy == 1) & (dom == 1)
    )
    weekend = weekday >= 5
    yn = ("N", "Y")
    return Table(
        "date_dim",
        {
            "d_date_sk": _sk(np.arange(n)),
            "d_date_id": _ids("D", n),
            "d_date": Column(days_since_epoch.astype(np.int32), T.DATE),
            "d_month_seq": _int(month_seq),
            "d_week_seq": _int(week_seq),
            "d_quarter_seq": _int((y - 1900) * 4 + qoy - 1),
            "d_year": _int(y),
            "d_dow": _int(dow),
            "d_moy": _int(moy),
            "d_dom": _int(dom),
            "d_qoy": _int(qoy),
            "d_fy_year": _int(y),
            "d_fy_quarter_seq": _int((y - 1900) * 4 + qoy - 1),
            "d_fy_week_seq": _int(week_seq),
            "d_day_name": Column(day_codes, T.VARCHAR, _DAY_NAMES),
            "d_quarter_name": Column(qname_codes, T.VARCHAR, quarter_names),
            "d_holiday": Column(
                holiday.astype(np.int32), T.VARCHAR, yn
            ),
            "d_weekend": Column(weekend.astype(np.int32), T.VARCHAR, yn),
            "d_following_holiday": Column(
                np.roll(holiday, -1).astype(np.int32), T.VARCHAR, yn
            ),
            "d_first_dom": _int(first_dom),
            "d_last_dom": _int(last_dom),
            "d_same_day_ly": _int(days_since_epoch - 365),
            "d_same_day_lq": _int(days_since_epoch - 91),
            "d_current_day": Column(np.zeros(n, np.int32), T.VARCHAR, yn),
            "d_current_week": Column(np.zeros(n, np.int32), T.VARCHAR, yn),
            "d_current_month": Column(np.zeros(n, np.int32), T.VARCHAR, yn),
            "d_current_quarter": Column(np.zeros(n, np.int32), T.VARCHAR, yn),
            "d_current_year": Column(np.zeros(n, np.int32), T.VARCHAR, yn),
        },
    )


def gen_time_dim() -> Table:
    n = 86400
    t = np.arange(n)
    hour = t // 3600
    minute = (t // 60) % 60
    second = t % 60
    shifts = tuple(sorted(_SHIFTS))  # ('first','second','third')
    # first: 8-16, second: 16-24, third: 0-8
    shift_codes = np.where(
        (hour >= 8) & (hour < 16),
        shifts.index("first"),
        np.where(hour >= 16, shifts.index("second"), shifts.index("third")),
    ).astype(np.int32)
    meals = tuple(sorted(set(_MEALS)))
    meal_codes = np.select(
        [
            (hour >= 6) & (hour < 9),
            (hour >= 11) & (hour < 13),
            (hour >= 17) & (hour < 20),
        ],
        [
            meals.index("breakfast"),
            meals.index("lunch"),
            meals.index("dinner"),
        ],
        meals.index(""),
    ).astype(np.int32)
    return Table(
        "time_dim",
        {
            "t_time_sk": _sk(t),
            "t_time_id": _ids("T", n),
            "t_time": _int(t),
            "t_hour": _int(hour),
            "t_minute": _int(minute),
            "t_second": _int(second),
            "t_am_pm": Column(
                (hour >= 12).astype(np.int32), T.VARCHAR, _AMPM
            ),
            "t_shift": Column(shift_codes, T.VARCHAR, shifts),
            "t_sub_shift": Column(shift_codes, T.VARCHAR, shifts),
            "t_meal_time": Column(meal_codes, T.VARCHAR, meals),
        },
    )


def gen_item(sf: float) -> Table:
    n = _scaled(18_000, sf, lo=100)
    rng = np.random.default_rng(7001)
    cat = rng.integers(0, len(_CATEGORIES), n)
    class_in_cat = rng.integers(1, 6, n)
    class_names = np.array(
        [
            f"{_CATEGORIES[c].lower()} class {k:02d}"
            for c, k in zip(cat, class_in_cat)
        ]
    )
    class_index = {s: i for i, s in enumerate(_CLASSES)}
    class_codes = np.array([class_index[s] for s in class_names], np.int32)
    brand_id = (cat + 1) * 1_000_000 + class_in_cat * 1000 + rng.integers(1, 10, n)
    brands = tuple(sorted({f"brand{b:08d}" for b in np.unique(brand_id)}))
    bindex = {s: i for i, s in enumerate(brands)}
    brand_codes = np.array(
        [bindex[f"brand{b:08d}"] for b in brand_id], np.int32
    )
    manufact_id = rng.integers(1, 1001, n)
    manufacts = tuple(f"manufact{i:06d}" for i in range(1, 1001))
    price = rng.integers(100, 30000, n)
    wholesale = (price * rng.uniform(0.3, 0.8, n)).astype(np.int64)
    start = int((np.datetime64("1997-01-01") - _EPOCH).astype(int))
    desc_pool = tuple(
        sorted(
            {
                f"{a} {b} {c}"
                for a in ("Durable", "Shiny", "Compact", "Modern", "Classic",
                          "Premium", "Basic", "Deluxe")
                for b in ("red", "blue", "steel", "wooden", "plastic",
                          "ceramic")
                for c in ("gadget", "tool", "device", "kit", "set", "pack")
            }
        )
    )
    return Table(
        "item",
        {
            "i_item_sk": _sk(np.arange(n)),
            "i_item_id": _ids("I", n),
            "i_rec_start_date": Column(
                np.full(n, start, np.int32), T.DATE
            ),
            "i_rec_end_date": Column(
                np.full(n, start + 3650, np.int32), T.DATE
            ),
            "i_item_desc": _pool(rng, n, desc_pool),
            "i_current_price": _dec(price),
            "i_wholesale_cost": _dec(wholesale),
            "i_brand_id": _int(brand_id),
            "i_brand": Column(brand_codes, T.VARCHAR, brands),
            "i_class_id": _int(class_in_cat),
            "i_class": Column(class_codes, T.VARCHAR, _CLASSES),
            "i_category_id": _int(cat + 1),
            "i_category": Column(cat.astype(np.int32), T.VARCHAR, _CATEGORIES),
            "i_manufact_id": _int(manufact_id),
            "i_manufact": Column(
                (manufact_id - 1).astype(np.int32), T.VARCHAR, manufacts
            ),
            "i_size": _pool(rng, n, ("N/A", "economy", "extra large",
                                     "large", "medium", "petite", "small")),
            "i_formulation": _pool(rng, n, tuple(f"form{i:04d}" for i in range(200))),
            "i_color": _pool(rng, n, _COLORS),
            "i_units": _pool(rng, n, _UNITS),
            "i_container": _pool(rng, n, ("Unknown",)),
            "i_manager_id": _int(rng.integers(1, 101, n)),
            "i_product_name": _ids("product", n),
        },
    )


def gen_customer_address(sf: float) -> Table:
    n = _scaled(50_000, sf, lo=200)
    rng = np.random.default_rng(7002)
    zips = tuple(f"{z:05d}" for z in range(100, 100 + 2000))
    return Table(
        "customer_address",
        {
            "ca_address_sk": _sk(np.arange(n)),
            "ca_address_id": _ids("A", n),
            "ca_street_number": _pool(
                rng, n, tuple(str(i) for i in range(1, 1000))
            ),
            "ca_street_name": _pool(rng, n, _STREET_NAMES),
            "ca_street_type": _pool(rng, n, _STREET_TYPES),
            "ca_suite_number": _pool(
                rng, n, tuple(f"Suite {i}" for i in range(100))
            ),
            "ca_city": _pool(rng, n, _CITIES),
            "ca_county": _pool(rng, n, _COUNTIES),
            "ca_state": _pool(rng, n, _STATES),
            "ca_zip": _pool(rng, n, zips),
            "ca_country": _pool(rng, n, _COUNTRIES),
            "ca_gmt_offset": _dec(
                rng.choice([-500, -600, -700, -800], n), 2, 5
            ),
            "ca_location_type": _pool(
                rng, n, ("apartment", "condo", "single family")
            ),
        },
    )


def gen_customer_demographics(sf: float) -> Table:
    # spec: fixed 1,920,800-row cross product; sampled down for small SF
    # (kept a cross-product enumeration so every attribute combination
    # that appears is self-consistent)
    n = min(1_920_800, _scaled(1_920_800, min(sf, 1.0), lo=2000))
    idx = np.arange(n, dtype=np.int64)
    g = idx % 2
    ms = (idx // 2) % 5
    ed = (idx // 10) % 7
    pe = (idx // 70) % 20
    cr = (idx // 1400) % 4
    dep = (idx // 5600) % 7
    demp = (idx // 39200) % 7
    dcol = (idx // 274400) % 7
    return Table(
        "customer_demographics",
        {
            "cd_demo_sk": _sk(idx),
            "cd_gender": Column(g.astype(np.int32), T.VARCHAR, _GENDERS),
            "cd_marital_status": Column(
                ms.astype(np.int32), T.VARCHAR, _MARITAL
            ),
            "cd_education_status": Column(
                ed.astype(np.int32), T.VARCHAR, _EDUCATION
            ),
            "cd_purchase_estimate": _int(500 * (pe + 1)),
            "cd_credit_rating": Column(
                cr.astype(np.int32), T.VARCHAR, _CREDIT
            ),
            "cd_dep_count": _int(dep),
            "cd_dep_employed_count": _int(demp),
            "cd_dep_college_count": _int(dcol),
        },
    )


def gen_household_demographics() -> Table:
    n = 7200
    idx = np.arange(n, dtype=np.int64)
    ib = idx % 20
    bp = (idx // 20) % 6
    dep = (idx // 120) % 10
    veh = (idx // 1200) % 6
    pots = tuple(sorted(_BUY_POTENTIAL))
    return Table(
        "household_demographics",
        {
            "hd_demo_sk": _sk(idx),
            "hd_income_band_sk": _sk(ib),
            "hd_buy_potential": Column(
                np.array(
                    [pots.index(_BUY_POTENTIAL[b]) for b in bp], np.int32
                ),
                T.VARCHAR,
                pots,
            ),
            "hd_dep_count": _int(dep),
            "hd_vehicle_count": _int(veh - 1),
        },
    )


def gen_income_band() -> Table:
    n = 20
    lo = np.arange(n, dtype=np.int64) * 10000
    return Table(
        "income_band",
        {
            "ib_income_band_sk": _sk(np.arange(n)),
            "ib_lower_bound": _int(lo + 1),
            "ib_upper_bound": _int(lo + 10000),
        },
    )


def gen_customer(sf: float) -> Table:
    n = _scaled(100_000, sf, lo=500)
    n_addr = _scaled(50_000, sf, lo=200)
    n_cd = min(1_920_800, _scaled(1_920_800, min(sf, 1.0), lo=2000))
    rng = np.random.default_rng(7003)
    first_sales = rng.integers(_SALES_LO - 3650, _SALES_LO, n)
    return Table(
        "customer",
        {
            "c_customer_sk": _sk(np.arange(n)),
            "c_customer_id": _ids("C", n),
            "c_current_cdemo_sk": _sk(rng.integers(0, n_cd, n)),
            "c_current_hdemo_sk": _sk(rng.integers(0, 7200, n)),
            "c_current_addr_sk": _sk(rng.integers(0, n_addr, n)),
            "c_first_shipto_date_sk": _sk(first_sales + 30),
            "c_first_sales_date_sk": _sk(first_sales),
            "c_salutation": _pool(rng, n, _SALUTATIONS),
            "c_first_name": _pool(rng, n, _FIRST_NAMES),
            "c_last_name": _pool(rng, n, _LAST_NAMES),
            "c_preferred_cust_flag": _pool(rng, n, ("N", "Y")),
            "c_birth_day": _int(rng.integers(1, 29, n)),
            "c_birth_month": _int(rng.integers(1, 13, n)),
            "c_birth_year": _int(rng.integers(1930, 1993, n)),
            # dsdgen stores birth country UPPERCASE (Q24 joins it against
            # upper(ca_country))
            "c_birth_country": _pool(
                rng, n, tuple(c.upper() for c in _COUNTRIES)
            ),
            "c_login": _ids("login", n),
            "c_email_address": _ids("email", n),
            "c_last_review_date_sk": _sk(
                rng.integers(_SALES_LO, _SALES_HI, n)
            ),
        },
    )


def gen_store(sf: float) -> Table:
    n = _scaled(12, sf, lo=4)
    rng = np.random.default_rng(7004)
    # dsdgen-style syllable store names (queries filter on e.g. 'ese')
    names = ("able", "anti", "ation", "bar", "cally", "eing", "ese", "ought")
    return Table(
        "store",
        {
            "s_store_sk": _sk(np.arange(n)),
            "s_store_id": _ids("S", n),
            "s_rec_start_date": Column(
                np.full(n, _SALES_LO - 3650, np.int32) * 0
                + int((np.datetime64("1997-03-13") - _EPOCH).astype(int)),
                T.DATE,
            ),
            "s_rec_end_date": Column(
                np.full(
                    n,
                    int((np.datetime64("2001-03-13") - _EPOCH).astype(int)),
                    np.int32,
                ),
                T.DATE,
            ),
            "s_closed_date_sk": _sk(np.zeros(n)),
            "s_store_name": Column(
                np.arange(n, dtype=np.int32) % len(names), T.VARCHAR, names
            ),
            "s_number_employees": _int(rng.integers(200, 301, n)),
            "s_floor_space": _int(rng.integers(5_000_000, 10_000_001, n)),
            "s_hours": _pool(rng, n, ("8AM-12AM", "8AM-4PM", "8AM-8AM")),
            "s_manager": _pool(rng, n, _LAST_NAMES),
            "s_market_id": _int(rng.integers(1, 11, n)),
            "s_geography_class": _pool(rng, n, ("Unknown",)),
            "s_market_desc": _pool(rng, n, ("Unknown",)),
            "s_market_manager": _pool(rng, n, _LAST_NAMES),
            "s_division_id": _int(np.ones(n)),
            "s_division_name": _pool(rng, n, ("Unknown",)),
            "s_company_id": _int(np.ones(n)),
            "s_company_name": _pool(rng, n, ("Unknown",)),
            "s_street_number": _pool(
                rng, n, tuple(str(i) for i in range(1, 1000))
            ),
            "s_street_name": _pool(rng, n, _STREET_NAMES),
            "s_street_type": _pool(rng, n, _STREET_TYPES),
            "s_suite_number": _pool(
                rng, n, tuple(f"Suite {i}" for i in range(100))
            ),
            "s_city": _pool(rng, n, _CITIES),
            "s_county": _pool(rng, n, _COUNTIES),
            "s_state": _pool(rng, n, _STATES[:8]),
            "s_zip": _pool(rng, n, tuple(f"{z:05d}" for z in range(100, 600))),
            "s_country": _pool(rng, n, _COUNTRIES),
            "s_gmt_offset": _dec(rng.choice([-500, -600], n), 2, 5),
            "s_tax_precentage": _dec(rng.integers(0, 12, n), 2, 5),
        },
    )


def gen_warehouse(sf: float) -> Table:
    n = _scaled(5, sf, lo=3)
    rng = np.random.default_rng(7005)
    return Table(
        "warehouse",
        {
            "w_warehouse_sk": _sk(np.arange(n)),
            "w_warehouse_id": _ids("W", n),
            "w_warehouse_name": _ids("warehouse", n),
            "w_warehouse_sq_ft": _int(rng.integers(50_000, 1_000_000, n)),
            "w_street_number": _pool(
                rng, n, tuple(str(i) for i in range(1, 1000))
            ),
            "w_street_name": _pool(rng, n, _STREET_NAMES),
            "w_street_type": _pool(rng, n, _STREET_TYPES),
            "w_suite_number": _pool(
                rng, n, tuple(f"Suite {i}" for i in range(100))
            ),
            "w_city": _pool(rng, n, _CITIES),
            "w_county": _pool(rng, n, _COUNTIES),
            "w_state": _pool(rng, n, _STATES[:8]),
            "w_zip": _pool(rng, n, tuple(f"{z:05d}" for z in range(100, 600))),
            "w_country": _pool(rng, n, _COUNTRIES),
            "w_gmt_offset": _dec(rng.choice([-500, -600], n), 2, 5),
        },
    )


def gen_ship_mode() -> Table:
    n = 20
    rng = np.random.default_rng(7006)
    types = tuple(sorted(_SHIP_MODE_TYPES))
    codes = tuple(sorted(_SHIP_MODE_CODES))
    return Table(
        "ship_mode",
        {
            "sm_ship_mode_sk": _sk(np.arange(n)),
            "sm_ship_mode_id": _ids("SM", n),
            "sm_type": Column(
                (np.arange(n) % len(types)).astype(np.int32), T.VARCHAR, types
            ),
            "sm_code": Column(
                (np.arange(n) % len(codes)).astype(np.int32), T.VARCHAR, codes
            ),
            "sm_carrier": Column(
                np.arange(n, dtype=np.int32), T.VARCHAR, _CARRIERS
            ),
            "sm_contract": _pool(rng, n, tuple(f"contract{i}" for i in range(20))),
        },
    )


def gen_reason() -> Table:
    n = len(_REASONS)
    return Table(
        "reason",
        {
            "r_reason_sk": _sk(np.arange(n)),
            "r_reason_id": _ids("R", n),
            "r_reason_desc": Column(
                np.arange(n, dtype=np.int32), T.VARCHAR, _REASONS
            ),
        },
    )


def gen_promotion(sf: float) -> Table:
    n = _scaled(300, sf, lo=30)
    rng = np.random.default_rng(7007)
    yn = ("N", "Y")
    start = rng.integers(_SALES_LO, _SALES_HI - 60, n)
    channels = {
        ch: Column(rng.integers(0, 2, n).astype(np.int32), T.VARCHAR, yn)
        for ch in (
            "p_channel_dmail", "p_channel_email", "p_channel_catalog",
            "p_channel_tv", "p_channel_radio", "p_channel_press",
            "p_channel_event", "p_channel_demo",
        )
    }
    return Table(
        "promotion",
        {
            "p_promo_sk": _sk(np.arange(n)),
            "p_promo_id": _ids("P", n),
            "p_start_date_sk": _sk(start),
            "p_end_date_sk": _sk(start + rng.integers(10, 60, n)),
            "p_item_sk": _sk(
                rng.integers(0, _scaled(18_000, sf, lo=100), n)
            ),
            "p_cost": _dec(rng.integers(50000, 300001, n), 2, 15),
            "p_response_target": _int(np.ones(n)),
            "p_promo_name": _pool(
                rng, n, ("able", "ation", "bar", "ese", "eing", "ought",
                         "anti", "cally", "ition", "pri")
            ),
            **channels,
            "p_channel_details": _ids("promo details ", n),
            "p_purpose": _pool(rng, n, ("Unknown",)),
            "p_discount_active": Column(
                rng.integers(0, 2, n).astype(np.int32), T.VARCHAR, yn
            ),
        },
    )


def gen_web_site(sf: float) -> Table:
    n = _scaled(30, sf, lo=5)
    rng = np.random.default_rng(7008)
    return Table(
        "web_site",
        {
            "web_site_sk": _sk(np.arange(n)),
            "web_site_id": _ids("WEB", n),
            "web_rec_start_date": Column(
                np.full(
                    n,
                    int((np.datetime64("1997-08-16") - _EPOCH).astype(int)),
                    np.int32,
                ),
                T.DATE,
            ),
            "web_rec_end_date": Column(
                np.full(
                    n,
                    int((np.datetime64("2001-08-16") - _EPOCH).astype(int)),
                    np.int32,
                ),
                T.DATE,
            ),
            "web_name": _pool(rng, n, tuple(f"site_{i}" for i in range(30))),
            "web_open_date_sk": _sk(rng.integers(_SALES_LO - 3650, _SALES_LO, n)),
            "web_close_date_sk": _sk(np.full(n, _SALES_HI + 1000)),
            "web_class": _pool(rng, n, ("Unknown",)),
            "web_manager": _pool(rng, n, _LAST_NAMES),
            "web_mkt_id": _int(rng.integers(1, 7, n)),
            "web_mkt_class": _pool(rng, n, ("Unknown",)),
            "web_mkt_desc": _pool(rng, n, ("Unknown",)),
            "web_market_manager": _pool(rng, n, _LAST_NAMES),
            "web_company_id": _int(rng.integers(1, 7, n)),
            "web_company_name": _pool(
                rng, n, ("able", "ation", "bar", "ese", "eing", "ought")
            ),
            "web_street_number": _pool(
                rng, n, tuple(str(i) for i in range(1, 1000))
            ),
            "web_street_name": _pool(rng, n, _STREET_NAMES),
            "web_street_type": _pool(rng, n, _STREET_TYPES),
            "web_suite_number": _pool(
                rng, n, tuple(f"Suite {i}" for i in range(100))
            ),
            "web_city": _pool(rng, n, _CITIES),
            "web_county": _pool(rng, n, _COUNTIES),
            "web_state": _pool(rng, n, _STATES[:8]),
            "web_zip": _pool(rng, n, tuple(f"{z:05d}" for z in range(100, 600))),
            "web_country": _pool(rng, n, _COUNTRIES),
            "web_gmt_offset": _dec(rng.choice([-500, -600], n), 2, 5),
            "web_tax_percentage": _dec(rng.integers(0, 12, n), 2, 5),
        },
    )


def gen_web_page(sf: float) -> Table:
    n = _scaled(60, sf, lo=10)
    rng = np.random.default_rng(7009)
    yn = ("N", "Y")
    return Table(
        "web_page",
        {
            "wp_web_page_sk": _sk(np.arange(n)),
            "wp_web_page_id": _ids("WP", n),
            "wp_rec_start_date": Column(
                np.full(
                    n,
                    int((np.datetime64("1997-09-03") - _EPOCH).astype(int)),
                    np.int32,
                ),
                T.DATE,
            ),
            "wp_rec_end_date": Column(
                np.full(
                    n,
                    int((np.datetime64("2001-09-03") - _EPOCH).astype(int)),
                    np.int32,
                ),
                T.DATE,
            ),
            "wp_creation_date_sk": _sk(
                rng.integers(_SALES_LO - 365, _SALES_LO, n)
            ),
            "wp_access_date_sk": _sk(rng.integers(_SALES_LO, _SALES_HI, n)),
            "wp_autogen_flag": _pool(rng, n, yn),
            "wp_customer_sk": _sk(rng.integers(0, _scaled(100_000, sf, lo=500), n)),
            "wp_url": _pool(rng, n, ("http://www.foo.com",)),
            "wp_type": _pool(
                rng, n, ("ad", "dynamic", "feedback", "general", "order",
                         "protected", "welcome")
            ),
            "wp_char_count": _int(rng.integers(100, 8000, n)),
            "wp_link_count": _int(rng.integers(2, 25, n)),
            "wp_image_count": _int(rng.integers(1, 7, n)),
            "wp_max_ad_count": _int(rng.integers(0, 5, n)),
        },
    )


def gen_call_center(sf: float) -> Table:
    n = _scaled(6, sf, lo=2)
    rng = np.random.default_rng(7010)
    return Table(
        "call_center",
        {
            "cc_call_center_sk": _sk(np.arange(n)),
            "cc_call_center_id": _ids("CC", n),
            "cc_rec_start_date": Column(
                np.full(
                    n,
                    int((np.datetime64("1998-01-01") - _EPOCH).astype(int)),
                    np.int32,
                ),
                T.DATE,
            ),
            "cc_rec_end_date": Column(
                np.full(
                    n,
                    int((np.datetime64("2002-01-01") - _EPOCH).astype(int)),
                    np.int32,
                ),
                T.DATE,
            ),
            "cc_closed_date_sk": _sk(np.zeros(n)),
            "cc_open_date_sk": _sk(rng.integers(_SALES_LO - 3650, _SALES_LO, n)),
            "cc_name": _ids("call center ", n),
            "cc_class": _pool(rng, n, ("large", "medium", "small")),
            "cc_employees": _int(rng.integers(100, 700, n)),
            "cc_sq_ft": _int(rng.integers(10_000, 50_000, n)),
            "cc_hours": _pool(rng, n, ("8AM-12AM", "8AM-4PM", "8AM-8AM")),
            "cc_manager": _pool(rng, n, _LAST_NAMES),
            "cc_mkt_id": _int(rng.integers(1, 7, n)),
            "cc_mkt_class": _pool(rng, n, ("Unknown",)),
            "cc_mkt_desc": _pool(rng, n, ("Unknown",)),
            "cc_market_manager": _pool(rng, n, _LAST_NAMES),
            "cc_division": _int(rng.integers(1, 7, n)),
            "cc_division_name": _pool(
                rng, n, ("able", "ation", "bar", "ese", "eing", "ought")
            ),
            "cc_company": _int(rng.integers(1, 7, n)),
            "cc_company_name": _pool(
                rng, n, ("able", "ation", "bar", "ese", "eing", "ought")
            ),
            "cc_street_number": _pool(
                rng, n, tuple(str(i) for i in range(1, 1000))
            ),
            "cc_street_name": _pool(rng, n, _STREET_NAMES),
            "cc_street_type": _pool(rng, n, _STREET_TYPES),
            "cc_suite_number": _pool(
                rng, n, tuple(f"Suite {i}" for i in range(100))
            ),
            "cc_city": _pool(rng, n, _CITIES),
            "cc_county": _pool(rng, n, _COUNTIES),
            "cc_state": _pool(rng, n, _STATES[:8]),
            "cc_zip": _pool(rng, n, tuple(f"{z:05d}" for z in range(100, 600))),
            "cc_country": _pool(rng, n, _COUNTRIES),
            "cc_gmt_offset": _dec(rng.choice([-500, -600], n), 2, 5),
            "cc_tax_percentage": _dec(rng.integers(0, 12, n), 2, 5),
        },
    )


def gen_catalog_page(sf: float) -> Table:
    n = _scaled(11_718, sf, lo=100)
    rng = np.random.default_rng(7011)
    return Table(
        "catalog_page",
        {
            "cp_catalog_page_sk": _sk(np.arange(n)),
            "cp_catalog_page_id": _ids("CP", n),
            "cp_start_date_sk": _sk(rng.integers(_SALES_LO, _SALES_HI - 90, n)),
            "cp_end_date_sk": _sk(rng.integers(_SALES_HI - 90, _SALES_HI, n)),
            "cp_department": _pool(rng, n, ("DEPARTMENT",)),
            "cp_catalog_number": _int(rng.integers(1, 110, n)),
            "cp_catalog_page_number": _int(rng.integers(1, 109, n)),
            "cp_description": _ids("catalog page ", n),
            "cp_type": _pool(rng, n, ("bi-annual", "monthly", "quarterly")),
        },
    )


def gen_inventory(sf: float) -> Table:
    # spec: weekly snapshots x items x warehouses
    n = _scaled(11_745_000, sf, lo=5000)
    rng = np.random.default_rng(7012)
    n_item = _scaled(18_000, sf, lo=100)
    n_wh = _scaled(5, sf, lo=3)
    weeks = np.arange(_SALES_LO, _SALES_HI, 7)
    return Table(
        "inventory",
        {
            "inv_date_sk": _sk(rng.choice(weeks, n)),
            "inv_item_sk": _sk(rng.integers(0, n_item, n)),
            "inv_warehouse_sk": _sk(rng.integers(0, n_wh, n)),
            "inv_quantity_on_hand": _int(rng.integers(0, 1000, n)),
        },
    )


# ---------------------------------------------------------------------------
# fact tables: sales + returns (returns reference their sales rows so
# join-back queries like Q25/Q29/Q93 have matching rows)
# ---------------------------------------------------------------------------


def _sales_money(rng, n, qty):
    wholesale = rng.integers(100, 10000, n)
    list_price = (wholesale * rng.uniform(1.2, 2.4, n)).astype(np.int64)
    discount = rng.uniform(0.0, 0.6, n)
    sales_price = (list_price * (1.0 - discount)).astype(np.int64)
    ext_discount = (list_price - sales_price) * qty
    ext_sales = sales_price * qty
    ext_wholesale = wholesale * qty
    ext_list = list_price * qty
    tax = (ext_sales * 0.08).astype(np.int64)
    coupon = (ext_sales * rng.choice([0.0, 0.0, 0.0, 0.1], n)).astype(np.int64)
    net_paid = ext_sales - coupon
    net_paid_tax = net_paid + tax
    profit = net_paid - ext_wholesale
    return {
        "wholesale_cost": wholesale,
        "list_price": list_price,
        "sales_price": sales_price,
        "ext_discount_amt": ext_discount,
        "ext_sales_price": ext_sales,
        "ext_wholesale_cost": ext_wholesale,
        "ext_list_price": ext_list,
        "ext_tax": tax,
        "coupon_amt": coupon,
        "net_paid": net_paid,
        "net_paid_inc_tax": net_paid_tax,
        "net_profit": profit,
    }


def _dims(sf: float):
    return {
        "item": _scaled(18_000, sf, lo=100),
        "customer": _scaled(100_000, sf, lo=500),
        "addr": _scaled(50_000, sf, lo=200),
        "cd": min(1_920_800, _scaled(1_920_800, min(sf, 1.0), lo=2000)),
        "hd": 7200,
        "store": _scaled(12, sf, lo=4),
        "promo": _scaled(300, sf, lo=30),
        "warehouse": _scaled(5, sf, lo=3),
        "web_page": _scaled(60, sf, lo=10),
        "web_site": _scaled(30, sf, lo=5),
        "call_center": _scaled(6, sf, lo=2),
        "catalog_page": _scaled(11_718, sf, lo=100),
        "ship_mode": 20,
        "reason": len(_REASONS),
    }


def gen_store_sales(sf: float) -> Table:
    n = _scaled(2_880_404, sf, lo=2000)
    rng = np.random.default_rng(8001)
    d = _dims(sf)
    qty = rng.integers(1, 101, n)
    m = _sales_money(rng, n, qty)
    # ~2 lines per ticket; ticket shares customer/store/date
    n_tickets = max(n // 2, 1)
    ticket = rng.integers(0, n_tickets, n)
    t_rng = np.random.default_rng(8002)
    t_date = t_rng.integers(_SALES_LO, _SALES_HI, n_tickets)
    t_cust = t_rng.integers(0, d["customer"], n_tickets)
    t_store = t_rng.integers(0, d["store"], n_tickets)
    t_hdemo = t_rng.integers(0, d["hd"], n_tickets)
    t_cdemo = t_rng.integers(0, d["cd"], n_tickets)
    t_addr = t_rng.integers(0, d["addr"], n_tickets)
    return Table(
        "store_sales",
        {
            "ss_sold_date_sk": _sk(t_date[ticket]),
            "ss_sold_time_sk": _sk(rng.integers(28800, 79200, n)),
            "ss_item_sk": _sk(rng.integers(0, d["item"], n)),
            "ss_customer_sk": _sk(t_cust[ticket]),
            "ss_cdemo_sk": _sk(t_cdemo[ticket]),
            "ss_hdemo_sk": _sk(t_hdemo[ticket]),
            "ss_addr_sk": _sk_nullable(t_addr[ticket], rng),
            "ss_store_sk": _sk_nullable(t_store[ticket], rng),
            "ss_promo_sk": _sk(rng.integers(0, d["promo"], n)),
            "ss_ticket_number": _sk(ticket),
            "ss_quantity": _int(qty),
            "ss_wholesale_cost": _dec(m["wholesale_cost"]),
            "ss_list_price": _dec(m["list_price"]),
            "ss_sales_price": _dec(m["sales_price"]),
            "ss_ext_discount_amt": _dec(m["ext_discount_amt"]),
            "ss_ext_sales_price": _dec(m["ext_sales_price"]),
            "ss_ext_wholesale_cost": _dec(m["ext_wholesale_cost"]),
            "ss_ext_list_price": _dec(m["ext_list_price"]),
            "ss_ext_tax": _dec(m["ext_tax"]),
            "ss_coupon_amt": _dec(m["coupon_amt"]),
            "ss_net_paid": _dec(m["net_paid"]),
            "ss_net_paid_inc_tax": _dec(m["net_paid_inc_tax"]),
            "ss_net_profit": _dec(m["net_profit"]),
        },
    )


def gen_store_returns(sf: float) -> Table:
    ss = table("store_sales", sf)
    n_ss = ss.num_rows
    rng = np.random.default_rng(8003)
    n = max(n_ss // 10, 1)
    idx = rng.choice(n_ss, n, replace=False)
    d = _dims(sf)
    qty = np.minimum(
        rng.integers(1, 101, n), ss.columns["ss_quantity"].data[idx]
    )
    sold_date = ss.columns["ss_sold_date_sk"].data[idx]
    amt = (
        ss.columns["ss_sales_price"].data[idx] * qty
    )
    tax = (amt * 0.08).astype(np.int64)
    fee = rng.integers(50, 10000, n)
    ship = rng.integers(100, 5000, n)
    refunded = (amt * rng.uniform(0.3, 1.0, n)).astype(np.int64)
    reversed_ = amt - refunded
    return Table(
        "store_returns",
        {
            "sr_returned_date_sk": _sk(
                np.minimum(sold_date + rng.integers(1, 60, n), _SALES_HI + 59)
            ),
            "sr_return_time_sk": _sk(rng.integers(28800, 79200, n)),
            "sr_item_sk": _sk(ss.columns["ss_item_sk"].data[idx]),
            "sr_customer_sk": _sk(ss.columns["ss_customer_sk"].data[idx]),
            "sr_cdemo_sk": _sk(ss.columns["ss_cdemo_sk"].data[idx]),
            "sr_hdemo_sk": _sk(ss.columns["ss_hdemo_sk"].data[idx]),
            "sr_addr_sk": _sk(
                ss.columns["ss_addr_sk"].data[idx],
                valid=ss.columns["ss_addr_sk"].valid[idx],
            ),
            "sr_store_sk": _sk(
                ss.columns["ss_store_sk"].data[idx],
                valid=ss.columns["ss_store_sk"].valid[idx],
            ),
            "sr_reason_sk": _sk(rng.integers(0, d["reason"], n)),
            "sr_ticket_number": _sk(ss.columns["ss_ticket_number"].data[idx]),
            "sr_return_quantity": _int(qty),
            "sr_return_amt": _dec(amt),
            "sr_return_tax": _dec(tax),
            "sr_return_amt_inc_tax": _dec(amt + tax),
            "sr_fee": _dec(fee),
            "sr_return_ship_cost": _dec(ship),
            "sr_refunded_cash": _dec(refunded),
            "sr_reversed_charge": _dec(reversed_),
            "sr_store_credit": _dec(np.zeros(n)),
            "sr_net_loss": _dec(fee + ship + tax),
        },
    )


def gen_catalog_sales(sf: float) -> Table:
    n = _scaled(1_441_548, sf, lo=1200)
    rng = np.random.default_rng(8004)
    d = _dims(sf)
    qty = rng.integers(1, 101, n)
    m = _sales_money(rng, n, qty)
    n_orders = max(n // 3, 1)
    order = rng.integers(0, n_orders, n)
    o_rng = np.random.default_rng(8005)
    o_date = o_rng.integers(_SALES_LO, _SALES_HI, n_orders)
    o_cust = o_rng.integers(0, d["customer"], n_orders)
    ship_cost = rng.integers(50, 5000, n) * qty
    return Table(
        "catalog_sales",
        {
            "cs_sold_date_sk": _sk(o_date[order]),
            "cs_sold_time_sk": _sk(rng.integers(0, 86400, n)),
            "cs_ship_date_sk": _sk(o_date[order] + rng.integers(2, 90, n)),
            "cs_bill_customer_sk": _sk(o_cust[order]),
            "cs_bill_cdemo_sk": _sk(rng.integers(0, d["cd"], n)),
            "cs_bill_hdemo_sk": _sk(rng.integers(0, d["hd"], n)),
            "cs_bill_addr_sk": _sk(rng.integers(0, d["addr"], n)),
            "cs_ship_customer_sk": _sk(o_cust[order]),
            "cs_ship_cdemo_sk": _sk(rng.integers(0, d["cd"], n)),
            "cs_ship_hdemo_sk": _sk(rng.integers(0, d["hd"], n)),
            "cs_ship_addr_sk": _sk_nullable(rng.integers(0, d["addr"], n), rng),
            "cs_call_center_sk": _sk(rng.integers(0, d["call_center"], n)),
            "cs_catalog_page_sk": _sk(rng.integers(0, d["catalog_page"], n)),
            "cs_ship_mode_sk": _sk(rng.integers(0, d["ship_mode"], n)),
            "cs_warehouse_sk": _sk(rng.integers(0, d["warehouse"], n)),
            "cs_item_sk": _sk(rng.integers(0, d["item"], n)),
            "cs_promo_sk": _sk(rng.integers(0, d["promo"], n)),
            "cs_order_number": _sk(order),
            "cs_quantity": _int(qty),
            "cs_wholesale_cost": _dec(m["wholesale_cost"]),
            "cs_list_price": _dec(m["list_price"]),
            "cs_sales_price": _dec(m["sales_price"]),
            "cs_ext_discount_amt": _dec(m["ext_discount_amt"]),
            "cs_ext_sales_price": _dec(m["ext_sales_price"]),
            "cs_ext_wholesale_cost": _dec(m["ext_wholesale_cost"]),
            "cs_ext_list_price": _dec(m["ext_list_price"]),
            "cs_ext_tax": _dec(m["ext_tax"]),
            "cs_coupon_amt": _dec(m["coupon_amt"]),
            "cs_ext_ship_cost": _dec(ship_cost),
            "cs_net_paid": _dec(m["net_paid"]),
            "cs_net_paid_inc_tax": _dec(m["net_paid_inc_tax"]),
            "cs_net_paid_inc_ship": _dec(m["net_paid"] + ship_cost),
            "cs_net_paid_inc_ship_tax": _dec(
                m["net_paid_inc_tax"] + ship_cost
            ),
            "cs_net_profit": _dec(m["net_profit"]),
        },
    )


def gen_catalog_returns(sf: float) -> Table:
    cs = table("catalog_sales", sf)
    n_cs = cs.num_rows
    rng = np.random.default_rng(8006)
    n = max(n_cs // 10, 1)
    idx = rng.choice(n_cs, n, replace=False)
    d = _dims(sf)
    qty = np.minimum(
        rng.integers(1, 101, n), cs.columns["cs_quantity"].data[idx]
    )
    amt = cs.columns["cs_sales_price"].data[idx] * qty
    tax = (amt * 0.08).astype(np.int64)
    fee = rng.integers(50, 10000, n)
    ship = rng.integers(100, 5000, n)
    refunded = (amt * rng.uniform(0.3, 1.0, n)).astype(np.int64)
    return Table(
        "catalog_returns",
        {
            "cr_returned_date_sk": _sk(
                cs.columns["cs_sold_date_sk"].data[idx]
                + rng.integers(1, 60, n)
            ),
            "cr_returned_time_sk": _sk(rng.integers(0, 86400, n)),
            "cr_item_sk": _sk(cs.columns["cs_item_sk"].data[idx]),
            "cr_refunded_customer_sk": _sk(
                cs.columns["cs_bill_customer_sk"].data[idx]
            ),
            "cr_refunded_cdemo_sk": _sk(rng.integers(0, d["cd"], n)),
            "cr_refunded_hdemo_sk": _sk(rng.integers(0, d["hd"], n)),
            "cr_refunded_addr_sk": _sk(rng.integers(0, d["addr"], n)),
            "cr_returning_customer_sk": _sk(
                cs.columns["cs_bill_customer_sk"].data[idx]
            ),
            "cr_returning_cdemo_sk": _sk(rng.integers(0, d["cd"], n)),
            "cr_returning_hdemo_sk": _sk(rng.integers(0, d["hd"], n)),
            "cr_returning_addr_sk": _sk(rng.integers(0, d["addr"], n)),
            "cr_call_center_sk": _sk(
                cs.columns["cs_call_center_sk"].data[idx]
            ),
            "cr_catalog_page_sk": _sk(
                cs.columns["cs_catalog_page_sk"].data[idx]
            ),
            "cr_ship_mode_sk": _sk(cs.columns["cs_ship_mode_sk"].data[idx]),
            "cr_warehouse_sk": _sk(cs.columns["cs_warehouse_sk"].data[idx]),
            "cr_reason_sk": _sk(rng.integers(0, d["reason"], n)),
            "cr_order_number": _sk(cs.columns["cs_order_number"].data[idx]),
            "cr_return_quantity": _int(qty),
            "cr_return_amount": _dec(amt),
            "cr_return_tax": _dec(tax),
            "cr_return_amt_inc_tax": _dec(amt + tax),
            "cr_fee": _dec(fee),
            "cr_return_ship_cost": _dec(ship),
            "cr_refunded_cash": _dec(refunded),
            "cr_reversed_charge": _dec(amt - refunded),
            "cr_store_credit": _dec(np.zeros(n)),
            "cr_net_loss": _dec(fee + ship + tax),
        },
    )


def gen_web_sales(sf: float) -> Table:
    n = _scaled(719_384, sf, lo=800)
    rng = np.random.default_rng(8007)
    d = _dims(sf)
    qty = rng.integers(1, 101, n)
    m = _sales_money(rng, n, qty)
    # ~4 lines per order, same site+date per order, VARYING warehouse per
    # line (Q95's "orders shipped from more than one warehouse")
    n_orders = max(n // 4, 1)
    order = rng.integers(0, n_orders, n)
    o_rng = np.random.default_rng(8008)
    o_date = o_rng.integers(_SALES_LO, _SALES_HI, n_orders)
    o_cust = o_rng.integers(0, d["customer"], n_orders)
    o_site = o_rng.integers(0, d["web_site"], n_orders)
    o_addr = o_rng.integers(0, d["addr"], n_orders)
    ship_cost = rng.integers(50, 5000, n) * qty
    return Table(
        "web_sales",
        {
            "ws_sold_date_sk": _sk(o_date[order]),
            "ws_sold_time_sk": _sk(rng.integers(0, 86400, n)),
            "ws_ship_date_sk": _sk(o_date[order] + rng.integers(2, 120, n)),
            "ws_item_sk": _sk(rng.integers(0, d["item"], n)),
            "ws_bill_customer_sk": _sk(o_cust[order]),
            "ws_bill_cdemo_sk": _sk(rng.integers(0, d["cd"], n)),
            "ws_bill_hdemo_sk": _sk(rng.integers(0, d["hd"], n)),
            "ws_bill_addr_sk": _sk(rng.integers(0, d["addr"], n)),
            "ws_ship_customer_sk": _sk_nullable(o_cust[order], rng),
            "ws_ship_cdemo_sk": _sk(rng.integers(0, d["cd"], n)),
            "ws_ship_hdemo_sk": _sk(rng.integers(0, d["hd"], n)),
            "ws_ship_addr_sk": _sk(o_addr[order]),
            "ws_web_page_sk": _sk(rng.integers(0, d["web_page"], n)),
            "ws_web_site_sk": _sk(o_site[order]),
            "ws_ship_mode_sk": _sk(rng.integers(0, d["ship_mode"], n)),
            "ws_warehouse_sk": _sk(rng.integers(0, d["warehouse"], n)),
            "ws_promo_sk": _sk(rng.integers(0, d["promo"], n)),
            "ws_order_number": _sk(order),
            "ws_quantity": _int(qty),
            "ws_wholesale_cost": _dec(m["wholesale_cost"]),
            "ws_list_price": _dec(m["list_price"]),
            "ws_sales_price": _dec(m["sales_price"]),
            "ws_ext_discount_amt": _dec(m["ext_discount_amt"]),
            "ws_ext_sales_price": _dec(m["ext_sales_price"]),
            "ws_ext_wholesale_cost": _dec(m["ext_wholesale_cost"]),
            "ws_ext_list_price": _dec(m["ext_list_price"]),
            "ws_ext_tax": _dec(m["ext_tax"]),
            "ws_coupon_amt": _dec(m["coupon_amt"]),
            "ws_ext_ship_cost": _dec(ship_cost),
            "ws_net_paid": _dec(m["net_paid"]),
            "ws_net_paid_inc_tax": _dec(m["net_paid_inc_tax"]),
            "ws_net_paid_inc_ship": _dec(m["net_paid"] + ship_cost),
            "ws_net_paid_inc_ship_tax": _dec(
                m["net_paid_inc_tax"] + ship_cost
            ),
            "ws_net_profit": _dec(m["net_profit"]),
        },
    )


def gen_web_returns(sf: float) -> Table:
    ws = table("web_sales", sf)
    n_ws = ws.num_rows
    rng = np.random.default_rng(8009)
    n = max(n_ws // 10, 1)
    idx = rng.choice(n_ws, n, replace=False)
    d = _dims(sf)
    qty = np.minimum(
        rng.integers(1, 101, n), ws.columns["ws_quantity"].data[idx]
    )
    amt = ws.columns["ws_sales_price"].data[idx] * qty
    tax = (amt * 0.08).astype(np.int64)
    fee = rng.integers(50, 10000, n)
    ship = rng.integers(100, 5000, n)
    refunded = (amt * rng.uniform(0.3, 1.0, n)).astype(np.int64)
    return Table(
        "web_returns",
        {
            "wr_returned_date_sk": _sk(
                ws.columns["ws_sold_date_sk"].data[idx]
                + rng.integers(1, 60, n)
            ),
            "wr_returned_time_sk": _sk(rng.integers(0, 86400, n)),
            "wr_item_sk": _sk(ws.columns["ws_item_sk"].data[idx]),
            "wr_refunded_customer_sk": _sk(
                ws.columns["ws_bill_customer_sk"].data[idx]
            ),
            "wr_refunded_cdemo_sk": _sk(rng.integers(0, d["cd"], n)),
            "wr_refunded_hdemo_sk": _sk(rng.integers(0, d["hd"], n)),
            "wr_refunded_addr_sk": _sk(rng.integers(0, d["addr"], n)),
            "wr_returning_customer_sk": _sk(
                ws.columns["ws_bill_customer_sk"].data[idx]
            ),
            "wr_returning_cdemo_sk": _sk(rng.integers(0, d["cd"], n)),
            "wr_returning_hdemo_sk": _sk(rng.integers(0, d["hd"], n)),
            "wr_returning_addr_sk": _sk(rng.integers(0, d["addr"], n)),
            "wr_web_page_sk": _sk(ws.columns["ws_web_page_sk"].data[idx]),
            "wr_reason_sk": _sk(rng.integers(0, d["reason"], n)),
            "wr_order_number": _sk(ws.columns["ws_order_number"].data[idx]),
            "wr_return_quantity": _int(qty),
            "wr_return_amt": _dec(amt),
            "wr_return_tax": _dec(tax),
            "wr_return_amt_inc_tax": _dec(amt + tax),
            "wr_fee": _dec(fee),
            "wr_return_ship_cost": _dec(ship),
            "wr_refunded_cash": _dec(refunded),
            "wr_reversed_charge": _dec(amt - refunded),
            "wr_account_credit": _dec(np.zeros(n)),
            "wr_net_loss": _dec(fee + ship + tax),
        },
    )


# ---------------------------------------------------------------------------
# module API (mirrors connectors/tpch.py)
# ---------------------------------------------------------------------------

_FIXED = {
    "date_dim": gen_date_dim,
    "time_dim": gen_time_dim,
    "household_demographics": gen_household_demographics,
    "income_band": gen_income_band,
    "ship_mode": gen_ship_mode,
    "reason": gen_reason,
}
_SCALED = {
    "item": gen_item,
    "customer": gen_customer,
    "customer_address": gen_customer_address,
    "customer_demographics": gen_customer_demographics,
    "store": gen_store,
    "warehouse": gen_warehouse,
    "promotion": gen_promotion,
    "web_site": gen_web_site,
    "web_page": gen_web_page,
    "call_center": gen_call_center,
    "catalog_page": gen_catalog_page,
    "inventory": gen_inventory,
    "store_sales": gen_store_sales,
    "store_returns": gen_store_returns,
    "catalog_sales": gen_catalog_sales,
    "catalog_returns": gen_catalog_returns,
    "web_sales": gen_web_sales,
    "web_returns": gen_web_returns,
}

TABLE_NAMES = sorted([*_FIXED, *_SCALED])

_TABLE_CACHE: Dict = {}


def table(name: str, sf: float = 1.0) -> Table:
    # fixed-size dimensions ignore sf — cache them once per process
    key = name if name in _FIXED else (name, sf)
    tb = _TABLE_CACHE.get(key)
    if tb is None:
        if name in _FIXED:
            tb = _FIXED[name]()
        elif name in _SCALED:
            tb = _SCALED[name](sf)
        else:
            raise KeyError(name)
        _TABLE_CACHE[key] = tb
    return tb


def schema(name: str, sf: float = 0.01):
    # schemas are SF-independent; a tiny instance supplies the types
    tb = table(name, min(sf, 0.01))
    return {cname: c.type for cname, c in tb.columns.items()}


_BASE_ROWS = {
    "date_dim": _N_DATES,
    "time_dim": 86_400,
    "household_demographics": 7_200,
    "income_band": 20,
    "ship_mode": 20,
    "reason": len(_REASONS),
    "item": 18_000,
    "customer": 100_000,
    "customer_address": 50_000,
    "customer_demographics": 1_920_800,
    "store": 12,
    "warehouse": 5,
    "promotion": 300,
    "web_site": 30,
    "web_page": 60,
    "call_center": 6,
    "catalog_page": 11_718,
    "inventory": 11_745_000,
    "store_sales": 2_880_404,
    "store_returns": 288_040,
    "catalog_sales": 1_441_548,
    "catalog_returns": 144_154,
    "web_sales": 719_384,
    "web_returns": 71_938,
}

_UNIQUE_COLUMNS = {
    "date_dim": [("d_date_sk",)],
    "time_dim": [("t_time_sk",)],
    "item": [("i_item_sk",)],
    "customer": [("c_customer_sk",)],
    "customer_address": [("ca_address_sk",)],
    "customer_demographics": [("cd_demo_sk",)],
    "household_demographics": [("hd_demo_sk",)],
    "income_band": [("ib_income_band_sk",)],
    "store": [("s_store_sk",)],
    "warehouse": [("w_warehouse_sk",)],
    "promotion": [("p_promo_sk",)],
    "web_site": [("web_site_sk",)],
    "web_page": [("wp_web_page_sk",)],
    "call_center": [("cc_call_center_sk",)],
    "catalog_page": [("cp_catalog_page_sk",)],
    "ship_mode": [("sm_ship_mode_sk",)],
    "reason": [("r_reason_sk",)],
}


class TpcdsCatalog:
    """Catalog + data provider for the embedded TPC-DS connector (mirrors
    TpchCatalog; reference TpcdsMetadata + tpcds/statistics/)."""

    name = "tpcds"

    def __init__(self, sf: float = 1.0):
        self.sf = sf
        self._pages: Dict[str, object] = {}

    def table_names(self):
        return list(TABLE_NAMES)

    def schema(self, tname: str):
        return schema(tname, self.sf)

    def row_count(self, tname: str) -> int:
        if tname in _FIXED:
            return _BASE_ROWS[tname]
        return max(int(_BASE_ROWS[tname] * self.sf), 1)

    def unique_columns(self, tname: str):
        return _UNIQUE_COLUMNS.get(tname, [])

    def table_version(self, tname: str) -> int:
        """Immutable generated data: constant version, always cacheable
        (exec/qcache.py)."""
        if tname not in TABLE_NAMES:
            raise KeyError(f"table {tname!r} does not exist")
        return 0

    def page(self, tname: str):
        pg = self._pages.get(tname)
        if pg is None:
            pg = self.host_table(tname).to_page()
            self._pages[tname] = pg
        return pg

    def host_table(self, tname: str) -> Table:
        return table(tname, self.sf)

    def exact_row_count(self, tname: str) -> int:
        return self.host_table(tname).num_rows

    def column_stats(self, tname: str, column: str):
        """Exact per-column statistics from the generator's host data
        (reference presto-tpcds tpcds/statistics/), cached."""
        from ..plan.stats import stats_from_column

        cache = getattr(self, "_stats_cache", None)
        if cache is None:
            cache = self._stats_cache = {}
        key = (tname, column)
        if key not in cache:
            col = self.host_table(tname).columns[column]
            cache[key] = stats_from_column(
                col.data,
                getattr(col, "valid", None),
                col.type,
                col.dictionary,
                self.exact_row_count(tname),
            )
        return cache[key]

    def scan(self, tname: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None):
        tb = self.host_table(tname)
        if columns is not None:
            tb = Table(tb.name, {c: tb.columns[c] for c in columns})
        return tb.to_page(start, stop, pad_to=pad_to)
