"""Device-resident TPC-H catalog: SQL scans GENERATE their batches on
device.

Round-4 verdict item 2: the host-fed `TpchCatalog` uploads table data to
the chip, and the axon tunnel wedges on bulk host->device transfers, so
the flagship SQL path could not run at real scale on TPU. The
reference's equivalent design point is worker-side generation —
presto-tpch/src/main/java/com/facebook/presto/tpch/TpchRecordSet.java
materializes rows inside the worker from the split alone, so table data
never crosses the coordinator link. Here the same contract holds against
the HOST-DEVICE link: `scan(table, start, stop)` ships ONE scalar (the
range start) and the splitmix64 column generators (benchmark/benchgen.py)
produce the batch on device under a cached jit.

The numpy twin of the same generators backs the SQLite oracle
(`table(name, sf)` below feeds testing/oracle.SqliteOracle), so every
query over this catalog is oracle-verifiable bit-for-bit; and it backs
`column_stats`, so the CBO sees statistics of exactly the data the device
will generate. nation/region (25/5 rows) stay host-generated — their
upload is a few hundred bytes, far below the tunnel's bulk-transfer
failure mode.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from .. import types as T
from ..benchmark import benchgen
from ..page import Block, Page, intern_dictionary
from . import tpch as tpch_host
from .tpch import Column, Table, TpchCatalog

TABLE_NAMES = sorted(list(benchgen.SCHEMAS) + ["nation", "region"])

_HOST_SMALL = {"nation": tpch_host.gen_nation, "region": tpch_host.gen_region}


def table(name: str, sf: float = 1.0) -> Table:
    """Host-twin Table (numpy, bit-identical to the device data) — the
    SqliteOracle source-module protocol."""
    if name in _HOST_SMALL:
        return _HOST_SMALL[name]()
    schema = benchgen.SCHEMAS[name]
    cols = benchgen.numpy_columns(name, sf, tuple(schema))
    out: Dict[str, Column] = {}
    for c, (typ, pool) in schema.items():
        data = cols[c]
        if pool is not None:
            out[c] = Column(data.astype(np.int32), typ, tuple(pool))
        else:
            out[c] = Column(data.astype(typ.storage_dtype), typ)
    return Table(name, out)


class DeviceTpchCatalog(TpchCatalog):
    """TpchCatalog whose scan path generates batches ON DEVICE."""

    name = "tpch"

    def table_names(self):
        return list(TABLE_NAMES)

    def schema(self, tname: str):
        if tname in _HOST_SMALL:
            return {
                c: col.type for c, col in self.host_table(tname).columns.items()
            }
        return {c: t for c, (t, _pool) in benchgen.SCHEMAS[tname].items()}

    def row_count(self, tname: str) -> int:
        if tname in _HOST_SMALL:
            return self.host_table(tname).num_rows
        return benchgen._sizes(self.sf)[tname]

    def exact_row_count(self, tname: str) -> int:
        return self.row_count(tname)

    def host_table(self, tname: str) -> Table:
        tb = self._tables.get(tname)
        if tb is None:
            tb = table(tname, self.sf)
            self._tables[tname] = tb
        return tb

    def column_stats(self, tname: str, column: str):
        """CBO statistics from the numpy twin; very large tables are
        sampled by prefix (the generators are row-wise stationary, so a
        prefix is representative) to bound host memory at high SF."""
        from ..plan.stats import stats_from_column

        cache = getattr(self, "_stats_cache", None)
        if cache is None:
            cache = self._stats_cache = {}
        key = (tname, column)
        if key not in cache:
            n = self.row_count(tname)
            cap = 2_000_000
            if tname in _HOST_SMALL or n <= cap:
                col = self.host_table(tname).columns[column]
                data, dic = col.data, col.dictionary
                valid = getattr(col, "valid", None)
            else:
                typ, pool = benchgen.SCHEMAS[tname][column]
                data = benchgen.numpy_columns_range(
                    tname, self.sf, (column,), 0, cap
                )[column].astype(typ.storage_dtype)
                dic, valid = pool, None
            cache[key] = stats_from_column(
                data, valid, self.schema(tname)[column], dic, n
            )
        return cache[key]

    def page(self, tname: str) -> Page:
        pg = self._pages.get(tname)
        if pg is None:
            if tname in _HOST_SMALL:
                pg = self.host_table(tname).to_page()
            else:
                pg = benchgen.device_page(
                    tname, self.sf, tuple(benchgen.SCHEMAS[tname])
                )
            self._pages[tname] = pg
        return pg

    def scan(self, tname: str, start: int, stop: int, pad_to=None,
             columns=None, predicate=None) -> Page:
        if tname in _HOST_SMALL:
            return super().scan(
                tname, start, stop, pad_to=pad_to, columns=columns,
                predicate=predicate,
            )
        schema = benchgen.SCHEMAS[tname]
        cols = tuple(columns) if columns is not None else tuple(schema)
        # the streaming driver over-requests the last batch and expects
        # the connector to clamp at table end (exec/stream.py scan loop)
        stop = min(stop, self.row_count(tname))
        start = min(start, stop)
        arrays = benchgen.device_range(
            tname, self.sf, cols, start, stop - start
        )
        blocks = {}
        for c, arr in zip(cols, arrays):
            typ, pool = schema[c]
            did = intern_dictionary(tuple(pool)) if pool is not None else None
            blocks[c] = Block(arr, typ, None, did)
        return Page.from_dict(blocks, pad_to=pad_to)
