"""Hive-analog warehouse connector: partitioned + bucketed parquet tables.

Re-designed equivalent of the reference's flagship presto-hive connector
(46,771 LoC): directory-per-partition layout with a JSON "metastore"
(reference CachingHiveMetastore), partition pruning at scan time
(reference BackgroundHiveSplitLoader + HivePartitionManager), and
bucketed-by-key files enabling co-located bucket joins and bucket-at-a-
time grouped execution (reference HiveBucketing.java +
HiveNodePartitioningProvider; execution/Lifespan.java:26-38 +
PipelineExecutionStrategy.GROUPED_EXECUTION).

TPU-first shape: a partition is a FILE-PRUNING unit (plan/scan-time, host
metadata only — nothing reaches the device for pruned partitions); a
bucket is a MEMORY-BOUNDING unit (the streaming executor joins bucket i
end-to-end before bucket i+1, so the build side resident in HBM is
1/bucket_count of the table). Files are parquet via the same pyarrow
host-decode path as connectors/parquet.py.

Layout under `root/`:

    <table>/_table.json                      # schema + partitioning spec
    <table>/<pcol>=<val>/part-00000.parquet  # unbucketed partition data
    <table>/<pcol>=<val>/bucket-00007.parquet# bucketed: one file per bucket
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page, _pad_block
from .parquet import _arrow_to_type, _type_to_arrow, build_sorted_dictionary
from .spi import Predicate, WritableConnector, WriteError


def _type_name(t: T.Type) -> str:
    return str(t)


def _type_from_name(s: str) -> T.Type:
    return T.parse_type(s)


def bucket_of_values(values: List, count: int) -> np.ndarray:
    """Deterministic bucket assignment (reference HiveBucketing.
    getHashedBucketNumber): ints via splitmix-style mixing, strings via
    crc32 — both sides of a co-located join agree because both were
    written through this function."""
    n = len(values[0]) if values else 0
    acc = np.zeros(n, np.uint64)
    for col in values:
        a = np.asarray(col)
        if a.dtype.kind in "iu":
            h = (a.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15)) * np.uint64(
                0xBF58476D1CE4E5B9
            )
            h ^= h >> np.uint64(31)
        else:
            h = np.array(
                [zlib.crc32(str(v).encode()) for v in col], np.uint64
            )
        acc = (acc * np.uint64(31)) ^ h
    return (acc % np.uint64(count)).astype(np.int64)


@dataclasses.dataclass
class _FileEntry:
    path: str
    partition: Tuple[Tuple[str, str], ...]  # ((col, raw string value), ...)
    bucket: Optional[int]
    rows: int


class HiveCatalog(WritableConnector):
    """root: warehouse directory. Tables are created via
    `create_partitioned_table` (the DDL-properties analog) or the plain
    WritableConnector surface (unpartitioned)."""

    name = "hive"
    SCALED_WRITER_MIN_ROWS = 10_000  # rows per added writer (scaled writers)

    def __init__(self, root: str):
        import pyarrow.parquet as pq

        self.root = root
        self._pq = pq
        os.makedirs(root, exist_ok=True)
        self._meta: Dict[str, dict] = {}
        self._manifest: Dict[str, List[_FileEntry]] = {}
        self._dicts: Dict[Tuple[str, str], tuple] = {}
        # decoded-table LRU: batched scans re-visit the same file many
        # times (batch_rows << file rows); without this every batch decodes
        # the whole parquet file again — O(rows^2/batch) I/O
        self._tbl_cache: Dict[Tuple[str, tuple], object] = {}
        # pruning observability (surfaced via EXPLAIN ANALYZE scan detail)
        self.last_scan_files_read = 0
        self.last_scan_files_skipped = 0
        for t in os.listdir(root):
            if os.path.isfile(self._meta_path(t)):
                self._load_table(t)

    # -- metastore --

    def _meta_path(self, table: str) -> str:
        return os.path.join(self.root, table, "_table.json")

    def _load_table(self, table: str) -> None:
        with open(self._meta_path(table)) as f:
            self._meta[table] = json.load(f)
        self._scan_manifest(table)

    def _save_meta(self, table: str) -> None:
        with open(self._meta_path(table), "w") as f:
            json.dump(self._meta[table], f, indent=1)

    def _scan_manifest(self, table: str) -> None:
        meta = self._meta[table]
        pcols = meta["partitioned_by"]
        entries: List[_FileEntry] = []
        base = os.path.join(self.root, table)

        def walk(d: str, parts: Tuple[Tuple[str, str], ...], depth: int):
            if depth == len(pcols):
                for fn in sorted(os.listdir(d)):
                    if not fn.endswith(".parquet"):
                        continue
                    bucket = None
                    if fn.startswith("bucket-"):
                        bucket = int(fn[len("bucket-"):-len(".parquet")])
                    path = os.path.join(d, fn)
                    rows = self._pq.ParquetFile(path).metadata.num_rows
                    entries.append(_FileEntry(path, parts, bucket, rows))
                return
            want = pcols[depth] + "="
            for sub in sorted(os.listdir(d)):
                if sub.startswith(want):
                    walk(
                        os.path.join(d, sub),
                        parts + ((pcols[depth], sub[len(want):]),),
                        depth + 1,
                    )

        walk(base, (), 0)
        self._manifest[table] = entries

    # -- DDL --

    def create_partitioned_table(
        self,
        table: str,
        schema: Dict[str, T.Type],
        partitioned_by: Sequence[str] = (),
        bucketed_by: Sequence[str] = (),
        bucket_count: int = 0,
    ) -> None:
        if table in self._meta:
            raise WriteError(f"table {table} exists")
        for c in list(partitioned_by) + list(bucketed_by):
            if c not in schema:
                raise WriteError(f"unknown partition/bucket column {c!r}")
        if bool(bucketed_by) != bool(bucket_count):
            raise WriteError("bucketed_by requires bucket_count and vice versa")
        os.makedirs(os.path.join(self.root, table), exist_ok=True)
        self._meta[table] = {
            "schema": {c: _type_name(t) for c, t in schema.items()},
            "partitioned_by": list(partitioned_by),
            "bucketed_by": list(bucketed_by),
            "bucket_count": int(bucket_count),
        }
        self._save_meta(table)
        self._manifest[table] = []

    def create_table(self, table: str, schema: Dict[str, T.Type]) -> None:
        self.create_partitioned_table(table, schema)

    def create_table_from_page(self, table: str, page: Page) -> None:
        self.create_table(
            table, {n: b.type for n, b in zip(page.names, page.blocks)}
        )
        self.append(table, page)

    def drop_table(self, table: str) -> None:
        import shutil

        if table not in self._meta:
            raise WriteError(f"unknown table {table}")
        shutil.rmtree(os.path.join(self.root, table))
        prefix = os.path.join(self.root, table) + os.sep
        self._tbl_cache = {
            k: v for k, v in self._tbl_cache.items()
            if not k[0].startswith(prefix)
        }
        self._meta.pop(table)
        self._manifest.pop(table, None)
        self._dicts = {
            k: v for k, v in self._dicts.items() if k[0] != table
        }

    # -- writes --

    def _page_host_columns(self, table: str, page: Page) -> Dict[str, list]:
        """Decode a result Page to host python/numpy values per column."""
        rows = page.to_pylist()
        return {
            n: [r[i] for r in rows] for i, n in enumerate(page.names)
        }

    def append(self, table: str, page: Page) -> None:
        import pyarrow as pa

        meta = self._meta.get(table)
        if meta is None:
            raise WriteError(f"unknown table {table}")
        schema = self.schema(table)
        if list(page.names) != list(schema):
            raise WriteError(
                f"insert columns {page.names} != table columns "
                f"{tuple(schema)}"
            )
        cols = self._page_host_columns(table, page)
        n = int(page.count)
        pcols = meta["partitioned_by"]
        bcols = meta["bucketed_by"]
        bcount = meta["bucket_count"]

        # partition key per row (raw string form for the directory name)
        if pcols:
            pkeys = list(zip(*[[str(v) for v in cols[c]] for c in pcols]))
        else:
            pkeys = [()] * n
        buckets = (
            bucket_of_values([cols[c] for c in bcols], bcount)
            if bcols
            else np.zeros(n, np.int64)
        )
        import collections

        groups: Dict[tuple, List[int]] = collections.defaultdict(list)
        for i in range(n):
            groups[(pkeys[i], int(buckets[i]) if bcols else None)].append(i)

        arrow_schema = pa.schema(
            [(c, _type_to_arrow(t)) for c, t in schema.items()]
        )
        # numpy object gathers keep per-row work out of Python loops
        np_cols = {c: np.asarray(v, object) for c, v in cols.items()}

        def write_group(item):
            (pkey, bucket), idxs = item
            d = os.path.join(self.root, table)
            for c, v in zip(pcols, pkey):
                d = os.path.join(d, f"{c}={v}")
            os.makedirs(d, exist_ok=True)
            if bucket is None:
                seq = len(
                    [f for f in os.listdir(d) if f.startswith("part-")]
                )
                path = os.path.join(d, f"part-{seq:05d}.parquet")
            else:
                path = os.path.join(d, f"bucket-{bucket:05d}.parquet")
            idx = np.asarray(idxs, np.int64)
            arrays = [
                pa.array(np_cols[c][idx], _type_to_arrow(t))
                for c, t in schema.items()
            ]
            tbl = pa.Table.from_arrays(arrays, schema=arrow_schema)
            if os.path.exists(path):
                old = self._pq.read_table(path)
                tbl = pa.concat_tables([old, tbl])
            self._pq.write_table(tbl, path, row_group_size=1 << 17)

        # SCALED WRITERS (reference SystemPartitioningHandle.java:62 +
        # ScaledWriterScheduler: writer parallelism grows with produced
        # data): one in-line writer for small inserts; a thread pool
        # sized by data volume for large multi-file ones (the heavy
        # arrow-conversion + parquet encode + IO release the GIL)
        items = list(groups.items())
        writers = 1
        if len(items) > 1 and n >= self.SCALED_WRITER_MIN_ROWS:
            writers = min(
                len(items),
                max(2, n // self.SCALED_WRITER_MIN_ROWS),
                8,
            )
        self.last_write_writers = writers
        if writers == 1:
            for item in items:
                write_group(item)
        else:
            # distinct (partition, bucket) targets: no two writers touch
            # the same file
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=writers) as pool:
                list(pool.map(write_group, items))
        self._dicts = {
            k: v for k, v in self._dicts.items() if k[0] != table
        }
        prefix = os.path.join(self.root, table) + os.sep
        self._tbl_cache = {
            k: v for k, v in self._tbl_cache.items()
            if not k[0].startswith(prefix)
        }
        self._scan_manifest(table)

    def replace(self, table: str, page: Page) -> None:
        meta = dict(self._meta[table])
        self.drop_table(table)
        self._meta[table] = meta
        os.makedirs(os.path.join(self.root, table), exist_ok=True)
        self._save_meta(table)
        self._manifest[table] = []
        self.append(table, page)

    # -- metadata --

    def table_names(self) -> List[str]:
        return sorted(self._meta)

    def schema(self, table: str) -> Dict[str, T.Type]:
        return {
            c: _type_from_name(s)
            for c, s in self._meta[table]["schema"].items()
        }

    def row_count(self, table: str) -> int:
        return sum(e.rows for e in self._manifest[table])

    def exact_row_count(self, table: str) -> int:
        return self.row_count(table)

    def unique_columns(self, table: str):
        return []

    def bucketing(self, table: str) -> Optional[Tuple[Tuple[str, ...], int]]:
        """(bucket columns, bucket count) when the table is bucketed —
        the grouped-execution contract consumed by the streaming
        executor (reference ConnectorBucketNodeMap)."""
        meta = self._meta.get(table)
        if not meta or not meta["bucketed_by"]:
            return None
        return tuple(meta["bucketed_by"]), meta["bucket_count"]

    def bucket_row_ranges(self, table: str, bucket: int) -> List[Tuple[int, int]]:
        """Global [start, stop) row ranges holding the given bucket."""
        out = []
        off = 0
        for e in self._manifest[table]:
            if e.bucket == bucket:
                out.append((off, off + e.rows))
            off += e.rows
        return out

    # -- partition pruning --

    def _prune(self, table: str, predicate: Optional[Predicate]):
        """Manifest entries surviving the predicate's constraints on
        partition columns (plan-time file pruning — reference
        HivePartitionManager.getPartitions). `predicate` is the SPI hint
        list [(source_column, op, value), ...]."""
        entries = self._manifest[table]
        if not predicate:
            return entries, 0
        import datetime as pydt

        schema = self.schema(table)

        def pval(col: str, raw: str):
            t = schema[col]
            if isinstance(t, T.DateType):
                try:
                    return pydt.date.fromisoformat(raw)
                except ValueError:
                    return raw
            if isinstance(t, T.VarcharType):
                return raw
            try:
                return float(raw) if "." in raw else int(raw)
            except ValueError:
                return raw

        ops = {
            "eq": lambda a, b: a == b,
            "lt": lambda a, b: a < b,
            "le": lambda a, b: a <= b,
            "gt": lambda a, b: a > b,
            "ge": lambda a, b: a >= b,
        }
        kept = []
        skipped = 0
        for e in entries:
            vals = {c: pval(c, raw) for c, raw in e.partition}
            ok = True
            for col, op, v in predicate:
                if col not in vals or op not in ops:
                    continue
                try:
                    if not ops[op](vals[col], v):
                        ok = False
                        break
                except TypeError:
                    continue
            if ok:
                kept.append(e)
            else:
                skipped += 1
        return kept, skipped

    # -- reads --

    def page(self, table: str) -> Page:
        return self.scan(table, 0, self.row_count(table))

    def _dictionary(self, table: str, column: str):
        key = (table, column)
        d = self._dicts.get(key)
        if d is None:
            import pyarrow as pa

            chunks = []
            for e in self._manifest[table]:
                pf = self._pq.ParquetFile(e.path)
                if column in pf.schema_arrow.names:
                    chunks.append(pf.read(columns=[column]).column(0))
            col = (
                pa.chunked_array(chunks)
                if chunks
                else pa.chunked_array([pa.array([], pa.string())])
            )
            d = build_sorted_dictionary(col)
            self._dicts[key] = d
        return d

    def scan(
        self,
        table: str,
        start: int,
        stop: int,
        pad_to: Optional[int] = None,
        columns: Optional[List[str]] = None,
        predicate: Optional[Predicate] = None,
    ) -> Page:
        """Slice of the manifest-ordered concatenation of files; files in
        PRUNED partitions contribute no rows (they cannot satisfy the
        predicate) — the range simply comes back short."""
        schema = self.schema(table)
        names = list(columns) if columns is not None else list(schema)
        kept, skipped = self._prune(table, predicate)
        kept_set = {id(e) for e in kept}
        self.last_scan_files_read = len(kept)
        self.last_scan_files_skipped = skipped

        pieces: List[Dict[str, np.ndarray]] = []
        off = 0
        for e in self._manifest[table]:
            e_start, e_stop = off, off + e.rows
            off = e_stop
            lo, hi = max(start, e_start), min(stop, e_stop)
            if lo >= hi or id(e) not in kept_set:
                continue
            ck = (e.path, tuple(names))
            tbl = self._tbl_cache.get(ck)
            if tbl is None:
                tbl = self._pq.ParquetFile(e.path).read(columns=names)
                self._tbl_cache[ck] = tbl
                while len(self._tbl_cache) > 2:  # bound host RAM
                    self._tbl_cache.pop(next(iter(self._tbl_cache)))
            sl = tbl.slice(lo - e_start, hi - lo)
            piece: Dict[str, np.ndarray] = {}
            for c in names:
                piece[c] = sl.column(c)
            pieces.append(piece)

        blocks = []
        total = sum(len(p[names[0]]) for p in pieces) if pieces else 0
        for c in names:
            t = schema[c]
            if isinstance(t, T.VarcharType):
                sorted_d, d_arr = self._dictionary(table, c)
                codes = []
                valids = []
                for p in pieces:
                    vals = p[c].to_pylist()
                    codes.append(
                        np.searchsorted(
                            d_arr, np.array(
                                [v if v is not None else "" for v in vals],
                                object,
                            )
                        ).astype(np.int32)
                    )
                    valids.append(
                        np.array([v is not None for v in vals], bool)
                    )
                data = (
                    np.concatenate(codes) if codes else np.empty(0, np.int32)
                )
                valid = (
                    np.concatenate(valids) if valids else np.empty(0, bool)
                )
                blk = Block.from_numpy(
                    data, t,
                    valid=None if valid.all() else valid,
                    dictionary=sorted_d,
                )
            else:
                arrs = []
                valids = []
                for p in pieces:
                    a = p[c]
                    npv = a.to_numpy(zero_copy_only=False)
                    if isinstance(t, T.DecimalType):
                        npv = np.array(
                            [
                                0 if v is None else int(v.scaleb(t.scale))
                                for v in a.to_pylist()
                            ],
                            np.int64,
                        )
                    elif isinstance(t, T.DateType):
                        npv = np.asarray(npv, "datetime64[D]").astype(
                            np.int32
                        )
                    valids.append(~np.asarray(a.is_null()))
                    arrs.append(npv)
                if arrs:
                    data = np.concatenate(arrs)
                    valid = np.concatenate(valids)
                else:
                    data = np.empty(0, t.storage_dtype if hasattr(t, "storage_dtype") else np.int64)
                    valid = np.empty(0, bool)
                if isinstance(t, T.DateType):
                    data = data.astype(np.int32)
                blk = Block.from_numpy(
                    data, t, valid=None if valid.all() else valid
                )
            if pad_to is not None and pad_to > total:
                blk = _pad_block(blk, pad_to)
            blocks.append(blk)
        return Page.from_blocks(blocks, names, count=total)
