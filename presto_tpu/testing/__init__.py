"""Testing utilities: the SQL correctness oracle + assertion helpers.

Equivalent of the reference's presto-tests harness: QueryAssertions runs
each query on Presto AND on H2 and diffs results
(presto-tests/.../QueryAssertions.java:94-116, H2QueryRunner). Here the
oracle is SQLite (in stdlib), with a small dialect transpiler for the
date/interval/extract constructs SQLite lacks.
"""

from .oracle import SqliteOracle, assert_same_results  # noqa: F401
