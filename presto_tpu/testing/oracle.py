"""SQLite correctness oracle.

Loads generated TPC-H tables into an in-memory SQLite database (dates as
ISO text, decimals as REAL) and runs a lightly transpiled version of each
query. Results are compared with type-aware tolerances: decimal columns
allow half-ulp-of-scale slack (our engine rounds HALF_UP in scaled ints,
SQLite computes in binary floats), doubles compare relatively, everything
else exactly.
"""

from __future__ import annotations

import datetime
import re
import sqlite3
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import types as T
from ..connectors import tpch


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


class _VarSamp:
    """Welford online variance (sample)."""

    def __init__(self):
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0

    def step(self, v):
        if v is None:
            return
        v = float(v)
        self.n += 1
        d = v - self.mean
        self.mean += d / self.n
        self.m2 += d * (v - self.mean)

    def finalize(self):
        return self.m2 / (self.n - 1) if self.n > 1 else None


class _StdDevSamp(_VarSamp):
    def finalize(self):
        var = super().finalize()
        return None if var is None else var**0.5


def _decode_column(col: tpch.Column) -> list:
    vals = _decode_values(col)
    if col.valid is not None:
        vals = [v if ok else None for v, ok in zip(vals, col.valid.tolist())]
    return vals


def _decode_values(col: tpch.Column) -> list:
    if isinstance(col.type, T.VarcharType):
        d = col.dictionary
        codes = col.data.tolist()
        if d is None:
            return codes
        cache: Dict[int, str] = {}
        out = []
        for c in codes:
            s = cache.get(c)
            if s is None:
                s = d[c]
                cache[c] = s
            out.append(s)
        return out
    if isinstance(col.type, T.DateType):
        base = datetime.date(1970, 1, 1)
        return [
            (base + datetime.timedelta(days=int(v))).isoformat()
            for v in col.data.tolist()
        ]
    if isinstance(col.type, T.DecimalType):
        s = 10**col.type.scale
        return [v / s for v in col.data.tolist()]
    return col.data.tolist()


_INDEXES = {
    "lineitem": ["l_orderkey", "l_partkey", "l_suppkey", "l_shipdate"],
    "orders": ["o_orderkey", "o_custkey", "o_orderdate"],
    "customer": ["c_custkey", "c_nationkey"],
    "part": ["p_partkey"],
    "partsupp": ["ps_partkey", "ps_suppkey"],
    "supplier": ["s_suppkey", "s_nationkey"],
    "nation": ["n_nationkey", "n_regionkey"],
    "region": ["r_regionkey"],
}


class SqliteOracle:
    """`source` is a generator module exposing table(name, sf) and
    TABLE_NAMES — connectors.tpch (default) or connectors.tpcds."""

    def __init__(
        self,
        sf: float = 0.01,
        tables: Optional[Sequence[str]] = None,
        source=tpch,
    ):
        self.conn = sqlite3.connect(":memory:")
        # SQLite has no stddev family; register Welford aggregates so
        # TPC-DS Q17/Q39 oracle SQL can stay the spec text
        self.conn.create_aggregate("stddev_samp", 1, _StdDevSamp)
        self.conn.create_aggregate("var_samp", 1, _VarSamp)
        # SQLite's math functions (sign, log10, ...) are compile-time
        # optional and only standard since 3.35; probe each and register
        # a Python fallback when the linked library lacks it so math
        # oracle SQL runs unmodified
        import math as _m

        def _null_safe(fn):
            return lambda *a: None if any(v is None for v in a) else fn(*a)

        for fname, nargs, fn, probe in (
            ("sign", 1, lambda v: (v > 0) - (v < 0), "sign(-1)"),
            ("log10", 1, _m.log10, "log10(1)"),
            ("log2", 1, _m.log2, "log2(1)"),
            ("ln", 1, _m.log, "ln(1)"),
            ("exp", 1, _m.exp, "exp(0)"),
            ("sqrt", 1, _m.sqrt, "sqrt(1)"),
            ("power", 2, lambda b, e: float(b) ** float(e), "power(2, 2)"),
            ("degrees", 1, _m.degrees, "degrees(0)"),
            ("radians", 1, _m.radians, "radians(0)"),
            ("mod", 2, _m.fmod, "mod(4, 2)"),
            ("pi", 0, _m.pi.__float__, "pi()"),
            ("sin", 1, _m.sin, "sin(0)"),
            ("cos", 1, _m.cos, "cos(0)"),
            ("tan", 1, _m.tan, "tan(0)"),
            ("asin", 1, _m.asin, "asin(0)"),
            ("acos", 1, _m.acos, "acos(1)"),
            ("atan", 1, _m.atan, "atan(0)"),
            ("atan2", 2, _m.atan2, "atan2(0, 1)"),
            ("floor", 1, _m.floor, "floor(0.5)"),
            ("ceil", 1, _m.ceil, "ceil(0.5)"),
            ("ceiling", 1, _m.ceil, "ceiling(0.5)"),
        ):
            try:
                self.conn.execute(f"SELECT {probe}").fetchone()
            except sqlite3.OperationalError:
                self.conn.create_function(
                    fname, nargs, _null_safe(fn), deterministic=True
                )
        for name in tables or source.TABLE_NAMES:
            t = source.table(name, sf)
            cols = list(t.columns.keys())
            self.conn.execute(
                f"CREATE TABLE {name} ({', '.join(cols)})"
            )
            data = [_decode_column(c) for c in t.columns.values()]
            rows = list(zip(*data))
            self.conn.executemany(
                f"INSERT INTO {name} VALUES ({', '.join('?' * len(cols))})",
                rows,
            )
            # TPC-DS key columns end in _sk; the TPC-H names are listed
            indexed = [c for c in cols if c.endswith("_sk")] or _INDEXES.get(
                name, []
            )
            for c in indexed:
                self.conn.execute(f"CREATE INDEX idx_{name}_{c} ON {name}({c})")
        self.conn.commit()

    def query(self, sql: str) -> List[tuple]:
        cur = self.conn.execute(transpile(sql))
        return [tuple(r) for r in cur.fetchall()]


# ---------------------------------------------------------------------------
# dialect transpiler (TPC-H constructs SQLite lacks)
# ---------------------------------------------------------------------------

_DATE_ARith = re.compile(
    r"date\s*'(\d{4}-\d{2}-\d{2})'\s*([+-])\s*interval\s*'(\d+)'\s*(day|month|year)",
    re.IGNORECASE,
)
_DATE_LIT = re.compile(r"date\s*'(\d{4}-\d{2}-\d{2})'", re.IGNORECASE)
_EXTRACT = re.compile(r"extract\s*\(\s*(year|month|day)\s+from\s+", re.IGNORECASE)
_SUBSTRING = re.compile(
    r"substring\s*\(\s*([A-Za-z_][\w.]*)\s+from\s+(\d+)\s+for\s+(\d+)\s*\)",
    re.IGNORECASE,
)

_FMT = {"year": "%Y", "month": "%m", "day": "%d"}


def _add_interval(date_str: str, sign: str, n: int, unit: str) -> str:
    d = datetime.date.fromisoformat(date_str)
    k = -n if sign == "-" else n
    if unit == "day":
        d = d + datetime.timedelta(days=k)
    elif unit == "month":
        m = d.month - 1 + k
        d = d.replace(year=d.year + m // 12, month=m % 12 + 1)
    else:
        d = d.replace(year=d.year + k)
    return d.isoformat()


# constant decimal arithmetic folded exactly: SQLite evaluates 0.06 + 0.01
# in binary floats (0.069999...), silently corrupting decimal-boundary
# predicates like Q6's BETWEEN. Both operands must be literals and the
# expression must sit right after a token that makes precedence unambiguous.
_CONST_FOLD = re.compile(
    r"(\(|=|<|>|,|\bbetween\b|\band\b|\bthen\b|\belse\b|\bwhen\b)"
    r"(\s*)(\d+(?:\.\d+)?)\s*([-+*/])\s*(\d+(?:\.\d+)?)",
    re.IGNORECASE,
)

_DERIVED_ALIAS = re.compile(r"\)\s*as\s+(\w+)\s*\(([\w\s,]*)\)", re.IGNORECASE)


def _fold_constants(sql: str) -> str:
    from decimal import Decimal

    def fold(m):
        a, op, b = Decimal(m.group(3)), m.group(4), Decimal(m.group(5))
        v = {
            "+": a + b,
            "-": a - b,
            "*": a * b,
            "/": a / b if b != 0 else None,
        }[op]
        if v is None:
            return m.group(0)
        return f"{m.group(1)}{m.group(2)}{v}"

    prev = None
    while prev != sql:
        prev = sql
        sql = _CONST_FOLD.sub(fold, sql)
    return sql


def transpile(sql: str) -> str:
    def arith(m):
        return "'" + _add_interval(
            m.group(1), m.group(2), int(m.group(3)), m.group(4).lower()
        ) + "'"

    out = _DATE_ARith.sub(arith, sql)
    out = _DATE_LIT.sub(lambda m: f"'{m.group(1)}'", out)
    out = _fold_constants(out)
    # SQLite lacks derived column aliases `AS t (c1, c2)` — rely on inner
    # select aliases matching instead
    out = _DERIVED_ALIAS.sub(lambda m: f") as {m.group(1)}", out)

    # extract(year from X) -> cast(strftime('%Y', X) as integer); need to
    # find the matching close paren
    while True:
        m = _EXTRACT.search(out)
        if not m:
            break
        start = m.end()
        depth = 1
        i = start
        while depth > 0:
            if out[i] == "(":
                depth += 1
            elif out[i] == ")":
                depth -= 1
            i += 1
        inner = out[start : i - 1]
        field = m.group(1).lower()
        repl = f"cast(strftime('{_FMT[field]}', {inner}) as integer)"
        out = out[: m.start()] + repl + out[i:]

    out = _SUBSTRING.sub(lambda m: f"substr({m.group(1)}, {m.group(2)}, {m.group(3)})", out)
    return out


# ---------------------------------------------------------------------------
# result comparison
# ---------------------------------------------------------------------------


def _canon(v):
    import decimal

    if v is None:
        return None
    if isinstance(v, decimal.Decimal):
        return float(v)
    if isinstance(v, np.datetime64):
        return str(v)[:10]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, datetime.date):
        return v.isoformat()
    return v


def _sort_key(row):
    out = []
    for v in row:
        if v is None:
            out.append((0, ""))
        elif isinstance(v, (int, float)):
            out.append((1, round(float(v), 4)))
        else:
            out.append((2, str(v)))
    return tuple(out)


def _value_close(a, b, tol: float) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        a, b = float(a), float(b)
        return abs(a - b) <= max(tol, 1e-9 * max(abs(a), abs(b)))
    return a == b


def assert_same_results(
    ours: List[tuple],
    oracle: List[tuple],
    types: Optional[Sequence[T.Type]] = None,
    ordered: bool = False,
):
    """Diff engine results against the oracle (reference
    QueryAssertions.assertEqualsIgnoreOrder semantics + tolerance)."""
    a = [tuple(_canon(v) for v in r) for r in ours]
    b = [tuple(_canon(v) for v in r) for r in oracle]
    if not ordered:
        a = sorted(a, key=_sort_key)
        b = sorted(b, key=_sort_key)
    assert len(a) == len(b), f"row count {len(a)} != oracle {len(b)}\nours[:5]={a[:5]}\noracle[:5]={b[:5]}"
    tols = []
    ncols = len(a[0]) if a else 0
    for i in range(ncols):
        tol = 1e-9
        if types is not None and i < len(types):
            ty = types[i]
            if isinstance(ty, T.DecimalType):
                tol = 0.5 * 10 ** (-ty.scale) + 1e-9
            elif T.is_floating(ty):
                tol = 1e-6
        else:
            tol = 1e-6
        tols.append(tol)
    for ri, (ra, rb) in enumerate(zip(a, b)):
        for ci, (va, vb) in enumerate(zip(ra, rb)):
            assert _value_close(va, vb, tols[ci] if ci < len(tols) else 1e-6), (
                f"row {ri} col {ci}: {va!r} != oracle {vb!r}\n"
                f"ours: {ra}\noracle: {rb}"
            )
