"""PEP 249 (DB-API 2.0) client over the REST statement protocol.

Re-designed equivalent of presto-jdbc (presto-jdbc/src/main/java/com/
facebook/presto/jdbc/ — PrestoConnection/PrestoStatement/PrestoResultSet
over the same /v1/statement protocol). `qmark` parameters are bound
SERVER-SIDE: the statement text (with its `?` placeholders intact) is
PREPAREd once per connection and each execute sends
`EXECUTE <name> USING <literals>`, where the values appear only in the
USING list as typed literals the server parses and binds as constants —
never spliced into arbitrary SQL positions (the old client-side
substitution was both a quoting/injection hazard and a plan-cache key
leak: every distinct value produced a distinct statement text, so no two
executions could share a cached plan skeleton; see exec/qcache.py).

    import presto_tpu.dbapi as dbapi
    conn = dbapi.connect("http://localhost:8080")
    cur = conn.cursor()
    cur.execute("select * from t where x > ?", (5,))
    print(cur.fetchall())
"""

from __future__ import annotations

import datetime
import decimal
from typing import List, Optional, Sequence, Tuple

apilevel = "2.0"
threadsafety = 1  # threads may share the module, not connections
paramstyle = "qmark"


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class ProgrammingError(DatabaseError):
    pass


def _escape(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float, decimal.Decimal)):
        return str(v)
    if isinstance(v, datetime.datetime):
        return f"timestamp '{v.isoformat(sep=' ')}'"
    if isinstance(v, datetime.date):
        return f"date '{v.isoformat()}'"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    raise InterfaceError(f"cannot bind parameter of type {type(v).__name__}")


def _substitute(sql: str, params: Sequence) -> str:
    """Replace ? placeholders outside string literals, quoted identifiers,
    and comments. LEGACY: kept for callers that need a textualized
    statement (and for tests of the escaper); Cursor.execute now binds
    server-side via PREPARE/EXECUTE USING instead."""
    out = []
    it = iter(params)
    i = 0
    n = len(sql)
    while i < n:
        c = sql[i]
        if c in ("'", '"'):  # string literal / quoted ident ('' "" escapes)
            q = c
            j = i + 1
            while j < n:
                if sql[j] == q:
                    if j + 1 < n and sql[j + 1] == q:
                        j += 2
                        continue
                    break
                j += 1
            out.append(sql[i : j + 1])
            i = j + 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":  # -- line comment
            j = sql.find("\n", i)
            j = n if j < 0 else j
            out.append(sql[i:j])
            i = j
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":  # /* block */
            j = sql.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append(sql[i:j])
            i = j
            continue
        if c == "?":
            try:
                out.append(_escape(next(it)))
            except StopIteration:
                raise ProgrammingError("not enough parameters") from None
            i += 1
            continue
        out.append(c)
        i += 1
    try:
        next(it)
        raise ProgrammingError("too many parameters")
    except StopIteration:
        pass
    return "".join(out)


class Cursor:
    arraysize = 1

    def __init__(self, conn: "Connection"):
        self._conn = conn
        self.description: Optional[List[tuple]] = None
        self.rowcount = -1
        self._rows: List[tuple] = []
        self._pos = 0
        self._closed = False

    # -- execution --

    def execute(self, operation: str, parameters: Sequence = ()) -> "Cursor":
        self._check()
        try:
            if parameters:
                cols, rows = self._conn._execute_prepared(
                    operation, parameters
                )
            else:
                cols, rows = self._conn._client.execute(operation)
        except Error:
            raise
        except Exception as e:  # noqa: BLE001 - wrap in DB-API error
            msg = str(e)
            if "parameters" in msg and "expects" in msg:
                raise ProgrammingError(msg) from e
            raise DatabaseError(msg) from e
        self.description = [
            (c["name"], c["type"], None, None, None, None, None)
            for c in (cols or [])
        ]
        self._rows = [tuple(r) for r in rows]
        self._pos = 0
        self.rowcount = len(self._rows)
        return self

    def executemany(self, operation: str, seq_of_parameters) -> "Cursor":
        for p in seq_of_parameters:
            self.execute(operation, p)
        return self

    # -- fetching --

    def fetchone(self) -> Optional[tuple]:
        self._check()
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> List[tuple]:
        self._check()
        size = size or self.arraysize
        out = self._rows[self._pos : self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self) -> List[tuple]:
        self._check()
        out = self._rows[self._pos :]
        self._pos = len(self._rows)
        return out

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    # -- boilerplate --

    def setinputsizes(self, sizes):  # noqa: D102 - PEP 249 no-op
        pass

    def setoutputsize(self, size, column=None):  # noqa: D102 - PEP 249 no-op
        pass

    def close(self):
        self._closed = True

    def _check(self):
        if self._closed or self._conn._closed:
            raise InterfaceError("cursor is closed")


class Connection:
    def __init__(self, uri: str, timeout: float = 300.0):
        from .server.client import Client

        self._client = Client(uri, timeout=timeout)
        self._closed = False
        self._prepared: dict = {}  # statement text -> server-side name

    # -- server-side parameter binding --

    def _prepare(self, operation: str) -> str:
        """PREPARE `operation` once per connection under a deterministic
        content-hashed name (concurrent connections preparing the same
        text collide onto the identical statement — benign)."""
        name = self._prepared.get(operation)
        if name is None:
            import hashlib

            name = "dbapi_" + hashlib.sha1(
                operation.encode()
            ).hexdigest()[:16]
            self._client.execute(f"prepare {name} from {operation}")
            self._prepared[operation] = name
        return name

    def _execute_prepared(self, operation: str, parameters: Sequence):
        using = ", ".join(_escape(v) for v in parameters)
        name = self._prepare(operation)
        sql = f"execute {name} using {using}"
        try:
            return self._client.execute(sql)
        except Exception as e:  # noqa: BLE001
            # match the server's specific missing-statement error, not any
            # message containing "not found" (404s say that too)
            if "prepared statement" in str(e) and "not found" in str(e):
                # server restarted / session recycled: re-prepare once
                self._prepared.pop(operation, None)
                name = self._prepare(operation)
                return self._client.execute(f"execute {name} using {using}")
            raise

    def cursor(self) -> Cursor:
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def commit(self):  # autocommit protocol; present per PEP 249
        pass

    def rollback(self):
        raise DatabaseError("transactions are not supported")

    def close(self):
        # DEALLOCATE this connection's server-side statements: the
        # coordinator session is shared, so leaked names would grow its
        # prepared map for the process lifetime. Best-effort — another
        # connection using the same content-hashed name simply re-PREPAREs.
        for name in self._prepared.values():
            try:
                self._client.execute(f"deallocate prepare {name}")
            except Exception:  # noqa: BLE001 — closing must not raise
                pass
        self._prepared.clear()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def connect(uri: str, timeout: float = 300.0) -> Connection:
    return Connection(uri, timeout=timeout)
