"""prestolint: repo-specific AST static analysis, gated in tier-1.

Run with ``python -m presto_tpu.analysis --check``. See
docs/static-analysis.md for the pass catalog, the baseline/suppression
workflow, and how to add a pass."""

from __future__ import annotations

import os
from pathlib import Path
from typing import List, Optional, Sequence

from .core import (
    CheckResult,
    Finding,
    Project,
    evaluate_against_baseline,
    load_baseline,
    load_project,
    save_baseline,
)
from .passes import ALL_PASSES, PASSES_BY_NAME

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def run_passes(
    project: Project, passes: Optional[Sequence] = None
) -> List[Finding]:
    """All findings from `passes` (default: every registered pass), with
    source-level `# prestolint: allow(rule)` suppressions applied."""
    out: List[Finding] = []
    for p in passes if passes is not None else ALL_PASSES:
        for f in p.run(project):
            sf = project.file(f.file)
            if sf is not None and sf.suppressed(f.line, f.rule):
                continue
            out.append(f)
    return out


def run_check(
    repo_root: Optional[os.PathLike] = None,
    baseline_path: Optional[os.PathLike] = None,
    passes: Optional[Sequence] = None,
) -> CheckResult:
    project = load_project(repo_root)
    findings = run_passes(project, passes)
    baseline = load_baseline(
        baseline_path if baseline_path is not None else DEFAULT_BASELINE
    )
    if passes is not None:
        # a scoped check only produced the selected passes' findings:
        # other passes' baseline entries must not be reported stale
        owned = {r for p in passes for r in p.rules}
        baseline = {
            fp: e for fp, e in baseline.items() if e["rule"] in owned
        }
    return evaluate_against_baseline(findings, baseline)


def update_baseline(
    repo_root: Optional[os.PathLike] = None,
    baseline_path: Optional[os.PathLike] = None,
    passes: Optional[Sequence] = None,
) -> int:
    """Regenerate the baseline. With a `passes` subset, only entries for
    those passes' declared rules are regenerated — everything else in the
    existing baseline is preserved verbatim, so scoping the update to one
    pass can't silently suppress another pass's open findings."""
    project = load_project(repo_root)
    path = baseline_path if baseline_path is not None else DEFAULT_BASELINE
    findings = run_passes(project, passes)
    if passes is None:
        save_baseline(path, findings)
        return len(findings)
    owned = {r for p in passes for r in p.rules}
    kept = [
        e for e in load_baseline(path).values() if e["rule"] not in owned
    ]
    save_baseline(path, findings, keep=kept)
    return len(findings) + len(kept)


__all__ = [
    "ALL_PASSES",
    "PASSES_BY_NAME",
    "CheckResult",
    "Finding",
    "run_check",
    "run_passes",
    "update_baseline",
    "load_project",
]
