"""prestolint pass framework.

The reference engine front-loads failure detection: the planner runs
PlanSanityChecker validators after every optimizer rule and the
bytecode-gen layer fails at generation time, not execution time
(presto-main/.../sql/planner/sanity/, .../sql/gen/). This reproduction's
equivalents — plan rewrites, jitted kernels, threaded server code — fail
at runtime, sometimes by deadlocking. prestolint is the analog: a small
AST pass framework with repo-specific rules (tracing safety, lock
discipline, exception hygiene, plan-node exhaustiveness, memory
accounting), gated in tier-1 so "added a node, forgot a dispatcher" or
"host callback reachable from jit" fails at lint time.

Design:

- every ``.py`` file under ``presto_tpu/`` parses once into a
  :class:`SourceFile` (ast tree + raw lines, for suppression comments);
- passes subclass :class:`AnalysisPass` and emit :class:`Finding`s with a
  rule id, severity, file, line and the enclosing def/class context;
- ``# prestolint: allow(rule-id) -- reason`` on the finding's line (or
  the line above) suppresses it at the source;
- pre-existing findings live in a committed ``baseline.json``; ``--check``
  fails only on NEW findings, so the suite could gate tier-1 from day one
  while the burndown proceeded. Fingerprints hash (rule, file, enclosing
  context, message) — NOT line numbers — so unrelated edits above a
  finding don't churn the baseline.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning")

# comment grammar: `# prestolint: allow(rule-a, rule-b) -- free-form reason`
_ALLOW_PREFIX = "# prestolint: allow("


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str  # 'error' | 'warning'
    file: str  # path relative to the repo root, posix separators
    line: int  # 1-based
    message: str
    context: str = ""  # enclosing Class.func qualname ('' at module level)

    def key(self) -> Tuple[str, str, str, str]:
        """Identity WITHOUT the line number: line drift from unrelated
        edits must not invalidate the baseline."""
        return (self.rule, self.file, self.context, self.message)

    def render(self) -> str:
        ctx = f" ({self.context})" if self.context else ""
        return (
            f"{self.file}:{self.line}: [{self.severity}] "
            f"{self.rule}: {self.message}{ctx}"
        )


def _fingerprints(findings: Sequence[Finding]) -> List[str]:
    """One stable fingerprint per finding. Identical (rule, file, context,
    message) tuples — e.g. two textually identical swallows in one
    function — disambiguate by occurrence ordinal, counted in line order
    so the mapping is deterministic."""
    seen: Dict[Tuple[str, str, str, str], int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule)):
        k = f.key()
        n = seen.get(k, 0)
        seen[k] = n + 1
        raw = "\x00".join((f.rule, f.file, f.context, f.message, str(n)))
        out.append(hashlib.sha1(raw.encode()).hexdigest()[:16])
    return out


class SourceFile:
    """One parsed module: ast tree + raw lines + suppression lookup."""

    def __init__(self, rel: str, abspath: str, text: str):
        self.rel = rel
        self.abspath = abspath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        self._allow: Optional[Dict[int, Tuple[str, ...]]] = None

    def _allowed_rules(self, line: int) -> Tuple[str, ...]:
        if self._allow is None:
            allow: Dict[int, Tuple[str, ...]] = {}
            for i, raw in enumerate(self.lines, start=1):
                at = raw.find(_ALLOW_PREFIX)
                if at < 0:
                    continue
                inner = raw[at + len(_ALLOW_PREFIX):]
                close = inner.find(")")
                if close < 0:
                    continue
                rules = tuple(
                    r.strip() for r in inner[:close].split(",") if r.strip()
                )
                allow[i] = rules
            self._allow = allow
        return self._allow.get(line, ())

    def _comment_block(self, line: int):
        """`line` itself plus every line of the contiguous comment block
        directly above it — the shared scan behind both allow()
        suppressions and marker comments, so the two accept identical
        comment placements."""
        yield line
        ln = line - 1
        while ln >= 1 and self.line_text(ln).strip().startswith("#"):
            yield ln
            ln -= 1

    def suppressed(self, line: int, rule: str) -> bool:
        """True when `line` itself, or any line of the contiguous
        comment block directly above it, carries an allow() for `rule`
        — multi-line justifications are encouraged."""
        return any(
            rule in self._allowed_rules(ln) for ln in self._comment_block(line)
        )

    def has_marker(self, line: int, marker: str) -> bool:
        """True when `line` or its contiguous comment block above
        contains the literal `marker` text (e.g. `# prestolint:
        host-function`)."""
        return any(
            marker in self.line_text(ln) for ln in self._comment_block(line)
        )

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


class Project:
    """The analyzed file set plus lazily-built cross-file symbol tables."""

    def __init__(self, root: Path, files: List[SourceFile]):
        self.root = root
        self.files = files
        self._by_rel = {f.rel: f for f in files}
        self._symbols: Dict[str, object] = {}

    def file(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)

    def iter_files(self, prefix: str = "") -> Iterable[SourceFile]:
        for f in self.files:
            if f.rel.startswith(prefix):
                yield f

    def symbol(self, key: str, build):
        """Memoized cross-file symbol table (e.g. the plan-node class
        list): built once per run, shared by all passes."""
        if key not in self._symbols:
            self._symbols[key] = build(self)
        return self._symbols[key]


_SKIP_DIRS = {"__pycache__"}


def load_project(
    repo_root: Optional[os.PathLike] = None,
    packages: Sequence[str] = ("presto_tpu", "tests"),
) -> Project:
    """Parse every .py under each of `packages` (relative paths keyed off
    the repo root, so findings read `presto_tpu/ops/sort.py:296`). The
    test tree loads alongside the package so the tracing/exception passes
    can lint test helpers too (PR 2's deadlock came from an unguarded
    `pure_callback` in a test helper); passes opt in per prefix."""
    root = Path(
        repo_root
        if repo_root is not None
        else Path(__file__).resolve().parents[2]
    )
    files: List[SourceFile] = []
    for package in packages:
        base = root / package
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                ap = os.path.join(dirpath, name)
                rel = Path(ap).relative_to(root).as_posix()
                with open(ap, "r", encoding="utf-8") as fh:
                    text = fh.read()
                try:
                    files.append(SourceFile(rel, ap, text))
                except SyntaxError as exc:
                    # a file that doesn't parse is itself a finding-worthy
                    # state, but the loader can't represent it as a pass
                    # result — surface it loudly instead of skipping
                    raise RuntimeError(
                        f"prestolint: {rel} failed to parse: {exc}"
                    )
    return Project(root, files)


class AnalysisPass:
    """Base class: subclasses set `name`/`description` and implement
    run(project) -> findings. Suppression filtering happens in the
    driver, not in the passes."""

    name = ""
    description = ""
    rules: Tuple[str, ...] = ()  # every rule id this pass can emit

    def run(self, project: Project) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


# -- context helpers shared by passes ---------------------------------------


class ContextVisitor(ast.NodeVisitor):
    """Tracks the enclosing Class.func qualname while walking a module.
    Subclasses read `self.context` when emitting findings."""

    def __init__(self):
        self._stack: List[str] = []

    @property
    def context(self) -> str:
        return ".".join(self._stack)

    def visit_ClassDef(self, node: ast.ClassDef):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def iter_scoped_defs(body: Sequence[ast.stmt]):
    """Yield ``(fn_node, class_node_or_None)`` for every function defined
    at module or class level, descending through compound statements
    (try/if/with/for — serde.py defines its zstd helpers inside a
    module-level ``try``) but never into other functions. For functions
    inside nested classes the INNERMOST class is reported."""

    def walk(stmts, cls):
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield (s, cls)
            elif isinstance(s, ast.ClassDef):
                yield from walk(s.body, s)
            else:
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(s, attr, None)
                    if sub:
                        yield from walk(sub, cls)
                for h in getattr(s, "handlers", ()):
                    yield from walk(h.body, cls)

    yield from walk(body, None)


def shallow_walk(root: ast.AST, skip=(ast.FunctionDef, ast.AsyncFunctionDef)):
    """Yield `root` and its descendants WITHOUT descending into `skip`
    subtrees — nested defs (and, where the caller says so, lambdas) run
    on their own schedule, not where they are defined, so their bodies
    must not inherit the enclosing context (held locks, device/guard
    flags). Skip-typed children are still yielded once, so callers can
    recurse into them explicitly with fresh context."""
    stack = [root]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, skip):
                yield c
            else:
                stack.append(c)


def dotted_name(node: ast.AST) -> str:
    """`a.b.c` for Attribute/Name chains, '' for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# -- baseline ----------------------------------------------------------------


def load_baseline(path: os.PathLike) -> Dict[str, dict]:
    p = Path(path)
    if not p.exists():
        return {}
    data = json.loads(p.read_text())
    # v2 keeps test-tree findings in their own section so the package
    # burndown stays readable; both sections share one fingerprint space
    entries = data.get("findings", []) + data.get("tests_findings", [])
    return {e["fingerprint"]: e for e in entries}


def save_baseline(
    path: os.PathLike,
    findings: Sequence[Finding],
    keep: Sequence[dict] = (),
) -> None:
    """Write the baseline from `findings`, plus `keep` — pre-existing raw
    entries preserved verbatim during a partial (`--pass`-scoped)
    update."""
    fps = _fingerprints(findings)
    ordered = sorted(findings, key=lambda f: (f.file, f.line, f.rule))
    entries = [
        {
            "fingerprint": fp,
            "rule": f.rule,
            "file": f.file,
            "context": f.context,
            "message": f.message,
        }
        for fp, f in zip(fps, ordered)
    ]
    entries = sorted(
        entries + list(keep),
        key=lambda e: (e["file"], e["rule"], e["message"], e["fingerprint"]),
    )
    pkg = [e for e in entries if not e["file"].startswith("tests/")]
    tst = [e for e in entries if e["file"].startswith("tests/")]
    payload = {"version": 2, "findings": pkg}
    if tst:
        payload["tests_findings"] = tst
    Path(path).write_text(json.dumps(payload, indent=1) + "\n")


@dataclasses.dataclass
class CheckResult:
    all_findings: List[Finding]
    new: List[Finding]  # not baselined, not suppressed -> check fails
    baselined: List[Finding]
    expired: List[dict]  # baseline entries no longer found

    @property
    def ok(self) -> bool:
        return not self.new


def evaluate_against_baseline(
    findings: Sequence[Finding], baseline: Dict[str, dict]
) -> CheckResult:
    fps = _fingerprints(findings)
    ordered = sorted(findings, key=lambda f: (f.file, f.line, f.rule))
    new, old = [], []
    seen = set()
    for fp, f in zip(fps, ordered):
        seen.add(fp)
        (old if fp in baseline else new).append(f)
    expired = [e for fp, e in sorted(baseline.items()) if fp not in seen]
    return CheckResult(list(ordered), new, old, expired)
