"""CLI: ``python -m presto_tpu.analysis --check`` (tier-1 gate) /
``--baseline-update`` (re-baseline after an intentional change)."""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

from . import (
    ALL_PASSES,
    DEFAULT_BASELINE,
    PASSES_BY_NAME,
    run_check,
    update_baseline,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m presto_tpu.analysis",
        description="prestolint: repo-specific AST static analysis",
    )
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument(
        "--check", action="store_true",
        help="fail (exit 1) on any finding not in the baseline [default]",
    )
    mode.add_argument(
        "--baseline-update", action="store_true",
        help="regenerate the suppression baseline from current findings",
    )
    mode.add_argument(
        "--list-passes", action="store_true", help="list passes and exit"
    )
    ap.add_argument(
        "--pass", dest="only", action="append", metavar="NAME",
        help="run only this pass (repeatable); default all",
    )
    ap.add_argument("--root", default=None, help="repo root (default: auto)")
    ap.add_argument(
        "--baseline", default=None, help="baseline path (default: committed)"
    )
    ap.add_argument(
        "--all", action="store_true",
        help="with --check: print baselined findings too",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="with --check: machine-readable result on stdout "
        "(tools/bench_gate.py and CI consume this)",
    )
    args = ap.parse_args(argv)

    if args.list_passes:
        for p in ALL_PASSES:
            print(f"{p.name}: {p.description}")
        return 0

    passes = None
    if args.only:
        unknown = [n for n in args.only if n not in PASSES_BY_NAME]
        if unknown:
            print(f"unknown pass(es): {', '.join(unknown)}", file=sys.stderr)
            print(f"known: {', '.join(PASSES_BY_NAME)}", file=sys.stderr)
            return 2
        passes = [PASSES_BY_NAME[n] for n in args.only]

    if args.baseline_update:
        n = update_baseline(args.root, args.baseline, passes)
        path = args.baseline or DEFAULT_BASELINE
        scope = f" ({', '.join(args.only)} scoped)" if args.only else ""
        print(f"prestolint: baselined {n} finding(s){scope} -> {path}")
        return 0

    t0 = time.monotonic()
    result = run_check(args.root, args.baseline, passes)
    dt = time.monotonic() - t0
    if args.json:
        by_rule: dict = {}
        for f in result.new:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        payload = {
            "ok": result.ok,
            "elapsed_s": round(dt, 3),
            "new": [dataclasses.asdict(f) for f in result.new],
            "new_by_rule": by_rule,
            "baselined": len(result.baselined),
            "expired": len(result.expired),
            "total": len(result.all_findings),
            "passes": [p.name for p in (passes or ALL_PASSES)],
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 0 if result.ok else 1
    if args.all:
        for f in result.baselined:
            print(f"{f.render()}  [baselined]")
    for f in result.new:
        print(f.render())
    if result.expired:
        print(
            f"prestolint: {len(result.expired)} baseline entr"
            f"{'y is' if len(result.expired) == 1 else 'ies are'} stale "
            "(finding no longer present) — run --baseline-update to prune"
        )
    verdict = "clean" if result.ok else "FAILED"
    print(
        f"prestolint {verdict}: {len(result.new)} new, "
        f"{len(result.baselined)} baselined, {len(result.expired)} expired "
        f"({len(result.all_findings)} total) in {dt:.2f}s"
    )
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
