"""prestolint pass registry. Import order is report order."""

from . import (
    coverage,
    exceptions,
    exhaustive,
    knobs,
    locks,
    memory,
    races,
    tracing,
)

ALL_PASSES = (
    tracing.PASS,
    locks.PASS,
    races.PASS,
    exceptions.PASS,
    exhaustive.PASS,
    memory.PASS,
    knobs.PASS,
    coverage.PASS,
)

PASSES_BY_NAME = {p.name: p for p in ALL_PASSES}
