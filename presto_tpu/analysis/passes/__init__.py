"""prestolint pass registry. Import order is report order."""

from . import exceptions, exhaustive, locks, memory, tracing

ALL_PASSES = (
    tracing.PASS,
    locks.PASS,
    exceptions.PASS,
    exhaustive.PASS,
    memory.PASS,
)

PASSES_BY_NAME = {p.name: p for p in ALL_PASSES}
