"""exception-hygiene: broad handlers must record, re-raise, or justify.

PR 7 set the norm: infrastructure loops count their failures into stats
or fire worker events instead of `except Exception: pass`-ing them into
the void (ClusterMemoryManager poll failures -> MEMORY_UNPOLLABLE
events). This pass makes that norm checkable.

Rules
-----
broad-except-swallow (error)
    `except Exception` / bare `except` / `except BaseException` whose
    body is pure control flow (`pass`/`continue`/`break`/bare `return`/
    `return None`/ellipsis) — the error vanishes without a trace.

broad-except-silent (warning)
    A broad handler that does real work but neither re-raises nor calls
    anything that looks like recording (substring match on
    record/stat/event/log/warn/count/... in any called name) — likely a
    silent fallback; either record the failure or justify it.

Suppressions: ``# prestolint: allow(broad-except-silent) -- reason`` on
the `except` line, or the tree's existing idiom — a ``# noqa: BLE001``
comment that CARRIES A REASON after a dash. A bare ``# noqa: BLE001``
does not count: the reason is the point.
"""

from __future__ import annotations

import ast
import re
from typing import List

from ..core import (
    AnalysisPass,
    ContextVisitor,
    Finding,
    Project,
    SourceFile,
    dotted_name,
)

_BROAD = {"Exception", "BaseException"}

_RECORD_TOKENS = (
    "record", "stat", "event", "log", "warn", "error", "exception",
    "count", "emit", "fire", "note", "fail", "abort", "blacklist",
    "increment", "observe", "retry", "degrade", "report",
    # a handler that prints is surfacing, not swallowing (CLI/REPL loops)
    "print",
)

# `# noqa: BLE001 — reason` / `# noqa: BLE001 -- reason` (reason REQUIRED)
_NOQA_REASON = re.compile(r"#\s*noqa:\s*BLE001\s*[—–-]+\s*\S")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names = []
    if isinstance(handler.type, ast.Tuple):
        names = [dotted_name(e).split(".")[-1] for e in handler.type.elts]
    else:
        names = [dotted_name(handler.type).split(".")[-1]]
    return any(n in _BROAD for n in names)


def _pure_control_flow(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return) and (
            stmt.value is None
            or (isinstance(stmt.value, ast.Constant) and stmt.value.value is None)
        ):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


def _records_or_raises(body: List[ast.stmt]) -> bool:
    for node in ast.walk(ast.Module(body=list(body), type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func).lower()
            if any(tok in name for tok in _RECORD_TOKENS):
                return True
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                tn = dotted_name(t) or (
                    dotted_name(t.value) + "[]"
                    if isinstance(t, ast.Subscript)
                    else ""
                )
                if any(tok in tn.lower() for tok in _RECORD_TOKENS):
                    return True
    return False


class ExceptionHygienePass(AnalysisPass):
    name = "exception-hygiene"
    description = "broad except handlers that swallow errors untracked"
    rules = ("broad-except-swallow", "broad-except-silent")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        # tests/ too: a broad except swallowing an assertion turns a
        # red test green (tests-only findings baseline separately)
        for prefix in ("presto_tpu/", "tests/"):
            for sf in project.iter_files(prefix):
                findings.extend(self._check_file(sf))
        return findings

    def _check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        outer = self

        class V(ContextVisitor):
            def visit_Try(self, node: ast.Try):
                for h in node.handlers:
                    if _is_broad(h):
                        outer._check_handler(sf, h, self.context, findings)
                self.generic_visit(node)

        V().visit(sf.tree)
        return findings

    def _check_handler(self, sf, handler, ctx, findings):
        # the existing reasoned-noqa idiom counts as a suppression
        if _NOQA_REASON.search(sf.line_text(handler.lineno)):
            return
        if _pure_control_flow(handler.body):
            findings.append(
                Finding(
                    "broad-except-swallow", "error", sf.rel, handler.lineno,
                    "broad except swallows the error with no trace: count "
                    "it into stats, fire an event, or annotate why it is "
                    "safe to drop",
                    ctx,
                )
            )
            return
        if not _records_or_raises(handler.body):
            findings.append(
                Finding(
                    "broad-except-silent", "warning", sf.rel, handler.lineno,
                    "broad except neither re-raises nor records: a silent "
                    "fallback hides real faults — record the failure or "
                    "justify with an allow()/reasoned noqa",
                    ctx,
                )
            )


PASS = ExceptionHygienePass()
