"""memory-accounting: every reserve must reach a matching free.

MemoryPool.free() counts over-frees into GLOBAL_ACCOUNTING instead of
clamping (PR 7), and the test conftest fails any test that leaks a
reservation — but both only fire when a test happens to drive the leaky
path. This pass enforces the structure statically.

The tree's idiom (exec/stream.py, exec/spill.py)::

    nb = page_device_bytes(page)
    self.pool.reserve(nb, "what")
    try:
        ...
    finally:
        self.pool.free(nb)

Ownership transfers are legal: a builder reserves and RETURNS the held
bytes for a consumer method of the same class to free (the hybrid-join
build side). Hence two rules at different strictness:

memory-reserve-unpaired (error)
    A function reserves on receiver R but neither it nor any method of
    the same class ever frees on R — the reservation cannot be released.

memory-reserve-no-finally (warning)
    A function both reserves and frees on R, but no free sits in a
    `finally`/`except` block: an exception between the two leaks the
    reservation (and, under a parent pool, permanently shrinks the
    worker's admission budget).

Receiver matching is textual on the dotted chain (`self.pool`, `pool`,
`self._pool`, names containing "pool"/"memory"), and `reserve*`/`free*`
are prefix-matched so reserve_execution/free_execution pair too.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..core import (
    AnalysisPass,
    Finding,
    Project,
    SourceFile,
    dotted_name,
    iter_scoped_defs,
)


def _pool_receiver(call: ast.Call) -> Tuple[str, str]:
    """('self.pool', 'reserve') for pool-ish reserve/free calls, else
    ('', '')."""
    if not isinstance(call.func, ast.Attribute):
        return "", ""
    meth = call.func.attr
    if not (meth.startswith("reserve") or meth.startswith("free")):
        return "", ""
    recv = dotted_name(call.func.value)
    if not recv:
        return "", ""
    tail = recv.split(".")[-1].lower()
    if "pool" in tail or "memory" in tail:
        return recv, meth
    return "", ""


def _collect(fn: ast.AST):
    """(reserves, frees, protected_frees) by receiver for one function,
    ignoring nested defs (they run on their own schedule)."""
    reserves: Dict[str, int] = {}
    frees: Set[str] = set()
    protected: Set[str] = set()

    def scan(node, in_cleanup: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Try):
                for b in child.body + child.orelse:
                    scan(b, in_cleanup)
                for b in child.finalbody:
                    scan(b, True)
                for h in child.handlers:
                    for b in h.body:
                        scan(b, True)
                continue
            scan(child, in_cleanup)
            if isinstance(child, ast.Call):
                recv, meth = _pool_receiver(child)
                if not recv:
                    continue
                if meth.startswith("reserve"):
                    reserves.setdefault(recv, child.lineno)
                else:
                    frees.add(recv)
                    if in_cleanup:
                        protected.add(recv)

    scan(fn, False)
    return reserves, frees, protected


def _direct_nested_defs(fn):
    """Function defs nested inside `fn` (any statement depth) WITHOUT
    descending into them — each gets its own check_fn visit."""
    out = []

    def scan(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
            else:
                scan(child)

    scan(fn)
    return out


class MemoryAccountingPass(AnalysisPass):
    name = "memory-accounting"
    description = "MemoryPool.reserve paths must reach a matching free"
    rules = ("memory-reserve-unpaired", "memory-reserve-no-finally")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for sf in project.iter_files("presto_tpu/"):
            findings.extend(self._check_file(sf))
        return findings

    def _check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []

        def check_fn(fn, ctx: str, class_frees: Set[str]):
            qual = f"{ctx}.{fn.name}" if ctx else fn.name
            reserves, frees, protected = _collect(fn)
            for recv, line in sorted(reserves.items()):
                if recv not in frees:
                    if recv in class_frees:
                        continue  # ownership transfer within the class
                    findings.append(
                        Finding(
                            "memory-reserve-unpaired", "error", sf.rel, line,
                            f"{recv}.reserve() with no matching free "
                            "anywhere in the function or its class — the "
                            "reservation can never be released",
                            qual,
                        )
                    )
                elif recv not in protected:
                    findings.append(
                        Finding(
                            "memory-reserve-no-finally", "warning", sf.rel,
                            line,
                            f"{recv}.reserve() whose free is not in a "
                            "finally/except: an exception in between leaks "
                            "the reservation against the worker budget",
                            qual,
                        )
                    )
            for fsub in _direct_nested_defs(fn):
                check_fn(fsub, qual, class_frees)

        # class-level frees computed once per class (ownership transfer:
        # reserve in one method, free in another)
        frees_by_class: Dict[int, Set[str]] = {}

        def class_frees_of(cnode) -> Set[str]:
            if cnode is None:
                return set()
            got = frees_by_class.get(id(cnode))
            if got is None:
                got = set()
                for sub in ast.walk(cnode):
                    if isinstance(sub, ast.Call):
                        recv, meth = _pool_receiver(sub)
                        if recv and meth.startswith("free"):
                            got.add(recv)
                frees_by_class[id(cnode)] = got
            return got

        for fn, cnode in iter_scoped_defs(sf.tree.body):
            check_fn(
                fn,
                cnode.name if cnode is not None else "",
                class_frees_of(cnode),
            )
        return findings


PASS = MemoryAccountingPass()
