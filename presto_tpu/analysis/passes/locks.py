"""lock-discipline: blocking calls under locks and acquisition-order cycles.

The server and executor now hold 16+ lock sites across three interacting
domains (worker memory pool, output buffers, query caches). Two bug
classes this pass makes structural:

lock-blocking-call (error)
    A call that can block indefinitely — HTTP (`urlopen`/`requests.*`),
    `queue.get()` without a timeout, `future.result()` without a
    timeout, `thread.join()`, `time.sleep`, `cond.wait()` without a
    timeout while OTHER locks are held, blocking `lock.acquire()`,
    device sync (`block_until_ready`/`jax.device_get`) — made while
    holding a lock. One slow peer then stalls every thread behind the
    lock; the PR 4 exchange threads and PR 7 memory killers both fan in
    here.

lock-order-inversion (error)
    Lock pair (A, B) acquired in both orders somewhere in the tree —
    the classic ABBA deadlock. Edges come from literal `with` nesting
    AND from one level of calls: `with self._lock: self.pool.reserve()`
    adds an edge to every lock `reserve` takes, resolved through
    `self.pool = WorkerMemoryPool(...)`-style attribute types.

Lock identity is `ClassName.attr` (or `module.name` for globals), so the
same attribute on different instances unifies — exactly what you want
for ordering discipline, at the cost of treating two instances of one
class as one lock (document real cases with an allow())."""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from ..core import (
    AnalysisPass,
    Finding,
    Project,
    SourceFile,
    dotted_name,
    iter_scoped_defs,
    shallow_walk,
)
from ..symbols import attr_kinds

_BLOCKING_NAME_PARTS = {"urlopen"}
_REQUESTS_METHODS = {"get", "post", "put", "delete", "head", "request"}


def _kw(call: ast.Call, name: str) -> bool:
    return any(k.arg == name for k in call.keywords)


@dataclasses.dataclass
class MethodInfo:
    key: Tuple[str, str, str]  # (file, class or '', func)
    acquires: Set[str]  # lock ids taken via `with` anywhere inside
    # (held locks at the call, callee key or attr-call spec, line)
    calls: List[Tuple[Tuple[str, ...], Tuple[str, str], int]]
    # (held tuple, new lock id, line) for nested-with edges
    edges: List[Tuple[Tuple[str, ...], str, int]]
    blocking: List[Tuple[Tuple[str, ...], str, int]]


def _class_index(project: Project) -> Dict[str, List[Tuple[str, str]]]:
    def build(p: Project):
        out: Dict[str, List[Tuple[str, str]]] = {}
        for sf in p.files:
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    out.setdefault(node.name, []).append((sf.rel, node.name))
        return out

    return project.symbol("class_index", build)


def _attr_classes(project: Project) -> Dict[Tuple[str, str], Dict[str, str]]:
    """(file, class) -> {attr: ClassName} for `self.attr = ClassName(...)`."""

    def build(p: Project):
        classes = _class_index(p)
        out: Dict[Tuple[str, str], Dict[str, str]] = {}
        for sf in p.files:
            for node in sf.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                m: Dict[str, str] = {}
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    if not isinstance(sub.value, ast.Call):
                        continue
                    ctor = dotted_name(sub.value.func).split(".")[-1]
                    if ctor not in classes:
                        continue
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            m[t.attr] = ctor
                if m:
                    out[(sf.rel, node.name)] = m
        return out

    return project.symbol("attr_classes", build)


class LockDisciplinePass(AnalysisPass):
    name = "lock-discipline"
    description = "blocking calls while holding locks; ABBA order inversions"
    rules = ("lock-blocking-call", "lock-order-inversion")

    def run(self, project: Project) -> List[Finding]:
        kinds = attr_kinds(project)
        # project-wide class -> {attr: kind} and class -> base names, so
        # `with self._cv:` in a SUBCLASS resolves to the defining class
        # (lock identity must unify across the inheritance chain)
        cls_attr: Dict[str, Dict[str, str]] = {}
        cls_bases: Dict[str, List[str]] = {}
        for sf in project.files:
            for cname, attrs in kinds[sf.rel].classes.items():
                m = cls_attr.setdefault(cname, {})
                for a, k in attrs.items():
                    m.setdefault(a, k)
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    cls_bases.setdefault(
                        node.name,
                        [dotted_name(b).split(".")[-1] for b in node.bases],
                    )

        def resolve_attr(cls: Optional[str], attr: str):
            """(defining class, kind) for self.<attr>, walking bases
            breadth-first; (None, None) when unknown."""
            queue, seen = [cls] if cls else [], set()
            while queue:
                cur = queue.pop(0)
                if cur in seen or cur is None:
                    continue
                seen.add(cur)
                if attr in cls_attr.get(cur, {}):
                    return cur, cls_attr[cur][attr]
                queue.extend(cls_bases.get(cur, []))
            return None, None

        methods: Dict[Tuple[str, str, str], MethodInfo] = {}
        for sf in project.iter_files("presto_tpu/"):
            self._collect_file(sf, kinds[sf.rel], methods, resolve_attr)
        return self._report(project, methods)

    # -- phase A: per-method collection ------------------------------------

    def _collect_file(self, sf: SourceFile, ak, methods, resolve_attr):
        mod = os.path.basename(sf.rel).rsplit(".", 1)[0]
        # per-function scratch read by classify_blocking (refreshed in
        # enter_func; nested defs share the enclosing function's view)
        state = {"future_locals": set()}

        def lock_id(expr, cls: Optional[str]) -> Optional[str]:
            """Resolve a with-item / receiver to a lock id, or None.
            Identity is `DefiningClass.attr` so subclasses unify with the
            class that created the lock."""
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                owner, kind = resolve_attr(cls, expr.attr)
                if kind == "lock":
                    return f"{owner}.{expr.attr}"
                return None
            if isinstance(expr, ast.Name) and ak.module.get(expr.id) == "lock":
                return f"{mod}.{expr.id}"
            return None

        def recv_kind(expr, cls: Optional[str]) -> Optional[str]:
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                return resolve_attr(cls, expr.attr)[1]
            if isinstance(expr, ast.Name):
                return ak.module.get(expr.id)
            return None

        def classify_blocking(call: ast.Call, cls, held) -> Optional[str]:
            name = dotted_name(call.func)
            tail = name.split(".")[-1]
            root = name.split(".")[0]
            if name == "time.sleep" or tail == "sleep" and root == "time":
                return "time.sleep"
            if any(p in name for p in _BLOCKING_NAME_PARTS):
                return name
            if root == "requests" and tail in _REQUESTS_METHODS:
                return name
            if not isinstance(call.func, ast.Attribute):
                return None
            recv = call.func.value
            kind = recv_kind(recv, cls)
            # dotted_name is "" for chains rooted at a call (e.g.
            # pool.submit(x).result()) — the method name itself is
            # always on the Attribute node
            tail = call.func.attr
            if tail == "result" and not call.args and not _kw(call, "timeout"):
                # gate on evidence of future-ness, like queue.get/thread
                # .join — an unrelated .result() method (a builder, a
                # parser) must not fail the tier-1 gate
                is_future = kind == "future"
                if (
                    isinstance(recv, ast.Name)
                    and recv.id in state["future_locals"]
                ):
                    is_future = True
                if isinstance(recv, ast.Call) and dotted_name(
                    recv.func
                ).split(".")[-1] == "submit":
                    is_future = True  # pool.submit(...).result()
                if is_future:
                    label = dotted_name(call.func) or f"<future>.{tail}"
                    return f"{label}() without timeout"
            if tail == "get" and kind == "queue":
                # only a LITERAL block=False is non-blocking — the mere
                # presence of the kwarg must not suppress (block=True is
                # exactly the indefinite wait this rule exists for)
                block_false = call.args and (
                    isinstance(call.args[0], ast.Constant)
                    and call.args[0].value is False
                )
                bkw = next(
                    (k.value for k in call.keywords if k.arg == "block"),
                    None,
                )
                if isinstance(bkw, ast.Constant) and bkw.value is False:
                    block_false = True
                if not _kw(call, "timeout") and not block_false:
                    return "queue.get() without timeout"
            if tail == "join" and kind == "thread":
                if not call.args and not _kw(call, "timeout"):
                    return "thread.join() without timeout"
            if tail == "wait" and not call.args and not _kw(call, "timeout"):
                rid = lock_id(recv, cls)
                if rid is not None and (
                    len(held) > 1 or (held and held[-1] != rid)
                ):
                    return (
                        f"{rid}.wait() without timeout while holding "
                        f"{[h for h in held if h != rid]}"
                    )
            if tail == "acquire" and not _kw(call, "timeout") and not (
                call.args
            ):
                rid = lock_id(recv, cls)
                if rid is not None and held:
                    return f"blocking {rid}.acquire()"
            if tail == "block_until_ready":
                return "device sync (block_until_ready)"
            if name == "jax.device_get":
                return "device sync (jax.device_get)"
            return None

        def walk(stmts, cls, fn_key, held: Tuple[str, ...]):
            info = methods[fn_key]
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # a nested def runs later, not under these locks, so
                    # its calls must not enter this MethodInfo (phase B
                    # would attribute them to every caller that invokes
                    # the method under a lock) — but closures like thread
                    # targets are prime blocking-under-lock candidates,
                    # so analyze the body as its OWN scope with a fresh
                    # held set, keyed by qualified name
                    nkey = (sf.rel, cls or "", f"{fn_key[2]}.{stmt.name}")
                    if nkey not in methods:
                        methods[nkey] = MethodInfo(nkey, set(), [], [], [])
                    walk(stmt.body, cls, nkey, ())
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    new = []
                    for item in stmt.items:
                        lid = lock_id(item.context_expr, cls)
                        if lid is not None:
                            # items earlier in the same `with a, b:` are
                            # already held when the next one acquires —
                            # a->b is a real ordering edge, same as the
                            # nested-with form
                            eff = tuple(
                                h for h in held + tuple(new) if h != lid
                            )
                            if eff:
                                info.edges.append((eff, lid, stmt.lineno))
                            new.append(lid)
                            info.acquires.add(lid)
                    self._scan_exprs(
                        stmt.items, cls, info, held, classify_blocking
                    )
                    walk(stmt.body, cls, fn_key, held + tuple(new))
                    continue
                # recurse into compound statements under the same held set
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        walk(sub, cls, fn_key, held)
                for h in getattr(stmt, "handlers", ()):
                    walk(h.body, cls, fn_key, held)
                # scan only the HEADER expressions of compound statements
                # — their bodies were just walked; scanning the whole
                # subtree again would double-count every call
                if isinstance(stmt, (ast.If, ast.While)):
                    headers = [stmt.test]
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    headers = [stmt.iter]
                elif isinstance(stmt, ast.Try):
                    headers = []
                else:
                    headers = [stmt]
                self._scan_exprs(headers, cls, info, held, classify_blocking)

        def enter_func(fn, cls):
            key = (sf.rel, cls or "", fn.name)
            if key not in methods:
                methods[key] = MethodInfo(key, set(), [], [], [])
            # locals assigned from submit()/Future() in this function
            # (incl. its closures) count as future-typed receivers
            futs = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    if dotted_name(node.value.func).split(".")[-1] in (
                        "submit", "Future",
                    ):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                futs.add(t.id)
            state["future_locals"] = futs
            walk(fn.body, cls, key, ())

        for fn, cnode in iter_scoped_defs(sf.tree.body):
            enter_func(fn, cnode.name if cnode is not None else None)

    def _scan_exprs(self, nodes, cls, info, held, classify_blocking):
        """Record blocking calls and outgoing method calls at this held
        set. Skips nested statements (the walker handles those)."""
        # lambdas and nested defs are deferred execution: a callback
        # BUILT under a lock does not RUN under it, so their bodies are
        # excluded from the held-set scan entirely
        deferred = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        for top in nodes:
            for node in shallow_walk(top, skip=deferred):
                if isinstance(node, deferred):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                # blocking calls record even with held=() — phase B uses
                # them to flag `with lock: self._helper()` where the
                # helper is what blocks
                what = classify_blocking(node, cls, held)
                if what:
                    info.blocking.append((held, what, node.lineno))
                if not held:
                    continue
                if isinstance(node.func, ast.Attribute):
                    recv = node.func.value
                    if isinstance(recv, ast.Name) and recv.id == "self":
                        info.calls.append(
                            (held, ("self", node.func.attr), node.lineno)
                        )
                    elif (
                        isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"
                    ):
                        info.calls.append(
                            (held, (recv.attr, node.func.attr), node.lineno)
                        )
                elif isinstance(node.func, ast.Name):
                    # bare module-level helper in the same file
                    info.calls.append(
                        (held, ("", node.func.id), node.lineno)
                    )

    # -- phase B: edges + report -------------------------------------------

    def _report(self, project: Project, methods) -> List[Finding]:
        findings: List[Finding] = []
        attr_cls = _attr_classes(project)
        # method lookup: (class, func) -> candidate MethodInfos. Class
        # names duplicate across files (plan/nodes.Join vs sql/tree.Join)
        # so resolution prefers the caller's file and gives up when the
        # cross-file candidates are ambiguous — a wrong-class body would
        # fabricate (or hide) lock findings
        by_cls: Dict[Tuple[str, str], List[MethodInfo]] = {}
        for (f, c, fn), info in sorted(methods.items()):
            by_cls.setdefault((c, fn), []).append(info)

        def lookup_method(cls_name, callee, caller_file):
            cands = by_cls.get((cls_name, callee), [])
            same = [i for i in cands if i.key[0] == caller_file]
            if same:
                return same[0]
            if len(cands) == 1:
                return cands[0]
            return None

        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        for (f, c, fn), info in sorted(methods.items()):
            ctx = f"{c}.{fn}" if c else fn
            for held, what, line in info.blocking:
                if not held:
                    continue  # kept only for phase-B propagation
                findings.append(
                    Finding(
                        "lock-blocking-call", "error", f, line,
                        f"{what} while holding {list(held)}",
                        ctx,
                    )
                )
            for held, lid, line in info.edges:
                for h in held:
                    edges.setdefault((h, lid), (f, line, ctx))
            # one level through the call graph
            for held, (recv, callee), line in info.calls:
                if recv == "self":
                    target = lookup_method(c, callee, f)
                elif recv == "":
                    target = methods.get((f, "", callee))
                else:
                    tcls = attr_cls.get((f, c), {}).get(recv)
                    target = (
                        lookup_method(tcls, callee, f) if tcls else None
                    )
                if target is None:
                    continue
                callee_ctx = ".".join(x for x in target.key[1:] if x)
                for bheld, what, _bline in target.blocking:
                    if not bheld:
                        findings.append(
                            Finding(
                                "lock-blocking-call", "error", f, line,
                                f"{what} (inside {callee_ctx}) while "
                                f"holding {list(held)}",
                                ctx,
                            )
                        )
                for lid in target.acquires:
                    for h in held:
                        if h != lid:
                            edges.setdefault(
                                (h, lid),
                                (f, line, f"{ctx} -> {callee_ctx}"),
                            )

        reported = set()
        for (a, b), (f, line, ctx) in sorted(edges.items()):
            if (b, a) in edges and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                # the message must stay line-number-free so baseline
                # fingerprints survive unrelated edits near either site
                f2, _line2, ctx2 = edges[(b, a)]
                findings.append(
                    Finding(
                        "lock-order-inversion", "error", f, line,
                        f"lock order inversion: {a} -> {b} here but "
                        f"{b} -> {a} in {f2} ({ctx2})",
                        ctx,
                    )
                )
        return findings


PASS = LockDisciplinePass()
