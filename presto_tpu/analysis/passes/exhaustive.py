"""plan/IR exhaustiveness: every node class must be handled everywhere.

The reference planner's IdentityTranslator/visitor hierarchy makes an
unhandled plan node a compile error; here the dispatch surfaces are
string-built method names and isinstance chains, so "added a node,
forgot a dispatcher" surfaces as an AttributeError mid-query — or worse,
as an EXPLAIN that silently prints nothing. This pass closes the gap at
lint time.

Surfaces (rule `plan-dispatch-missing`, error):

- ``Executor._exec_<node>`` in exec/executor.py — every PlanNode
  subclass from plan/nodes.py AND plan/fragment.py (Exchange,
  AggFinalize) needs a method; `run()` getattr's with no default.
- ``Fragmenter._v_<node>`` in plan/fragment.py — every plan/nodes.py
  class; the fragmenter raises on a miss, but only when a query first
  exercises it.
- ``plan_tree_str`` in plan/nodes.py (EXPLAIN) — every node class must
  be MENTIONED (isinstance branch or name-string match). Nodes with no
  interesting config belong in the explicit name-only branch, so the
  next reader knows the omission is deliberate.
- ``evaluate`` in expr/compiler.py — every RowExpression subclass from
  expr/ir.py must be mentioned, if only to be explicitly rejected
  (a bare Lambda outside a lambda-form call).

exec/dist.py's ``_d_<node>`` visitor is deliberately NOT a surface: it
has a sound generic fallback (gather to single-node execution) and
raises a structured error on sharded input it cannot handle.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..core import AnalysisPass, Finding, Project
from ..symbols import (
    class_def,
    function_def,
    ir_node_classes,
    method_names,
    plan_node_classes,
)


def _mentions(fn: ast.AST) -> Set[str]:
    """Every Name and string constant inside `fn` — the 'is this class
    handled here' oracle for isinstance chains, dispatch-dict literals
    and `name == "Exchange"` string dispatch alike."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
    return out


class ExhaustivenessPass(AnalysisPass):
    name = "plan-exhaustiveness"
    description = "every plan/IR node handled in executor, fragmenter, EXPLAIN"
    rules = ("plan-dispatch-missing",)

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        nodes = plan_node_classes(project)
        node_names = [c for _, c in nodes]
        from_nodes_py = [
            c for f, c in nodes if f == "presto_tpu/plan/nodes.py"
        ]

        self._method_surface(
            project, findings,
            file="presto_tpu/exec/executor.py", cls="Executor",
            prefix="_exec_", required=node_names,
            surface="Executor dispatch (run() getattr's _exec_<node>)",
        )
        self._method_surface(
            project, findings,
            file="presto_tpu/plan/fragment.py", cls="Fragmenter",
            prefix="_v_", required=from_nodes_py,
            surface="Fragmenter visitor (_v_<node>)",
        )
        self._mention_surface(
            project, findings,
            file="presto_tpu/plan/nodes.py", func="plan_tree_str",
            required=node_names,
            surface="EXPLAIN rendering (plan_tree_str)",
        )
        self._mention_surface(
            project, findings,
            file="presto_tpu/expr/compiler.py", func="evaluate",
            required=[c for _, c in ir_node_classes(project)],
            surface="expression evaluation (evaluate)",
        )
        return findings

    def _method_surface(
        self, project, findings, *, file, cls, prefix, required, surface
    ):
        sf = project.file(file)
        if sf is None:
            return
        have = {
            m[len(prefix):]
            for m in method_names(sf, cls)
            if m.startswith(prefix)
        }
        anchor = class_def(sf, cls)
        line = anchor.lineno if anchor is not None else 1
        for node in required:
            if node.lower() not in have:
                findings.append(
                    Finding(
                        "plan-dispatch-missing", "error", file, line,
                        f"{surface}: no {prefix}{node.lower()} for plan "
                        f"node {node} — add the handler (or an explicit "
                        "rejecting one) before the node ships",
                        cls,
                    )
                )

    def _mention_surface(
        self, project, findings, *, file, func, required, surface
    ):
        sf = project.file(file)
        if sf is None:
            return
        fn = function_def(sf, func)
        if fn is None:
            findings.append(
                Finding(
                    "plan-dispatch-missing", "error", file, 1,
                    f"{surface}: function {func} not found", "",
                )
            )
            return
        seen = _mentions(fn)
        for node in required:
            if node not in seen:
                findings.append(
                    Finding(
                        "plan-dispatch-missing", "error", file, fn.lineno,
                        f"{surface}: {func} never mentions {node} — handle "
                        "it, or add it to the explicit name-only branch so "
                        "the omission is visibly deliberate",
                        func,
                    )
                )


PASS = ExhaustivenessPass()
