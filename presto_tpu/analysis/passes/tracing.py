"""tracing-safety: host callbacks and Python-on-tracer patterns.

The marquee bug class: `jax.pure_callback` reachable from jitted code
wedges forever on the single-device CPU runtime (the main thread blocks
synchronizing the kernel while the callback thread starves — the ORDER
BY >= 14k deadlock bisected to PR 2 and root-fixed alongside this pass),
and silently recomputes or crashes on sharded inputs. Related classes:
Python truthiness on a tracer raises TracerBoolConversionError at trace
time, and `np.*` applied to a tracer either crashes or silently forces a
host sync.

Rules
-----
tracing-host-callback (error)
    A `pure_callback`/`io_callback` call whose enclosing function has no
    concreteness guard. A guard is a reference to `Tracer` (an
    `isinstance(x, jax.core.Tracer)` eager bypass) or a call to a
    `_concrete`-style helper — the fixed idiom in ops/sort.py: run numpy
    DIRECTLY when operands are concrete, keep the callback only as the
    under-trace fallback, and make the caller route host plans around
    jit.

tracing-tracer-bool (error)
    `if`/`while`/`assert`/`not` applied directly to an array-returning
    `jnp.any`/`jnp.all`/`.any()`/`.all()` call inside a device function
    (a function whose body uses jnp/lax). Under jit the test raises; the
    device idiom is `jnp.where`/`lax.cond`, or return the predicate
    array to an eager caller (ops/sort.py's `ok` flags).

tracing-numpy-on-device (warning)
    An ARRAY-CONSUMING `np.<fn>` (asarray/argsort/flatnonzero/...)
    inside a device function in `ops/` or `expr/` that is neither a
    host-callback target nor a `_host_*` helper. numpy on a tracer
    fails at trace time; on a concrete device array it forces a host
    transfer mid-kernel. Constructors (np.zeros/np.array over host
    data) are the established host-side dictionary idiom and stay
    legal.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import (
    AnalysisPass,
    Finding,
    Project,
    SourceFile,
    dotted_name,
    iter_scoped_defs,
    shallow_walk,
)

_CALLBACKS = {"pure_callback", "io_callback"}
_BOOL_REDUCERS = {"any", "all"}

# np.* calls that CONSUME an existing array — applied to a tracer these
# fail at trace time, applied to a device array they force a host sync.
# Constructors (np.zeros/empty/array over host data) are the tree's
# established host-side varchar-dictionary idiom and are trace-safe, so
# this is an explicit flag-list, not an allow-list.
_NP_ARRAY_CONSUMERS = {
    "asarray", "ascontiguousarray", "asfortranarray", "copy",
    "flatnonzero", "nonzero", "argwhere",
    "argsort", "lexsort", "sort", "argpartition", "partition",
    "unique", "searchsorted", "bincount", "digitize",
    "concatenate", "stack", "hstack", "vstack", "split",
    "take", "clip", "where", "cumsum", "cumprod",
    "sum", "prod", "min", "max", "argmin", "argmax", "mean",
    "isnan", "isfinite", "isinf", "frombuffer",
}

_DEVICE_ROOTS = {"jnp", "lax"}
_DEVICE_DOTTED = {"jax.numpy", "jax.lax"}


def _uses_device_ops(fn: ast.AST) -> bool:
    # shallow: a nested helper's jnp usage must not make the OUTER
    # function a device function (the helper is analyzed on its own)
    for node in shallow_walk(fn):
        if isinstance(node, ast.Attribute):
            name = dotted_name(node)
            root = name.split(".")[0]
            if root in _DEVICE_ROOTS or any(
                name.startswith(d + ".") or name == d for d in _DEVICE_DOTTED
            ):
                return True
    return False


def _mentions_guard(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr == "Tracer":
            return True
        if isinstance(node, ast.Call):
            tail = dotted_name(node.func).split(".")[-1]
            if tail in {"_concrete", "is_concrete"}:
                return True
    return False


def _guard_ifs(fn: ast.AST):
    """``(if_node, body_terminates)`` for every `if` in `fn` whose test
    references Tracer / a ``_concrete``-style helper. Shallow: a guard
    inside a nested helper guards the HELPER, not the enclosing
    function."""
    out = []
    for node in shallow_walk(fn):
        if isinstance(node, ast.If) and _mentions_guard(node.test):
            terminates = bool(node.body) and isinstance(
                node.body[-1], (ast.Return, ast.Raise, ast.Continue)
            )
            out.append((node, terminates))
    return out


def _call_is_guarded(call: ast.Call, guards) -> bool:
    """A callback call is guarded only when it sits INSIDE a
    guard-conditional's subtree (either branch: the author explicitly
    branched on concreteness) or AFTER a guard whose body early-returns
    (the ops/sort.py eager-bypass idiom). A guard elsewhere in the
    function must not silence an unrelated callback — that is how the
    single-device deadlock class would re-enter the tree."""
    for g, terminates in guards:
        end = getattr(g, "end_lineno", None) or g.lineno
        if g.lineno <= call.lineno <= end:
            return True
        if terminates and end < call.lineno:
            return True
    return False


def _callback_targets(tree: ast.Module) -> Set[str]:
    """Names passed as the callback argument to pure_callback/io_callback
    anywhere in the module — those functions RUN on the host."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            tail = dotted_name(node.func).split(".")[-1]
            if tail in _CALLBACKS and node.args:
                first = node.args[0]
                if isinstance(first, ast.Name):
                    out.add(first.id)
                elif isinstance(first, ast.Call):
                    # factory idiom: pure_callback(_host_topn(cap), ...)
                    factory = dotted_name(first.func).split(".")[-1]
                    if factory:
                        out.add(factory)
    return out


def _is_bool_reducer_call(node: ast.AST) -> Optional[ast.Call]:
    """The offending Call when `node` is jnp.any/all(...) or x.any()/.all(),
    unwrapping a leading `not`."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
        node = node.operand
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    parts = name.split(".")
    if parts[-1] not in _BOOL_REDUCERS:
        return None
    if len(parts) >= 2 and parts[0] in _DEVICE_ROOTS | {"jax"}:
        return node
    # method form x.any(): only when the receiver is itself a device
    # expression we can see (jnp call) — bare names are too ambiguous
    if isinstance(node.func, ast.Attribute) and isinstance(
        node.func.value, ast.Call
    ):
        recv = dotted_name(node.func.value.func)
        if recv.split(".")[0] in _DEVICE_ROOTS | {"jax"}:
            return node
    return None


class TracingSafetyPass(AnalysisPass):
    name = "tracing-safety"
    description = (
        "host callbacks under jit, tracer truthiness, numpy on device arrays"
    )
    rules = (
        "tracing-host-callback",
        "tracing-numpy-on-device",
        "tracing-tracer-bool",
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        # tests/ is in scope too: a test that jits a host callback
        # deadlocks CI the same way product code would (tests-only
        # findings land in the baseline's tests_findings section)
        for prefix in ("presto_tpu/", "tests/"):
            for sf in project.iter_files(prefix):
                findings.extend(self._check_file(sf))
        return findings

    def _check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        host_fns = _callback_targets(sf.tree)
        # numpy/truthiness rules only police kernel-land (ops/, expr/):
        # exec/ and server/ legitimately mix eager numpy with device code
        kernel_land = sf.rel.startswith(
            ("presto_tpu/ops/", "presto_tpu/expr/")
        )

        def marked_host(fn) -> bool:
            # explicit escape hatch for host-orchestrated functions that
            # legally mix numpy with jnp setup/teardown:
            # `# prestolint: host-function` on the def line or in the
            # contiguous comment block above it (same placement contract
            # as allow() suppressions — one shared scan in core.py)
            return sf.has_marker(fn.lineno, "# prestolint: host-function")

        def walk_fn(fn: ast.FunctionDef, ctx: str, host: bool):
            qual = f"{ctx}.{fn.name}" if ctx else fn.name
            is_host = host or fn.name in host_fns or fn.name.startswith(
                "_host_"
            ) or marked_host(fn)
            device_fn = not is_host and _uses_device_ops(fn)
            guards = _guard_ifs(fn)
            for node in fn.body:
                self._walk_stmts(
                    node, sf, qual, is_host, device_fn, guards,
                    kernel_land, findings, walk_fn,
                )

        for fn, cls in iter_scoped_defs(sf.tree.body):
            walk_fn(fn, cls.name if cls is not None else "", host=False)
        return findings

    def _walk_stmts(
        self, node, sf, qual, is_host, device_fn, guards, kernel_land,
        findings, walk_fn,
    ):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_fn(node, qual, host=is_host)
            return
        # shallow: defs nested inside compound statements re-enter
        # walk_fn with their OWN host/device/guard flags instead of
        # being scanned under the enclosing function's. Lambdas are NOT
        # boundaries here — in kernel code they typically run inline
        # under the same trace (lax.cond branches etc.).
        for sub in shallow_walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if sub is not node:
                    walk_fn(sub, qual, host=is_host)
                continue
            if isinstance(sub, ast.Call):
                tail = dotted_name(sub.func).split(".")[-1]
                if tail in _CALLBACKS and not is_host and not (
                    _call_is_guarded(sub, guards)
                ):
                    findings.append(
                        Finding(
                            "tracing-host-callback", "error", sf.rel,
                            sub.lineno,
                            f"{tail} without a concreteness guard: add an "
                            "eager direct-numpy bypass (isinstance(x, "
                            "jax.core.Tracer) / _concrete()) — the jitted "
                            "callback path deadlocks on single-device CPU "
                            "and breaks on sharded inputs",
                            qual,
                        )
                    )
                if (
                    kernel_land
                    and device_fn
                    and dotted_name(sub.func).split(".")[0] == "np"
                ):
                    attr = dotted_name(sub.func).split(".")[1:]
                    if attr and attr[0] in _NP_ARRAY_CONSUMERS:
                        findings.append(
                            Finding(
                                "tracing-numpy-on-device", "warning", sf.rel,
                                sub.lineno,
                                f"np.{'.'.join(attr)} inside a device "
                                "function: fails on tracers under jit and "
                                "forces a host sync eagerly — use jnp, or "
                                "move the host step behind a guarded "
                                "callback/_host_ helper",
                                qual,
                            )
                        )
            tests = []
            if isinstance(sub, (ast.If, ast.While)):
                tests.append(sub.test)
            elif isinstance(sub, ast.Assert):
                tests.append(sub.test)
            elif isinstance(sub, ast.IfExp):
                tests.append(sub.test)
            for t in tests:
                if not (kernel_land and device_fn):
                    break
                bad = _is_bool_reducer_call(t)
                if bad is not None:
                    findings.append(
                        Finding(
                            "tracing-tracer-bool", "error", sf.rel,
                            bad.lineno,
                            "Python truthiness on a device-array reduction: "
                            "raises TracerBoolConversionError under jit — "
                            "use jnp.where/lax.cond or return the predicate "
                            "to an eager caller",
                            qual,
                        )
                    )


PASS = TracingSafetyPass()
