"""observability-coverage: breakers degrade visibly, stats stay reachable.

The ROADMAP guardrail is that degraded execution must be OBSERVABLE:
every kernel behind a `KernelCircuitBreaker` needs a fallback the
breaker can route to, and every counter object needs a path to the
EXPLAIN ANALYZE / snapshot surface — otherwise a new subsystem ships
dark and the first sign of trouble is a soak-test diff. Four rules:

breaker-no-fallback (error)
    A breaker name whose `BREAKERS.allow(name)` decision never gates a
    branch. Calling `allow()` and ignoring the result (or only ever
    calling `record_*`) means the breaker can open but execution never
    actually routes to a fallback — the circuit breaks nothing. The
    decision counts as consumed when it appears in an `if`/`while`/
    ternary test, is assigned to a variable, is returned, or the name
    goes through the `_kernel_guarded`/`_run_packed` wrappers (which
    fall back by construction).

breaker-undocumented (error)
    A breaker name absent from the docs/fault-tolerance.md breaker
    catalog (and docs/tuning.md) — the table that names each kernel
    path and its fallback is the operator-facing strategy mention.

stats-not-snapshotted (error)
    A `*Stats` class under exec/ or server/ that no snapshot surface
    consumes: nothing calls `.snapshot()` on an instance of it and its
    name never appears in a stats/snapshot/explain/summary-named
    function outside the class itself.

cache-not-snapshotted (error)
    A module-level `*Cache` instance in exec/qcache.py missing from
    `snapshot_all()` — the one aggregation point EXPLAIN ANALYZE and
    the server stats endpoints read.

stats-not-exported (error)
    A `*Stats` class that reaches a snapshot surface (passes
    stats-not-snapshotted) but never reaches the unified metrics plane
    (presto_tpu/obs/): its name never appears — as a reference or a
    parameter annotation — inside an export/metrics-named function.
    Snapshot-only stats show in EXPLAIN ANALYZE but stay invisible to
    `/v1/metrics` and `system.runtime.metrics`; every silo must feed
    both."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import (
    AnalysisPass,
    Finding,
    Project,
    dotted_name,
    iter_scoped_defs,
)
from .locks import _attr_classes

_REGISTRY_METHODS = {
    "allow", "record_failure", "record_success", "forced_fallback",
}
_WRAPPERS = {"_kernel_guarded", "_run_packed"}
_BREAKER_DOCS = ("docs/fault-tolerance.md", "docs/tuning.md")
_QCACHE_FILE = "presto_tpu/exec/qcache.py"
_SNAPSHOT_ALL = "snapshot_all"
_SURFACE_TOKENS = ("snapshot", "stats", "status", "explain", "summary")
_EXPORT_TOKENS = ("export", "metrics")
_STATS_SCOPES = (
    "presto_tpu/exec/", "presto_tpu/server/",
    "presto_tpu/plan/history.py",
)


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class ObservabilityCoveragePass(AnalysisPass):
    name = "observability-coverage"
    description = "breaker fallback/doc coverage; stats snapshot reach"
    rules = (
        "breaker-no-fallback",
        "breaker-undocumented",
        "stats-not-snapshotted",
        "cache-not-snapshotted",
        "stats-not-exported",
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        findings += self._check_breakers(project)
        findings += self._check_stats_classes(project)
        findings += self._check_qcache_globals(project)
        return findings

    # -- breakers ------------------------------------------------------------

    def _check_breakers(self, project: Project) -> List[Finding]:
        # name -> [(file, line)], plus whether fallback evidence exists
        sites: Dict[str, List[Tuple[str, int]]] = {}
        has_fallback: Set[str] = set()
        has_allow: Set[str] = set()

        for sf in project.iter_files("presto_tpu/"):
            # expression positions where a decision gates a branch:
            # if/while/ternary tests, assignment values, return values
            gated: Set[int] = set()
            for node in ast.walk(sf.tree):
                roots = []
                if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                    roots = [node.test]
                elif isinstance(node, ast.Assign):
                    roots = [node.value]
                elif isinstance(node, (ast.Return, ast.AnnAssign)):
                    if getattr(node, "value", None) is not None:
                        roots = [node.value]
                for r in roots:
                    for sub in ast.walk(r):
                        gated.add(id(sub))

            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not isinstance(node.func, ast.Attribute):
                    # wrapper helpers called as bare names
                    tail = dotted_name(node.func)
                else:
                    tail = node.func.attr
                if tail in _WRAPPERS or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _WRAPPERS
                ):
                    for arg in node.args:
                        s = _const_str(arg)
                        if s:
                            sites.setdefault(s, []).append(
                                (sf.rel, node.lineno)
                            )
                            has_fallback.add(s)
                            has_allow.add(s)
                            break
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                if node.func.attr not in _REGISTRY_METHODS:
                    continue
                recv = dotted_name(node.func.value)
                if recv.split(".")[-1] != "BREAKERS":
                    continue
                name = _const_str(node.args[0]) if node.args else None
                if name is None:
                    continue
                sites.setdefault(name, []).append((sf.rel, node.lineno))
                if node.func.attr == "allow":
                    has_allow.add(name)
                    if id(node) in gated:
                        has_fallback.add(name)

        documented = ""
        for rel in _BREAKER_DOCS:
            path = project.root / rel
            if path.exists():
                documented += path.read_text(encoding="utf-8")

        findings: List[Finding] = []
        for name in sorted(sites):
            f, ln = sorted(sites[name])[0]
            if name not in has_fallback:
                why = (
                    "allow() result never gates a branch"
                    if name in has_allow
                    else "no allow() gate anywhere — only record_* calls"
                )
                findings.append(
                    Finding(
                        "breaker-no-fallback", "error", f, ln,
                        f"breaker '{name}' has no reachable fallback "
                        f"branch ({why})",
                    )
                )
            if f"`{name}`" not in documented and name not in documented:
                findings.append(
                    Finding(
                        "breaker-undocumented", "error", f, ln,
                        f"breaker '{name}' missing from the "
                        f"{_BREAKER_DOCS[0]} fallback catalog",
                    )
                )
        return findings

    # -- *Stats classes ------------------------------------------------------

    def _check_stats_classes(self, project: Project) -> List[Finding]:
        attr_cls = _attr_classes(project)

        # every *Stats class defined under the runtime scopes
        stats_classes: Dict[str, Tuple[str, int]] = {}
        for sf in project.iter_files("presto_tpu/"):
            if not sf.rel.startswith(_STATS_SCOPES):
                continue
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef) and node.name.endswith(
                    "Stats"
                ):
                    stats_classes.setdefault(
                        node.name, (sf.rel, node.lineno)
                    )

        surfaced: Set[str] = set()
        exported: Set[str] = set()
        for sf in project.iter_files("presto_tpu/"):
            for fn, cnode in iter_scoped_defs(sf.tree.body):
                cls = cnode.name if cnode is not None else None
                # (b) class named inside a stats/snapshot/explain/...
                # function that is not one of its own methods
                fn_is_surface = any(
                    t in fn.name for t in _SURFACE_TOKENS
                )
                # metrics-plane reach: the class named (by reference or
                # by parameter annotation — quoted annotations are str
                # constants) inside an export/metrics-named function.
                # Str constants count ONLY in annotation positions: a
                # docstring or help text merely mentioning the class is
                # not an export.
                if any(t in fn.name for t in _EXPORT_TOKENS):
                    ann_ids: Set[int] = set()
                    a = fn.args
                    ann_roots = [
                        arg.annotation
                        for arg in (
                            list(getattr(a, "posonlyargs", []))
                            + list(a.args) + list(a.kwonlyargs)
                            + [a.vararg, a.kwarg]
                        )
                        if arg is not None and arg.annotation is not None
                    ]
                    if fn.returns is not None:
                        ann_roots.append(fn.returns)
                    for sub in ast.walk(fn):
                        if isinstance(sub, ast.AnnAssign):
                            ann_roots.append(sub.annotation)
                    for root in ann_roots:
                        for sub in ast.walk(root):
                            ann_ids.add(id(sub))
                    for node in ast.walk(fn):
                        ref = None
                        if isinstance(node, ast.Name):
                            ref = node.id
                        elif (
                            id(node) in ann_ids
                            and isinstance(node, ast.Constant)
                            and isinstance(node.value, str)
                        ):
                            ref = node.value.split(".")[-1].strip("'\"")
                        if ref in stats_classes and cls != ref:
                            exported.add(ref)
                # local/param typing for (a): v = CStats() assigns and
                # `x: CStats` annotations inside this function
                typed: Dict[str, str] = {}
                for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
                    ann = arg.annotation
                    if ann is None:
                        continue
                    t = _const_str(ann) or dotted_name(ann)
                    t = t.split(".")[-1].strip("'\"")
                    if t in stats_classes:
                        typed[arg.arg] = t
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Call
                    ):
                        ctor = dotted_name(node.value.func).split(".")[-1]
                        if ctor in stats_classes:
                            for t in node.targets:
                                if isinstance(t, ast.Name):
                                    typed[t.id] = ctor
                    if (
                        fn_is_surface
                        and isinstance(node, ast.Name)
                        and node.id in stats_classes
                        and cls != node.id
                    ):
                        surfaced.add(node.id)
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "snapshot"
                    ):
                        recv = node.func.value
                        rcls = None
                        if isinstance(recv, ast.Name):
                            rcls = typed.get(recv.id)
                        elif (
                            isinstance(recv, ast.Attribute)
                            and isinstance(recv.value, ast.Name)
                            and recv.value.id == "self"
                            and cls is not None
                        ):
                            rcls = attr_cls.get((sf.rel, cls), {}).get(
                                recv.attr
                            )
                        if rcls in stats_classes:
                            surfaced.add(rcls)
            # module-level globals: G = CStats(); G.snapshot() elsewhere
            mod_typed: Dict[str, str] = {}
            for node in sf.tree.body:
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    ctor = dotted_name(node.value.func).split(".")[-1]
                    if ctor in stats_classes:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                mod_typed[t.id] = ctor
            if mod_typed:
                for node in ast.walk(sf.tree):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "snapshot"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in mod_typed
                    ):
                        surfaced.add(mod_typed[node.func.value.id])

        findings = [
            Finding(
                "stats-not-snapshotted", "error", rel, line,
                f"{name} is not reachable from any snapshot/stats/"
                f"explain surface — its counters are write-only",
                name,
            )
            for name, (rel, line) in sorted(stats_classes.items())
            if name not in surfaced
        ]
        # only classes that PASS stats-not-snapshotted are held to the
        # export bar — a write-only silo already has the stronger finding
        findings += [
            Finding(
                "stats-not-exported", "error", rel, line,
                f"{name} reaches a snapshot surface but never the "
                f"metrics plane — no export/metrics-named function "
                f"references it (presto_tpu/obs/export.py)",
                name,
            )
            for name, (rel, line) in sorted(stats_classes.items())
            if name in surfaced and name not in exported
        ]
        return findings

    # -- qcache globals ------------------------------------------------------

    def _check_qcache_globals(self, project: Project) -> List[Finding]:
        sf = project.file(_QCACHE_FILE)
        if sf is None:
            return []
        caches: Dict[str, int] = {}
        snap_fn = None
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                ctor = dotted_name(node.value.func).split(".")[-1]
                if ctor.endswith("Cache"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            caches.setdefault(t.id, node.lineno)
            elif (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == _SNAPSHOT_ALL
            ):
                snap_fn = node
        referenced: Set[str] = set()
        if snap_fn is not None:
            for node in ast.walk(snap_fn):
                if isinstance(node, ast.Name):
                    referenced.add(node.id)
        return [
            Finding(
                "cache-not-snapshotted", "error", _QCACHE_FILE, line,
                f"{name} missing from {_SNAPSHOT_ALL}() — EXPLAIN "
                f"ANALYZE and the stats endpoints cannot see it",
            )
            for name, line in sorted(caches.items())
            if name not in referenced
        ]


PASS = ObservabilityCoveragePass()
