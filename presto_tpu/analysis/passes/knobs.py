"""knob-consistency: every PRESTO_TPU_* env knob parses once, docs match.

The tuning surface (docs/tuning.md) is part of the serving contract:
operators set knobs from the docs, and a knob that is parsed in two
modules with two defaults silently configures half the fleet. This pass
pins code/doc parity both ways:

knob-multi-parse (error)
    One `PRESTO_TPU_*` name is parsed (read WITH a default, directly or
    through an env helper) at more than one site. A knob gets exactly
    one parse site — a module-level helper or constant that everything
    else imports — so a default change cannot diverge by file.

knob-undocumented (error)
    A knob read in code but absent from docs/tuning.md and
    docs/static-analysis.md. New knobs ship documented or not at all.

knob-near-miss (error)
    A name within edit distance 1 of a known knob, on either side: code
    reads a name the docs never mention but a documented knob is one
    typo away, or the docs describe a name the code never reads but a
    parsed knob is one typo away. Both are almost always typos, and a
    typo'd env read fails silent — the default always wins.

knob-stale-doc (warning)
    A documented knob no code reads or writes any more. Stale docs send
    operators chasing a control that no longer exists.

Reads WITHOUT a default (`os.environ.get(name)` one-arg, subscripts,
`in os.environ` membership) are save/restore probes, not parse sites —
the benchmark harness snapshots and restores knobs this way — and env
WRITES (`os.environ[k] = v`, setdefault, pop) never count as parsing.
Env-helper calls count as parse sites when the helper is a module-level
function anywhere in the tree whose body reads `os.environ`."""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..core import AnalysisPass, Finding, Project, dotted_name

_PREFIX = "PRESTO_TPU_"
_DOC_FILES = ("docs/tuning.md", "docs/static-analysis.md")
_DOC_RE = re.compile(r"PRESTO_TPU_[A-Z0-9_]+")


def _edit_distance_1(a: str, b: str) -> bool:
    """True when a != b and one substitution/insertion/deletion maps
    a -> b. Cheap specialized check — no DP table needed for d<=1."""
    if a == b:
        return False
    la, lb = len(a), len(b)
    if abs(la - lb) > 1:
        return False
    if la == lb:
        return sum(x != y for x, y in zip(a, b)) == 1
    if la > lb:
        a, b, la, lb = b, a, lb, la
    # a is shorter by one: b must equal a with one char inserted
    i = 0
    while i < la and a[i] == b[i]:
        i += 1
    return a[i:] == b[i + 1:]


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _env_helpers(project: Project) -> Set[str]:
    """Names of module-level functions whose body touches os.environ —
    `_env_int`-style parse helpers, matched by bare name at call sites."""

    def build(p: Project):
        out: Set[str] = set()
        for sf in p.iter_files("presto_tpu/"):
            for node in sf.tree.body:
                if not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                for sub in ast.walk(node):
                    name = dotted_name(sub) if isinstance(
                        sub, ast.Attribute
                    ) else ""
                    if name.startswith("os.environ") or name == "os.getenv":
                        out.add(node.name)
                        break
        return out

    return project.symbol("env_helpers", build)


class KnobConsistencyPass(AnalysisPass):
    name = "knob-consistency"
    description = "PRESTO_TPU_* knobs: one parse site, doc parity, typos"
    rules = (
        "knob-multi-parse",
        "knob-undocumented",
        "knob-near-miss",
        "knob-stale-doc",
    )

    def run(self, project: Project) -> List[Finding]:
        helpers = _env_helpers(project)
        # knob -> [(file, line, default-repr)]
        parse_sites: Dict[str, List[Tuple[str, int, str]]] = {}
        reads: Dict[str, List[Tuple[str, int]]] = {}  # incl. probes
        writes: Dict[str, List[Tuple[str, int]]] = {}

        for sf in project.iter_files("presto_tpu/"):
            # `env = os.environ.get` aliases (module or function scope)
            aliases: Set[str] = set()
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Assign) and dotted_name(
                    node.value
                ) in ("os.environ.get", "os.getenv"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            aliases.add(t.id)

            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    fname = dotted_name(node.func)
                    tail = fname.split(".")[-1]
                    first = _const_str(node.args[0]) if node.args else None
                    if first is None or not first.startswith(_PREFIX):
                        # os.environ.setdefault/pop with knob first arg
                        continue
                    if fname in ("os.environ.get", "os.getenv") or (
                        isinstance(node.func, ast.Name)
                        and node.func.id in aliases
                    ):
                        reads.setdefault(first, []).append(
                            (sf.rel, node.lineno)
                        )
                        if len(node.args) >= 2:
                            parse_sites.setdefault(first, []).append(
                                (
                                    sf.rel,
                                    node.lineno,
                                    self._default_repr(node.args[1]),
                                )
                            )
                    elif fname in (
                        "os.environ.setdefault", "os.environ.pop",
                    ):
                        writes.setdefault(first, []).append(
                            (sf.rel, node.lineno)
                        )
                    elif (
                        isinstance(node.func, ast.Name)
                        and node.func.id in helpers
                    ):
                        reads.setdefault(first, []).append(
                            (sf.rel, node.lineno)
                        )
                        parse_sites.setdefault(first, []).append(
                            (
                                sf.rel,
                                node.lineno,
                                self._default_repr(
                                    node.args[1]
                                    if len(node.args) >= 2
                                    else None
                                ),
                            )
                        )
                elif isinstance(node, ast.Subscript):
                    if dotted_name(node.value) != "os.environ":
                        continue
                    key = _const_str(
                        node.slice.value
                        if isinstance(node.slice, ast.Index)  # py<3.9
                        else node.slice
                    )
                    if key is None or not key.startswith(_PREFIX):
                        continue
                    if isinstance(node.ctx, (ast.Store, ast.Del)):
                        writes.setdefault(key, []).append(
                            (sf.rel, node.lineno)
                        )
                    else:
                        reads.setdefault(key, []).append(
                            (sf.rel, node.lineno)
                        )
                elif isinstance(node, ast.Compare):
                    # `"PRESTO_TPU_X" in os.environ` membership probe
                    if any(
                        dotted_name(c) == "os.environ"
                        for c in node.comparators
                    ):
                        key = _const_str(node.left)
                        if key and key.startswith(_PREFIX):
                            reads.setdefault(key, []).append(
                                (sf.rel, node.lineno)
                            )

        documented: Dict[str, Tuple[str, int]] = {}
        for rel in _DOC_FILES:
            path = project.root / rel
            if not path.exists():
                continue
            for i, line in enumerate(
                path.read_text(encoding="utf-8").splitlines(), start=1
            ):
                for m in _DOC_RE.finditer(line):
                    name = m.group(0)
                    if name.endswith("_"):
                        # family wildcard ("PRESTO_TPU_BREAKER_*"), not
                        # a knob name — the member knobs stand alone
                        continue
                    documented.setdefault(name, (rel, i))

        findings: List[Finding] = []
        known = set(parse_sites) | set(documented)

        for knob in sorted(parse_sites):
            sites = sorted(parse_sites[knob])
            if len(sites) > 1:
                desc = ", ".join(
                    f"{f} (default {d})" for f, _ln, d in sites
                )
                findings.append(
                    Finding(
                        "knob-multi-parse", "error",
                        sites[0][0], sites[0][1],
                        f"{knob} parsed at {len(sites)} sites — one "
                        f"module-level parse site per knob: {desc}",
                    )
                )

        near_pairs: set = set()
        for knob in sorted(reads):
            if knob in documented:
                continue
            near = sorted(
                d for d in documented if _edit_distance_1(knob, d)
            )
            f, ln = sorted(reads[knob])[0]
            if near:
                near_pairs.add(frozenset((knob, near[0])))
                findings.append(
                    Finding(
                        "knob-near-miss", "error", f, ln,
                        f"{knob} read in code but undocumented — one "
                        f"edit away from documented {near[0]} (typo?)",
                    )
                )
            else:
                findings.append(
                    Finding(
                        "knob-undocumented", "error", f, ln,
                        f"{knob} read in code but absent from "
                        f"{' and '.join(_DOC_FILES)}",
                    )
                )

        code_names = set(reads) | set(writes)
        for knob in sorted(documented):
            if knob in code_names:
                continue
            rel, ln = documented[knob]
            near = sorted(
                c for c in code_names if _edit_distance_1(knob, c)
            )
            if near:
                # one finding per typo pair: the code-side report above
                # already covers (code_name, doc_name)
                if frozenset((knob, near[0])) not in near_pairs:
                    findings.append(
                        Finding(
                            "knob-near-miss", "error", rel, ln,
                            f"{knob} documented but never read — one "
                            f"edit away from code knob {near[0]} "
                            f"(typo?)",
                        )
                    )
            else:
                findings.append(
                    Finding(
                        "knob-stale-doc", "warning", rel, ln,
                        f"{knob} documented in {rel} but no code reads "
                        f"or writes it",
                    )
                )
        return findings

    @staticmethod
    def _default_repr(node) -> str:
        if node is None:
            return "<none>"
        if isinstance(node, ast.Constant):
            return repr(node.value)
        return "<dynamic>"


PASS = KnobConsistencyPass()
