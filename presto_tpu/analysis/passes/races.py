"""guarded-field races: mutations of lock-guarded attrs outside the lock.

The locks pass checks what happens WHILE a lock is held; this pass
checks that shared state is not touched WITHOUT one. For every class
that creates a `threading.Lock/RLock/Condition`, it infers which lock
guards each `self.<attr>` by majority-of-accesses — an attr read or
written under `with self._lock` in two or more distinct methods is
"guarded by" that lock — and then flags every mutation of a guarded
attr made outside it:

race-unguarded-mutation (error)
    An assignment, aug-assign, `del`, subscript store, or mutating
    container-method call (`append`/`pop`/`update`/...) on a guarded
    attr with the guard not held. Mutations inside nested defs and
    lambdas are analyzed with an EMPTY held set (a callback built under
    a lock does not run under it), which is exactly how a thread target
    that scribbles on shared state gets caught. Passing a guarded
    container into `submit()`/`Thread(...)` outside the lock is also
    flagged — publication hands the object to another thread with no
    happens-before edge.

Inference reuses the locks pass's machinery: lock identity is the
inheritance-resolved `DefiningClass.attr` (so a subclass method holding
the base's condition counts), `iter_scoped_defs` walks the same scope
shapes, and one level of call-graph propagation whitelists `_locked`
-style helpers whose every in-class call site holds the guard.

Cross-object writes get one level of the same treatment: a mutation
reached through `self.X.Y...` where `self.X = SomeClass(...)` and `Y`
is guarded inside SomeClass is flagged too — `self.scheduler.stats.x =
v` from a class that never takes the scheduler's lock races every
scheduler thread that mutates `stats` under it. Holding the foreign
lock the chained way (`with self.manager._lock:`) is resolved through
the same attribute-type table, and method CALLS on a foreign object
are never flagged (the method synchronizes internally); only direct
field writes and container-mutator calls reach through.

`__init__` is exempt (construction happens-before publication of self),
attrs whose value is itself a lock/queue/thread/future/threading.local
are skipped (those types carry their own synchronization), and reads
outside the lock are deliberately NOT flagged — a torn stats read is a
display glitch, not a corruption. Intentional benign races are
suppressed at the site with:

    # prestolint: unguarded(attr) -- reason

which documents the claim next to the code it covers."""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ..core import (
    AnalysisPass,
    Finding,
    Project,
    SourceFile,
    dotted_name,
    iter_scoped_defs,
)
from ..symbols import attr_kinds
from .locks import _attr_classes

# container mutators: calling one of these ON a guarded attr mutates it
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "appendleft", "popleft",
    "sort", "reverse", "__setitem__", "__delitem__",
}
# calling one of these PUBLISHES its arguments to another thread
_PUBLISHERS = {"submit", "Thread", "start_new_thread", "run_in_executor"}
# attr value kinds that synchronize themselves — never inferred as state
_SELF_SYNC_KINDS = {"lock", "queue", "thread", "future", "tls"}

_MARKER_FMT = "prestolint: unguarded({attr})"


@dataclasses.dataclass
class Access:
    scope: str  # method name, dotted for nested defs ("flush.cb")
    held: Tuple[str, ...]  # lock ids held at the access site
    mutates: bool
    publishes: bool  # guarded attr passed into a thread/executor call
    line: int


@dataclasses.dataclass
class ClassRecord:
    file: str
    cls: str
    accesses: Dict[str, List[Access]] = dataclasses.field(
        default_factory=dict
    )
    # method -> held sets at every `self.m()` call site inside the class
    call_sites: Dict[str, List[Tuple[str, ...]]] = dataclasses.field(
        default_factory=dict
    )
    # methods handed to a thread/executor as `self.m` — their bodies run
    # on any thread, so call-site lock propagation is off for them
    escaped: Set[str] = dataclasses.field(default_factory=set)
    # (obj_attr, field, scope, held, line): mutations reaching THROUGH
    # `self.X.Y...` — checked against type(X)'s inferred guards
    foreign: List[Tuple[str, str, str, Tuple[str, ...], int]] = (
        dataclasses.field(default_factory=list)
    )


def _base_self_attr(expr) -> Optional[str]:
    """`self.a`, `self.a.b`, `self.a[k]...` -> 'a'; else None."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        inner = expr.value
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(inner, ast.Name)
            and inner.id == "self"
        ):
            return expr.attr
        expr = inner
    return None


def _self_spine(expr) -> List[str]:
    """Pure-attribute spine rooted at self: `self.a.b.c` ->
    ['a', 'b', 'c']; [] when not self-rooted or broken by a subscript.
    Cross-object guard checks need the SECOND hop (`self.X.Y`), and a
    subscript between self and Y would retype the object mid-chain."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name) and expr.id == "self":
        return list(reversed(parts))
    return []


class GuardedFieldPass(AnalysisPass):
    name = "guarded-fields"
    description = "mutations of lock-guarded attrs outside the lock"
    rules = ("race-unguarded-mutation",)

    def run(self, project: Project) -> List[Finding]:
        kinds = attr_kinds(project)
        cls_attr: Dict[str, Dict[str, str]] = {}
        cls_bases: Dict[str, List[str]] = {}
        for sf in project.files:
            for cname, attrs in kinds[sf.rel].classes.items():
                m = cls_attr.setdefault(cname, {})
                for a, k in attrs.items():
                    m.setdefault(a, k)
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    cls_bases.setdefault(
                        node.name,
                        [dotted_name(b).split(".")[-1] for b in node.bases],
                    )

        def resolve_attr(cls: Optional[str], attr: str):
            queue, seen = [cls] if cls else [], set()
            while queue:
                cur = queue.pop(0)
                if cur in seen or cur is None:
                    continue
                seen.add(cur)
                if attr in cls_attr.get(cur, {}):
                    return cur, cls_attr[cur][attr]
                queue.extend(cls_bases.get(cur, []))
            return None, None

        # class name -> {attr: ClassName}, merged across files, for
        # typing `self.X.Y` chains (first definition wins on collision)
        attr_cls = _attr_classes(project)
        cls_attr_types: Dict[str, Dict[str, str]] = {}
        for (_f, c), m in sorted(attr_cls.items()):
            tgt = cls_attr_types.setdefault(c, {})
            for a, t in m.items():
                tgt.setdefault(a, t)

        records: List[ClassRecord] = []
        for sf in project.iter_files("presto_tpu/"):
            records.extend(
                self._collect_file(sf, resolve_attr, cls_attr_types)
            )
        return self._infer_and_report(
            project, records, resolve_attr, attr_cls
        )

    # -- phase A: per-class access collection --------------------------------

    def _collect_file(self, sf: SourceFile, resolve_attr, cls_attr_types):
        by_cls: Dict[str, ClassRecord] = {}

        def lock_id(expr, cls) -> Optional[str]:
            if not isinstance(expr, ast.Attribute):
                return None
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                owner, kind = resolve_attr(cls, expr.attr)
                if kind == "lock":
                    return f"{owner}.{expr.attr}"
                return None
            # chained receiver: `with self.manager._lock:` — type the
            # spine through the attribute-class table
            spine = _self_spine(expr.value)
            if spine:
                cur = cls
                for a in spine:
                    cur = cls_attr_types.get(cur, {}).get(a)
                    if cur is None:
                        return None
                owner, kind = resolve_attr(cur, expr.attr)
                if kind == "lock":
                    return f"{owner}.{expr.attr}"
            return None

        def record(rec, attr, scope, held, line, mutates, publishes=False):
            rec.accesses.setdefault(attr, []).append(
                Access(scope, tuple(held), mutates, publishes, line)
            )

        def note_foreign(rec, expr, scope, held, line):
            """Mutation target/receiver reaching through `self.X.Y`."""
            while isinstance(expr, ast.Subscript):
                expr = expr.value
            spine = _self_spine(expr)
            if len(spine) >= 2:
                rec.foreign.append(
                    (spine[0], spine[1], scope, tuple(held), line)
                )

        def scan_expr(top, rec, cls, scope, held):
            """Reads, mutating calls, in-class call sites and
            publications inside one expression. Lambdas are deferred
            execution: their bodies re-scan with an empty held set."""
            stack = [(top, tuple(held))]
            no_read: Set[int] = set()  # Attribute nodes that are call
            while stack:  # targets, not data reads
                node, h = stack.pop()
                if isinstance(node, ast.Lambda):
                    stack.append((node.body, ()))
                    continue
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(node, ast.Call):
                    tail = (
                        node.func.attr
                        if isinstance(node.func, ast.Attribute)
                        else dotted_name(node.func)
                    )
                    if isinstance(node.func, ast.Attribute):
                        fv = node.func.value
                        if isinstance(fv, ast.Name) and fv.id == "self":
                            # `self.m(...)`: a call site for lock
                            # propagation, not a data read of `m`
                            rec.call_sites.setdefault(
                                node.func.attr, []
                            ).append(h)
                            no_read.add(id(node.func))
                        else:
                            base = _base_self_attr(fv)
                            if base is not None and tail in _MUTATORS:
                                record(
                                    rec, base, scope, h, node.lineno, True
                                )
                                note_foreign(
                                    rec, fv, scope, h, node.lineno
                                )
                    if tail in _PUBLISHERS:
                        args = list(node.args) + [
                            k.value for k in node.keywords
                        ]
                        flat = []
                        for a in args:
                            if isinstance(a, (ast.Tuple, ast.List)):
                                flat.extend(a.elts)
                            else:
                                flat.append(a)
                        for a in flat:
                            if (
                                isinstance(a, ast.Attribute)
                                and isinstance(a.value, ast.Name)
                                and a.value.id == "self"
                            ):
                                # `self.x` handed to another thread:
                                # treat as both an escape of the method
                                # name and a publication of the attr
                                rec.escaped.add(a.attr)
                                record(
                                    rec, a.attr, scope, h, node.lineno,
                                    False, publishes=True,
                                )
                                no_read.add(id(a))
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and id(node) not in no_read
                ):
                    record(rec, node.attr, scope, h, node.lineno, False)
                for c in ast.iter_child_nodes(node):
                    stack.append((c, h))

        def scan_stmt(stmt, rec, cls, scope, held):
            """Simple-statement classification: mutation targets first,
            then reads in the value expressions."""
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    stmt.targets
                    if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )

                def note_target(t):
                    a = _base_self_attr(t)
                    if a is None:
                        return
                    record(rec, a, scope, held, stmt.lineno, True)
                    note_foreign(rec, t, scope, held, stmt.lineno)

                for t in targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        for el in t.elts:
                            note_target(el)
                    else:
                        note_target(t)
                    # subscript/attr chains below the base still read
                    # other attrs (self.a[self.k] = v) — scan indices
                    for sub in ast.iter_child_nodes(t):
                        if not isinstance(sub, ast.Name):
                            scan_expr(sub, rec, cls, scope, held)
                value = getattr(stmt, "value", None)
                if value is not None:
                    scan_expr(value, rec, cls, scope, held)
                return
            if isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    a = _base_self_attr(t)
                    if a is not None:
                        record(rec, a, scope, held, stmt.lineno, True)
                        note_foreign(rec, t, scope, held, stmt.lineno)
                return
            scan_expr(stmt, rec, cls, scope, held)

        def walk(stmts, rec, cls, scope, held):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # deferred execution: a nested def built here runs on
                    # its own schedule — fresh held set, dotted scope
                    walk(stmt.body, rec, cls, f"{scope}.{stmt.name}", ())
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    new = []
                    for item in stmt.items:
                        lid = lock_id(item.context_expr, cls)
                        if lid is not None:
                            new.append(lid)
                        else:
                            scan_expr(
                                item.context_expr, rec, cls, scope, held
                            )
                    walk(stmt.body, rec, cls, scope, held + tuple(new))
                    continue
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        walk(sub, rec, cls, scope, held)
                for h in getattr(stmt, "handlers", ()):
                    walk(h.body, rec, cls, scope, held)
                if isinstance(stmt, (ast.If, ast.While)):
                    scan_expr(stmt.test, rec, cls, scope, held)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    scan_expr(stmt.iter, rec, cls, scope, held)
                elif isinstance(stmt, (ast.Try, ast.ClassDef)):
                    pass
                else:
                    scan_stmt(stmt, rec, cls, scope, held)

        for fn, cnode in iter_scoped_defs(sf.tree.body):
            # lock-less classes still collect: their cross-object writes
            # are checked against the TARGET class's inferred guards
            if cnode is None:
                continue
            rec = by_cls.setdefault(
                cnode.name, ClassRecord(sf.rel, cnode.name)
            )
            walk(fn.body, rec, cnode.name, fn.name, ())

        return list(by_cls.values())

    # -- phase B: inference + report -----------------------------------------

    def _infer_and_report(self, project, records, resolve_attr, attr_cls):
        # call-site lock propagation per class: methods whose every
        # in-class call site holds lock L run "as if" under L (the
        # `_foo_locked` convention), disabled once the method escapes
        # as a callback handle
        assumed_by_rec: Dict[int, Dict[str, Set[str]]] = {}
        for rec in records:
            assumed: Dict[str, Set[str]] = {}
            for m, sites in rec.call_sites.items():
                if m in rec.escaped or not sites:
                    continue
                common = set(sites[0])
                for s in sites[1:]:
                    common &= set(s)
                if common:
                    assumed[m] = common
            assumed_by_rec[id(rec)] = assumed

        def eff_held(rec: ClassRecord, scope: str, held) -> Set[str]:
            out = set(held)
            root = scope.split(".")[0]
            # propagation covers the method's direct body only — a
            # nested def inside it still runs later, lock released
            if scope == root:
                out |= assumed_by_rec[id(rec)].get(root, set())
            return out

        # guard inference: majority-of-accesses, >=2 distinct methods
        guards_by_rec: Dict[int, Dict[str, Tuple[str, int]]] = {}
        for rec in records:
            guards: Dict[str, Tuple[str, int]] = {}
            for attr, accs in rec.accesses.items():
                _owner, kind = resolve_attr(rec.cls, attr)
                if kind in _SELF_SYNC_KINDS:
                    continue
                by_lock: Dict[str, Set[str]] = {}
                for a in accs:
                    if a.scope == "__init__":
                        continue
                    for lid in eff_held(rec, a.scope, a.held):
                        by_lock.setdefault(lid, set()).add(
                            a.scope.split(".")[0]
                        )
                cands = {
                    lid: ms for lid, ms in by_lock.items() if len(ms) >= 2
                }
                if not cands:
                    continue
                best = max(len(ms) for ms in cands.values())
                top = [
                    lid for lid, ms in cands.items() if len(ms) == best
                ]
                if len(top) == 1:  # ambiguous guard: refuse to infer
                    guards[attr] = (top[0], best)
            guards_by_rec[id(rec)] = guards

        # class-name view for cross-object checks; conflicting
        # same-name classes (different files) drop the conflicted attr
        guards_by_cls: Dict[str, Dict[str, Tuple[str, int]]] = {}
        for rec in records:
            g = guards_by_rec[id(rec)]
            if not g:
                continue
            cur = guards_by_cls.setdefault(rec.cls, {})
            for a, info in g.items():
                if a in cur and cur[a] != info:
                    cur[a] = ("", 0)
                else:
                    cur.setdefault(a, info)

        findings: List[Finding] = []
        for rec in records:
            sf = project.file(rec.file)
            guards = guards_by_rec[id(rec)]

            for attr, (guard, nmethods) in sorted(guards.items()):
                marker = _MARKER_FMT.format(attr=attr)
                for a in rec.accesses[attr]:
                    if not (a.mutates or a.publishes):
                        continue
                    if guard in eff_held(rec, a.scope, a.held):
                        continue
                    if a.scope == "__init__":
                        continue  # happens-before publication of self
                    if sf is not None and sf.has_marker(a.line, marker):
                        continue
                    if a.publishes:
                        what = (
                            f"self.{attr} published into a thread/"
                            f"executor callback outside {guard}"
                        )
                    elif "." in a.scope:
                        what = (
                            f"self.{attr} mutated in deferred callback "
                            f"without {guard}"
                        )
                    else:
                        what = f"self.{attr} mutated outside {guard}"
                    findings.append(
                        Finding(
                            "race-unguarded-mutation", "error",
                            rec.file, a.line,
                            f"{what} (guarded by {guard} in "
                            f"{nmethods} methods)",
                            f"{rec.cls}.{a.scope}",
                        )
                    )

            for x, y, scope, held, line in rec.foreign:
                if scope == "__init__":
                    continue
                tcls = attr_cls.get((rec.file, rec.cls), {}).get(x)
                if tcls is None or tcls == rec.cls:
                    continue
                info = guards_by_cls.get(tcls, {}).get(y)
                if not info or not info[0]:
                    continue
                guard, nmethods = info
                if guard in eff_held(rec, scope, held):
                    continue
                if sf is not None and (
                    sf.has_marker(line, _MARKER_FMT.format(attr=y))
                    or sf.has_marker(
                        line, _MARKER_FMT.format(attr=f"{x}.{y}")
                    )
                ):
                    continue
                findings.append(
                    Finding(
                        "race-unguarded-mutation", "error",
                        rec.file, line,
                        f"self.{x}.{y} mutated outside {guard} "
                        f"({tcls}.{y} is guarded by {guard} in "
                        f"{nmethods} methods)",
                        f"{rec.cls}.{scope}",
                    )
                )
        return findings


PASS = GuardedFieldPass()
