"""Cross-file symbol tables shared by prestolint passes.

Small, purpose-built views of the tree: which classes are plan nodes /
IR nodes, which methods a class defines, which attributes a class binds
to locks or queues or threads. Everything is name-based AST analysis —
no imports of the analyzed code, so the linter can run on a tree that
would fail at import time."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Project, SourceFile, dotted_name

PLAN_NODE_FILES = ("presto_tpu/plan/nodes.py", "presto_tpu/plan/fragment.py")
IR_FILE = "presto_tpu/expr/ir.py"


def _subclasses_of(sf: SourceFile, bases: Set[str]) -> List[str]:
    """Class names in `sf` whose direct base matches one of `bases`
    (matching both `PlanNode` and `N.PlanNode` spellings)."""
    out = []
    for node in sf.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for b in node.bases:
            name = dotted_name(b)
            if name in bases or name.split(".")[-1] in bases:
                out.append(node.name)
                break
    return out


def plan_node_classes(project: Project) -> List[Tuple[str, str]]:
    """[(file, class)] for every concrete PlanNode subclass."""

    def build(p: Project):
        found = []
        for rel in PLAN_NODE_FILES:
            sf = p.file(rel)
            if sf is None:
                continue
            for cls in _subclasses_of(sf, {"PlanNode"}):
                found.append((rel, cls))
        return found

    return project.symbol("plan_nodes", build)


def ir_node_classes(project: Project) -> List[Tuple[str, str]]:
    """[(file, class)] for every RowExpression subclass."""

    def build(p: Project):
        sf = p.file(IR_FILE)
        if sf is None:
            return []
        return [(IR_FILE, c) for c in _subclasses_of(sf, {"RowExpression"})]

    return project.symbol("ir_nodes", build)


def class_def(sf: SourceFile, name: str) -> Optional[ast.ClassDef]:
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def method_names(sf: SourceFile, cls: str) -> Set[str]:
    node = class_def(sf, cls)
    if node is None:
        return set()
    return {
        n.name
        for n in node.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def function_def(sf: SourceFile, name: str) -> Optional[ast.FunctionDef]:
    for node in sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name == name
        ):
            return node
    return None


# -- attribute classification (locks / queues / threads) ---------------------

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
_THREAD_CTORS = {"Thread"}
_FUTURE_SOURCES = {"Future", "submit"}  # Future() ctor or pool.submit(...)
_TLS_CTORS = {"local"}  # threading.local() — per-thread, needs no lock


def _ctor_kind(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    tail = name.split(".")[-1]
    if tail in _LOCK_CTORS:
        return "lock"
    if tail in _QUEUE_CTORS:
        return "queue"
    if tail in _THREAD_CTORS:
        return "thread"
    if tail in _FUTURE_SOURCES:
        return "future"
    if tail in _TLS_CTORS and name.split(".")[0] in ("threading", "local"):
        return "tls"
    return None


class AttrKinds:
    """Per-file map of lock/queue/thread-valued names.

    - ``classes[cls][attr] -> kind`` for ``self.attr = threading.Lock()``
    - ``module[name] -> kind``       for module-level ``name = Lock()``
    """

    def __init__(self):
        self.classes: Dict[str, Dict[str, str]] = {}
        self.module: Dict[str, str] = {}


def attr_kinds(project: Project) -> Dict[str, AttrKinds]:
    """file rel -> AttrKinds, built once for the whole tree."""

    def build(p: Project):
        out: Dict[str, AttrKinds] = {}
        for sf in p.files:
            ak = AttrKinds()
            for node in sf.tree.body:
                if isinstance(node, ast.Assign):
                    kind = _ctor_kind(node.value)
                    if kind:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                ak.module[t.id] = kind
                elif isinstance(node, ast.ClassDef):
                    attrs: Dict[str, str] = {}
                    for sub in ast.walk(node):
                        if not isinstance(sub, ast.Assign):
                            continue
                        kind = _ctor_kind(sub.value)
                        if not kind:
                            continue
                        for t in sub.targets:
                            if (
                                isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                            ):
                                attrs[t.attr] = kind
                    if attrs:
                        ak.classes[node.name] = attrs
            out[sf.rel] = ak
        return out

    return project.symbol("attr_kinds", build)
