"""Hand-written SQL lexer + recursive-descent parser.

Replaces the reference's generated ANTLR4 parser (presto-parser/src/main/
antlr4/.../SqlBase.g4 + SqlParser.java). A recursive-descent parser keeps
the whole grammar in one readable file and error messages precise; the
grammar covers the analytic SELECT dialect (precedence follows SqlBase.g4's
expression hierarchy: OR < AND < NOT < predicate < additive <
multiplicative < unary < primary).
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple

from . import tree as t

# ---------------------------------------------------------------------------
# lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*\n?|/\*.*?\*/)
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><=|>=|<>|!=|\|\||->|[=<>+\-*/%(),.;?\[\]])
    """,
    re.VERBOSE | re.DOTALL,
)

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "distinct", "all", "as", "and", "or", "not", "in", "exists", "between",
    "like", "escape", "is", "null", "true", "false", "case", "when", "then",
    "else", "end", "cast", "try_cast", "extract", "date", "timestamp",
    "interval", "join", "inner", "left", "right", "full", "outer", "cross",
    "on", "using", "with", "union", "intersect", "except", "asc", "desc",
    "nulls", "first", "last", "over", "partition", "rows", "range",
    "unbounded", "preceding", "following", "current", "row", "filter",
    "explain", "analyze", "show", "tables", "columns", "substring", "for",
    "create", "drop", "insert", "into", "delete", "values", "table",
    "start", "transaction", "begin", "commit", "rollback", "work",
}


class Token:
    __slots__ = ("kind", "text", "pos")

    def __init__(self, kind: str, text: str, pos: int):
        self.kind = kind  # 'number' | 'string' | 'ident' | 'kw' | op text
        self.text = text
        self.pos = pos

    def __repr__(self):
        return f"Token({self.kind!r}, {self.text!r})"


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i = 0
    n = len(sql)
    while i < n:
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise SqlParseError(f"unexpected character {sql[i]!r}", sql, i)
        i = m.end()
        if m.lastgroup == "ws":
            continue
        text = m.group()
        if m.lastgroup == "number":
            out.append(Token("number", text, m.start()))
        elif m.lastgroup == "string":
            out.append(Token("string", text[1:-1].replace("''", "'"), m.start()))
        elif m.lastgroup == "qident":
            out.append(Token("ident", text[1:-1].replace('""', '"'), m.start()))
        elif m.lastgroup == "ident":
            low = text.lower()
            out.append(Token("kw" if low in KEYWORDS else "ident", low if low in KEYWORDS else text, m.start()))
        else:
            out.append(Token(text, text, m.start()))
    out.append(Token("eof", "", n))
    return out


class SqlParseError(ValueError):
    def __init__(self, message: str, sql: str, pos: int):
        line = sql.count("\n", 0, pos) + 1
        col = pos - (sql.rfind("\n", 0, pos) + 1) + 1
        super().__init__(f"{message} at line {line}:{col}")
        self.pos = pos


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.tokens = tokenize(sql)
        self.i = 0
        self._param_count = 0  # `?` markers, indexed left-to-right

    # -- token helpers --
    @property
    def tok(self) -> Token:
        return self.tokens[self.i]

    def peek(self, k: int = 1) -> Token:
        return self.tokens[min(self.i + k, len(self.tokens) - 1)]

    def error(self, msg: str):
        raise SqlParseError(f"{msg} (got {self.tok.text or 'end of input'!r})", self.sql, self.tok.pos)

    def at_kw(self, *kws: str) -> bool:
        return self.tok.kind == "kw" and self.tok.text in kws

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.i += 1
            return True
        return False

    def expect_kw(self, kw: str):
        if not self.accept_kw(kw):
            self.error(f"expected {kw.upper()}")

    def accept(self, op: str) -> bool:
        if self.tok.kind == op:
            self.i += 1
            return True
        return False

    def expect(self, op: str):
        if not self.accept(op):
            self.error(f"expected {op!r}")

    def ident(self) -> str:
        if self.tok.kind == "ident":
            s = self.tok.text
            self.i += 1
            return s
        # permissive: non-reserved keywords usable as identifiers
        if self.tok.kind == "kw" and self.tok.text in _NONRESERVED:
            s = self.tok.text
            self.i += 1
            return s
        self.error("expected identifier")

    # -- entry --
    def parse_statement(self) -> t.Node:
        if self.accept_kw("explain"):
            analyze = self.accept_kw("analyze")
            etype = "logical"
            if not analyze and self.accept("("):
                # EXPLAIN (TYPE LOGICAL|DISTRIBUTED|VALIDATE|IO
                #          [, FORMAT TEXT]) — reference SqlBase.g4 explain
                while True:
                    if self.accept_word("type"):
                        etype = self.tok.text.lower()
                        if etype not in (
                            "logical", "distributed", "validate", "io"
                        ):
                            self.error(
                                "expected LOGICAL, DISTRIBUTED, VALIDATE "
                                "or IO"
                            )
                        self.i += 1
                    elif self.accept_word("format"):
                        if not self.accept_word("text"):
                            self.error("only FORMAT TEXT is supported")
                    else:
                        self.error("expected TYPE or FORMAT")
                    if not self.accept(","):
                        break
                self.expect(")")
            q = self.parse_query()
            self.finish()
            return t.Explain(q, analyze, etype)
        if self.accept_kw("show"):
            if self.accept_kw("tables"):
                like = self._accept_like_pattern()
                self.finish()
                return t.ShowTables(like)
            if self.accept_kw("columns"):
                self.expect_kw("from")
                name = self.ident()
                self.finish()
                return t.ShowColumns(name)
            if self.accept_word("schemas"):
                like = self._accept_like_pattern()
                self.finish()
                return t.ShowSchemas(like)
            if self.accept_word("session"):
                self.finish()
                return t.ShowSession()
            if self.accept_word("functions"):
                like = self._accept_like_pattern()
                self.finish()
                return t.ShowFunctions(like)
            if self.accept_word("catalogs"):
                self.finish()
                return t.ShowCatalogs()
            if self.accept_word("grants"):
                name = None
                if self.accept_kw("on"):
                    self.accept_kw("table")
                    name = self.ident()
                self.finish()
                return t.ShowGrants(name)
            if self.accept_word("stats"):
                self.expect_kw("for")
                name = self.ident()
                self.finish()
                return t.ShowStats(name)
            if self.accept_kw("create"):
                if self.accept_word("view"):
                    name = self.ident()
                    self.finish()
                    return t.ShowCreateView(name)
                self.expect_kw("table")
                name = self.ident()
                self.finish()
                return t.ShowCreateTable(name)
            self.error(
                "expected TABLES, COLUMNS, SCHEMAS, SESSION, FUNCTIONS, "
                "CATALOGS, STATS FOR or CREATE TABLE/VIEW"
            )
        if self.accept_kw("begin") or (
            self.accept_kw("start") and self.expect_kw("transaction") is None
        ):
            self.accept_kw("work") or self.accept_kw("transaction")
            self.finish()
            return t.StartTransaction()
        if self.accept_kw("commit"):
            self.accept_kw("work")
            self.finish()
            return t.Commit()
        if self.accept_kw("rollback"):
            self.accept_kw("work")
            self.finish()
            return t.Rollback()
        if self.accept_kw("create"):
            stmt = self.parse_create()
            self.finish()
            return stmt
        if self.accept_kw("drop"):
            if self.accept_word("materialized"):
                self.expect_word("view")
                if_exists = self._accept_if_exists()
                name = self.ident()
                self.finish()
                return t.DropMaterializedView(name, if_exists)
            if self.accept_word("view"):
                if_exists = self._accept_if_exists()
                name = self.ident()
                self.finish()
                return t.DropView(name, if_exists)
            if self.accept_word("schema"):
                if_exists = self._accept_if_exists()
                name = self.ident()
                self.finish()
                return t.DropSchema(name, if_exists)
            self.expect_kw("table")
            if_exists = self._accept_if_exists()
            name = self.ident()
            self.finish()
            return t.DropTable(name, if_exists)
        if self.at_word("refresh"):
            self.i += 1
            self.expect_word("materialized")
            self.expect_word("view")
            name = self.ident()
            full = self.accept_word("full")
            self.finish()
            return t.RefreshMaterializedView(name, full)
        if self.at_word("alter"):
            self.i += 1
            self.expect_kw("table")
            name = self.ident()
            stmt = self.parse_alter_table_tail(name)
            self.finish()
            return stmt
        if self.at_word("prepare"):
            self.i += 1
            name = self.ident()
            self.expect_kw("from")
            body = self._rest_of_statement()
            self.finish()
            return t.Prepare(name, body)
        if self.at_word("execute") and self.peek().kind == "ident":
            self.i += 1
            name = self.ident()
            params: Tuple[t.Node, ...] = ()
            if self.accept_kw("using"):
                ps = [self.parse_expr()]
                while self.accept(","):
                    ps.append(self.parse_expr())
                params = tuple(ps)
            self.finish()
            return t.ExecutePrepared(name, params)
        if self.at_word("deallocate"):
            self.i += 1
            self.expect_word("prepare")
            name = self.ident()
            self.finish()
            return t.Deallocate(name)
        if self.at_word("describe") or self.at_word("desc"):
            self.i += 1
            if self.accept_word("input"):
                name = self.ident()
                self.finish()
                return t.DescribeInput(name)
            if self.accept_word("output"):
                name = self.ident()
                self.finish()
                return t.DescribeOutput(name)
            # DESCRIBE <table> = SHOW COLUMNS (reference SqlParser maps
            # describe to ShowColumns)
            name = self.ident()
            self.finish()
            return t.ShowColumns(name)
        if self.at_word("use"):
            self.i += 1
            a = self.ident()
            b = self.ident() if self.accept(".") else None
            self.finish()
            return t.Use(a if b is not None else None, b if b is not None else a)
        if self.at_word("analyze"):
            self.i += 1
            name = self.ident()
            self.finish()
            return t.Analyze(name)
        if self.at_word("set") and self.peek().text.lower() == "session":
            self.i += 2
            name = self.ident()
            while self.accept("."):
                name += "." + self.ident()
            self.expect("=")
            value = self.parse_expr()
            self.finish()
            return t.SetSession(name, value)
        if self.at_word("reset") and self.peek().text.lower() == "session":
            self.i += 2
            name = self.ident()
            while self.accept("."):
                name += "." + self.ident()
            self.finish()
            return t.ResetSession(name)
        if self.at_word("grant") or self.at_word("revoke"):
            is_grant = self.at_word("grant")
            self.i += 1
            priv = self.tok.text.lower()
            self.i += 1
            if priv == "all":
                self.accept_word("privileges")
            self.expect_kw("on")
            self.accept_kw("table")
            table = self.ident()
            if is_grant:
                self.expect_word("to")
            else:
                self.expect_kw("from")
            grantee = self.ident()
            self.finish()
            return (
                t.Grant(priv, table, grantee)
                if is_grant
                else t.Revoke(priv, table, grantee)
            )
        if self.accept_kw("insert"):
            self.expect_kw("into")
            name = self.ident()
            cols: Tuple[str, ...] = ()
            if self.tok.kind == "(":
                self.expect("(")
                cs = [self.ident()]
                while self.accept(","):
                    cs.append(self.ident())
                self.expect(")")
                cols = tuple(cs)
            q = self.parse_query()
            self.finish()
            return t.Insert(name, cols, q)
        if self.accept_kw("delete"):
            self.expect_kw("from")
            name = self.ident()
            where = self.parse_expr() if self.accept_kw("where") else None
            self.finish()
            return t.Delete(name, where)
        q = self.parse_query()
        self.finish()
        return q

    def _accept_like_pattern(self):
        """Optional LIKE 'pattern' tail on SHOW statements (reference
        SqlBase.g4 showTables/showSchemas/showFunctions)."""
        if self.accept_kw("like") or self.accept_word("like"):
            tk = self.tok
            if tk.kind != "string":
                self.error("expected a string pattern after LIKE")
            self.i += 1
            return tk.text
        return None

    def _accept_if_exists(self) -> bool:
        # IF is contextual (not a keyword) so that if(c, a, b) stays callable
        if self.tok.kind == "ident" and self.tok.text.lower() == "if":
            self.i += 1
            self.expect_kw("exists")
            return True
        return False

    def _accept_if_not_exists(self) -> bool:
        if self.tok.kind == "ident" and self.tok.text.lower() == "if":
            self.i += 1
            self.expect_kw("not")
            self.expect_kw("exists")
            return True
        return False

    def accept_word(self, w: str) -> bool:
        """Accept a CONTEXTUAL keyword: matches whether the tokenizer
        classified it as kw or ident (statement heads like VIEW/PREPARE/
        ALTER stay usable as identifiers elsewhere)."""
        tk = self.tok
        if (tk.kind == "kw" and tk.text == w) or (
            tk.kind == "ident" and tk.text.lower() == w
        ):
            self.i += 1
            return True
        return False

    def at_word(self, w: str) -> bool:
        tk = self.tok
        return (tk.kind == "kw" and tk.text == w) or (
            tk.kind == "ident" and tk.text.lower() == w
        )

    def expect_word(self, w: str):
        if not self.accept_word(w):
            self.error(f"expected {w.upper()}")

    def _rest_of_statement(self) -> str:
        """Raw SQL text from the current token to end of input (PREPARE
        body) — re-parsed at EXECUTE time with parameters bound."""
        text = self.sql[self.tok.pos:].rstrip().rstrip(";")
        self.i = len(self.tokens) - 1  # jump to eof
        return text

    def parse_alter_table_tail(self, name: str) -> t.Node:
        if self.accept_word("rename"):
            if self.accept_word("to"):
                return t.RenameTable(name, self.ident())
            self.expect_word("column")
            old = self.ident()
            self.expect_word("to")
            return t.RenameColumn(name, old, self.ident())
        if self.accept_word("add"):
            self.expect_word("column")
            cname = self.ident()
            ctype = self.parse_type_name()
            return t.AddColumn(name, t.ColumnDefinition(cname, ctype))
        if self.accept_kw("drop"):
            self.expect_word("column")
            return t.DropColumn(name, self.ident())
        self.error("expected RENAME, ADD COLUMN or DROP COLUMN")

    def parse_create(self) -> t.Node:
        if self.accept_kw("or"):
            self.expect_word("replace")
            self.expect_word("view")
            name = self.ident()
            self.expect_kw("as")
            body = self._rest_of_statement()
            return t.CreateView(name, body, or_replace=True)
        if self.accept_word("view"):
            name = self.ident()
            self.expect_kw("as")
            body = self._rest_of_statement()
            return t.CreateView(name, body, or_replace=False)
        if self.accept_word("materialized"):
            self.expect_word("view")
            if_not_exists = self._accept_if_not_exists()
            name = self.ident()
            self.expect_kw("as")
            body = self._rest_of_statement()
            return t.CreateMaterializedView(name, body, if_not_exists)
        if self.accept_word("schema"):
            if_not_exists = self._accept_if_not_exists()
            return t.CreateSchema(self.ident(), if_not_exists)
        self.expect_kw("table")
        if_not_exists = self._accept_if_not_exists()
        name = self.ident()
        if self.accept_kw("as"):
            q = self.parse_query()
            return t.CreateTable(name, (), q, if_not_exists)
        self.expect("(")
        cols = []
        while True:
            cname = self.ident()
            ctype = self.parse_type_name()
            cols.append(t.ColumnDefinition(cname, ctype))
            if not self.accept(","):
                break
        self.expect(")")
        if self.accept_kw("as"):
            self.error("column list and AS query are mutually exclusive")
        return t.CreateTable(name, tuple(cols), None, if_not_exists)

    def finish(self):
        self.accept(";")
        if self.tok.kind != "eof":
            self.error("unexpected trailing input")

    # -- query --
    def parse_query(self) -> t.Query:
        with_items: Tuple[t.WithItem, ...] = ()
        if self.accept_kw("with"):
            items = []
            while True:
                name = self.ident()
                col_aliases: Tuple[str, ...] = ()
                if self.accept("("):
                    cols = [self.ident()]
                    while self.accept(","):
                        cols.append(self.ident())
                    self.expect(")")
                    col_aliases = tuple(cols)
                self.expect_kw("as")
                self.expect("(")
                sub = self.parse_query()
                self.expect(")")
                items.append(t.WithItem(name, sub, col_aliases))
                if not self.accept(","):
                    break
            with_items = tuple(items)

        body = self.parse_set_operation()

        order_by: Tuple[t.SortItem, ...] = ()
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by = self.parse_sort_items()
        limit = None
        if self.accept_kw("limit"):
            if self.accept_kw("all"):
                limit = None
            elif self.tok.kind == "?":
                # LIMIT ? in a prepared statement: bound to an integer at
                # EXECUTE time (the planner rejects an unbound Parameter)
                self.i += 1
                limit = t.Parameter(self._param_count)
                self._param_count += 1
            else:
                if self.tok.kind != "number":
                    self.error("expected LIMIT count")
                limit = int(self.tok.text)
                self.i += 1
        return t.Query(body, with_items, order_by, limit)

    def parse_sort_items(self) -> Tuple[t.SortItem, ...]:
        items = []
        while True:
            e = self.parse_expr()
            asc = True
            if self.accept_kw("asc"):
                asc = True
            elif self.accept_kw("desc"):
                asc = False
            nulls_first = None
            if self.accept_kw("nulls"):
                if self.accept_kw("first"):
                    nulls_first = True
                else:
                    self.expect_kw("last")
                    nulls_first = False
            items.append(t.SortItem(e, asc, nulls_first))
            if not self.accept(","):
                break
        return tuple(items)

    def parse_set_operation(self) -> t.Node:
        # INTERSECT binds tighter than UNION/EXCEPT (SqlBase.g4 set-op
        # precedence), so each operand here is a full intersect chain
        left = self.parse_intersect_chain()
        while self.at_kw("union", "except"):
            op = self.tok.text
            self.i += 1
            if self.accept_kw("all"):
                op = f"{op}_all"  # EXCEPT ALL: planner rejects clearly
            else:
                self.accept_kw("distinct")
            right = self.parse_intersect_chain()
            left = t.SetOperation(op, left, right)
        return left

    def parse_intersect_chain(self) -> t.Node:
        left = self.parse_select_or_parens()
        while self.at_kw("intersect"):
            self.i += 1
            op = "intersect"
            if self.accept_kw("all"):
                op = "intersect_all"
            else:
                self.accept_kw("distinct")
            right = self.parse_select_or_parens()
            left = t.SetOperation(op, left, right)
        return left

    def parse_select_or_parens(self) -> t.Node:
        if self.accept("("):
            inner = self.parse_query()
            self.expect(")")
            # a parenthesized query as a set-op operand: unwrap if trivial
            if not inner.with_items and not inner.order_by and inner.limit is None:
                return inner.body
            return inner
        if self.at_kw("values"):
            return self.parse_values()
        if self.at_kw("table"):
            # TABLE t = SELECT * FROM t (SqlBase.g4 TABLE queryPrimary)
            self.i += 1
            name = self.ident()
            return t.Select((t.Star(),), t.Table(name))
        return self.parse_select()

    def parse_values(self) -> t.Values:
        self.expect_kw("values")
        rows = []
        while True:
            self.expect("(")
            cells = [self.parse_expr()]
            while self.accept(","):
                cells.append(self.parse_expr())
            self.expect(")")
            rows.append(tuple(cells))
            if not self.accept(","):
                break
        return t.Values(tuple(rows))

    def parse_select(self) -> t.Select:
        self.expect_kw("select")
        distinct = False
        if self.accept_kw("distinct"):
            distinct = True
        else:
            self.accept_kw("all")
        items: List[t.Node] = []
        while True:
            items.append(self.parse_select_item())
            if not self.accept(","):
                break
        from_ = None
        if self.accept_kw("from"):
            from_ = self.parse_relation_list()
        where = self.parse_expr() if self.accept_kw("where") else None
        group_by: Tuple[t.Node, ...] = ()
        if self.accept_kw("group"):
            self.expect_kw("by")
            gs = [self.parse_group_item()]
            while self.accept(","):
                gs.append(self.parse_group_item())
            group_by = tuple(gs)
        having = self.parse_expr() if self.accept_kw("having") else None
        return t.Select(tuple(items), from_, where, group_by, having, distinct)

    def parse_select_item(self) -> t.Node:
        if self.accept("*"):
            return t.Star()
        # t.* form
        if (
            self.tok.kind == "ident"
            and self.peek().kind == "."
            and self.peek(2).kind == "*"
        ):
            q = self.ident()
            self.expect(".")
            self.expect("*")
            return t.Star(q)
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.tok.kind == "ident":
            alias = self.ident()
        return t.SelectItem(e, alias)

    # -- relations --
    def parse_group_item(self) -> t.Node:
        """One GROUP BY element: plain expression, or the grouping-set
        constructs ROLLUP(...) / CUBE(...) / GROUPING SETS ((..), ..).
        The construct names are contextual (not reserved keywords)."""
        if self.tok.kind == "ident":
            word = self.tok.text.lower()
            if word in ("rollup", "cube") and self.peek().kind == "(":
                self.i += 1
                exprs = self._parse_paren_exprs()
                if word == "cube" and len(exprs) > 12:
                    # expansion is 2^n sets — bound it here so a wide CUBE
                    # cannot DoS the parser (planner caps total sets at 64)
                    self.error("CUBE supports at most 12 columns")
                if word == "rollup":
                    sets = tuple(
                        tuple(exprs[:k]) for k in range(len(exprs), -1, -1)
                    )
                else:  # cube: all subsets, preserving expr order
                    n = len(exprs)
                    sets = tuple(
                        tuple(e for i, e in enumerate(exprs) if mask & (1 << i))
                        for mask in range((1 << n) - 1, -1, -1)
                    )
                return t.GroupingSets(sets)
            if (
                word == "grouping"
                and self.peek().kind == "ident"
                and self.peek().text.lower() == "sets"
            ):
                self.i += 2
                self.expect("(")
                sets = []
                while True:
                    if self.tok.kind == "(":
                        sets.append(tuple(self._parse_paren_exprs()))
                    else:
                        sets.append((self.parse_expr(),))
                    if not self.accept(","):
                        break
                self.expect(")")
                return t.GroupingSets(tuple(sets))
        return self.parse_expr()

    def _parse_paren_exprs(self) -> list:
        self.expect("(")
        if self.accept(")"):
            return []
        out = [self.parse_expr()]
        while self.accept(","):
            out.append(self.parse_expr())
        self.expect(")")
        return out

    def parse_relation_list(self) -> t.Node:
        rel = self.parse_join_tree()
        while self.accept(","):
            right = self.parse_join_tree()
            rel = t.Join("cross", rel, right)
        return rel

    def parse_join_tree(self) -> t.Node:
        rel = self.parse_primary_relation()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self.parse_primary_relation()
                rel = t.Join("cross", rel, right)
                continue
            kind = None
            if self.at_kw("join", "inner"):
                kind = "inner"
                self.accept_kw("inner")
                self.expect_kw("join")
            elif self.at_kw("left", "right", "full"):
                kind = self.tok.text
                self.i += 1
                self.accept_kw("outer")
                self.expect_kw("join")
            else:
                break
            right = self.parse_primary_relation()
            if self.accept_kw("on"):
                cond = self.parse_expr()
                rel = t.Join(kind, rel, right, cond)
            elif self.accept_kw("using"):
                self.expect("(")
                cols = [self.ident()]
                while self.accept(","):
                    cols.append(self.ident())
                self.expect(")")
                rel = t.Join(kind, rel, right, None, tuple(cols))
            else:
                self.error("expected ON or USING")
        return rel

    def parse_primary_relation(self) -> t.Node:
        if (
            self.tok.kind == "ident"
            and self.tok.text.lower() == "unnest"
            and self.peek().kind == "("
        ):
            self.i += 1
            exprs = tuple(self._parse_paren_exprs())
            if not exprs:
                self.error("UNNEST requires at least one argument")
            ordinality = False
            if self.at_kw("with"):
                nxt = self.peek()
                if nxt.kind == "ident" and nxt.text.lower() == "ordinality":
                    self.i += 2
                    ordinality = True
            alias, col_aliases = self._parse_alias(required=False)
            return t.Unnest(exprs, alias, col_aliases, ordinality)
        if self.accept("("):
            # subquery or parenthesized join tree
            if self.at_kw("select", "with", "values") or self.tok.kind == "(":
                sub = self.parse_query()
                self.expect(")")
                alias, col_aliases = self._parse_alias(required=True)
                return t.SubqueryRelation(sub, alias, col_aliases)
            rel = self.parse_relation_list()
            self.expect(")")
            return rel
        name = self.ident()
        while self.accept("."):  # qualified: catalog.schema.table
            name += "." + self.ident()
        rel = None
        if self.accept_word("tablesample"):
            # TABLESAMPLE binds before the alias in SqlBase.g4
            # (sampledRelation: aliasedRelation TABLESAMPLE ...), but
            # accepting it here first keeps `t TABLESAMPLE ...` and
            # `t alias TABLESAMPLE ...` both parseable
            rel = self._parse_tablesample(t.Table(name, None))
            alias, _ = self._parse_alias(required=False)
            if alias is not None:
                rel = dataclasses.replace(
                    rel, relation=t.Table(name, alias)
                )
            return rel
        alias, _ = self._parse_alias(required=False)
        rel = t.Table(name, alias)
        if self.accept_word("tablesample"):
            rel = self._parse_tablesample(rel)
        return rel

    def _parse_tablesample(self, rel):
        method = self.tok.text.lower()
        if method not in ("bernoulli", "system"):
            self.error("expected BERNOULLI or SYSTEM")
        self.i += 1
        self.expect("(")
        pct_tok = self.tok
        if pct_tok.kind != "number":
            self.error("expected a sample percentage")
        self.i += 1
        self.expect(")")
        return t.TableSample(rel, method, float(pct_tok.text))

    def _parse_alias(self, required: bool):
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.tok.kind == "ident":
            alias = self.ident()
        elif required:
            self.error("expected subquery alias")
        col_aliases: Tuple[str, ...] = ()
        if alias is not None and self.accept("("):
            cols = [self.ident()]
            while self.accept(","):
                cols.append(self.ident())
            self.expect(")")
            col_aliases = tuple(cols)
        return alias, col_aliases

    # -- expressions (precedence climbing) --
    def parse_expr(self) -> t.Node:
        return self.parse_or()

    def parse_or(self) -> t.Node:
        terms = [self.parse_and()]
        while self.accept_kw("or"):
            terms.append(self.parse_and())
        return terms[0] if len(terms) == 1 else t.LogicalOp("or", tuple(terms))

    def parse_and(self) -> t.Node:
        terms = [self.parse_not()]
        while self.accept_kw("and"):
            terms.append(self.parse_not())
        return terms[0] if len(terms) == 1 else t.LogicalOp("and", tuple(terms))

    def parse_not(self) -> t.Node:
        if self.accept_kw("not"):
            return t.NotOp(self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> t.Node:
        if self.at_kw("exists"):
            self.i += 1
            self.expect("(")
            q = self.parse_query()
            self.expect(")")
            return t.Exists(q)
        e = self.parse_additive()
        while True:
            if self.accept_kw("is"):
                negated = self.accept_kw("not")
                if self.accept_kw("distinct"):
                    # IS [NOT] DISTINCT FROM: null-safe comparison
                    # (SqlBase.g4 predicate DISTINCT FROM) — desugared:
                    # both-null -> not-distinct; one-null -> distinct;
                    # else plain <>/=
                    self.expect_kw("from")
                    other = self.parse_additive()
                    both_null = t.LogicalOp(
                        "and", (t.IsNull(e, False), t.IsNull(other, False))
                    )
                    either_null = t.LogicalOp(
                        "or", (t.IsNull(e, False), t.IsNull(other, False))
                    )
                    cmp_ = t.BinaryOp("<>" if not negated else "=", e, other)
                    e = t.Case(
                        None,
                        (
                            (
                                both_null,
                                t.BooleanLiteral(negated),
                            ),
                            (
                                either_null,
                                t.BooleanLiteral(not negated),
                            ),
                        ),
                        cmp_,
                    )
                    continue
                self.expect_kw("null")
                e = t.IsNull(e, negated)
                continue
            negated = False
            save = self.i
            if self.accept_kw("not"):
                negated = True
            if self.accept_kw("between"):
                lo = self.parse_additive()
                self.expect_kw("and")
                hi = self.parse_additive()
                e = t.Between(e, lo, hi, negated)
                continue
            if self.accept_kw("in"):
                self.expect("(")
                if self.at_kw("select", "with"):
                    q = self.parse_query()
                    self.expect(")")
                    e = t.InSubquery(e, q, negated)
                else:
                    opts = [self.parse_expr()]
                    while self.accept(","):
                        opts.append(self.parse_expr())
                    self.expect(")")
                    e = t.InList(e, tuple(opts), negated)
                continue
            if self.accept_kw("like"):
                pat = self.parse_additive()
                esc = None
                if self.accept_kw("escape"):
                    esc = self.parse_additive()
                e = t.Like(e, pat, esc, negated)
                continue
            if negated:
                self.i = save
                break
            op = None
            for cand in ("=", "<>", "!=", "<=", ">=", "<", ">"):
                if self.tok.kind == cand:
                    op = "<>" if cand == "!=" else cand
                    break
            if op is None:
                break
            self.i += 1
            if self.at_kw("all") or (
                self.tok.kind == "ident"
                and self.tok.text.lower() in ("any", "some")
            ):
                quant = "all" if self.at_kw("all") else "any"
                self.i += 1
                self.expect("(")
                if self.at_kw("select", "with"):
                    q = self.parse_query()
                elif self.at_kw("values"):
                    v = self.parse_values()
                    q = t.Query(v)
                else:
                    self.error("expected a subquery after ALL/ANY/SOME")
                self.expect(")")
                e = t.quantified_comparison(op, quant, e, q)
                continue
            # quantified comparison / subquery comparand
            if self.tok.kind == "(" and self.peek().kind == "kw" and self.peek().text in ("select", "with"):
                self.i += 1
                q = self.parse_query()
                self.expect(")")
                right: t.Node = t.ScalarSubquery(q)
            else:
                right = self.parse_additive()
            e = t.BinaryOp(op, e, right)
        return e

    def parse_additive(self) -> t.Node:
        e = self.parse_multiplicative()
        while True:
            if self.tok.kind in ("+", "-", "||"):
                op = self.tok.kind
                self.i += 1
                e = t.BinaryOp(op, e, self.parse_multiplicative())
            else:
                return e

    def parse_multiplicative(self) -> t.Node:
        e = self.parse_unary()
        while True:
            if self.tok.kind in ("*", "/", "%"):
                op = self.tok.kind
                self.i += 1
                e = t.BinaryOp(op, e, self.parse_unary())
            else:
                return e

    def parse_unary(self) -> t.Node:
        if self.tok.kind == "-":
            self.i += 1
            return t.UnaryOp("-", self.parse_unary())
        if self.tok.kind == "+":
            self.i += 1
            return self.parse_unary()
        node = self.parse_primary()
        # postfix subscript: a[i] is 1-based element access, sugar for
        # element_at (SqlBase.g4 subscript -> SubscriptExpression)
        while self.tok.kind == "[":
            self.i += 1
            idx = self.parse_expr()
            self.expect("]")
            node = t.FunctionCall("element_at", (node, idx))
        return node

    def parse_primary(self) -> t.Node:
        tok = self.tok
        if tok.kind == "?":
            self.i += 1
            idx = self._param_count
            self._param_count += 1
            return t.Parameter(idx)
        if (
            tok.kind == "ident"
            and tok.text.lower() == "array"
            and self.peek().kind == "["
        ):
            self.i += 2
            items = []
            if self.tok.kind != "]":
                items.append(self.parse_expr())
                while self.accept(","):
                    items.append(self.parse_expr())
            self.expect("]")
            return t.ArrayLiteral(tuple(items))
        if tok.kind == "number":
            self.i += 1
            return t.NumberLiteral(tok.text)
        if tok.kind == "string":
            self.i += 1
            return t.StringLiteral(tok.text)
        if self.at_kw("null"):
            self.i += 1
            return t.NullLiteral()
        if self.at_kw("true"):
            self.i += 1
            return t.BooleanLiteral(True)
        if self.at_kw("false"):
            self.i += 1
            return t.BooleanLiteral(False)
        if self.at_kw("date"):
            if self.peek().kind == "string":
                self.i += 1
                s = self.tok.text
                self.i += 1
                return t.DateLiteral(s)
        if self.at_kw("timestamp"):
            if self.peek().kind == "string":
                self.i += 1
                s = self.tok.text
                self.i += 1
                return t.TimestampLiteral(s)
        if self.at_kw("interval"):
            self.i += 1
            negative = False
            if self.tok.kind == "-":
                negative = True
                self.i += 1
            if self.tok.kind != "string":
                self.error("expected interval literal string")
            value = self.tok.text
            self.i += 1
            unit = self.ident().lower()
            unit = unit.rstrip("s") if unit.endswith("s") else unit
            return t.IntervalLiteral(value, unit, negative)
        if self.at_kw("case"):
            return self.parse_case()
        if self.at_kw("cast", "try_cast"):
            try_cast = self.tok.text == "try_cast"
            self.i += 1
            self.expect("(")
            operand = self.parse_expr()
            self.expect_kw("as")
            type_name = self.parse_type_name()
            self.expect(")")
            return t.Cast(operand, type_name, try_cast)
        if self.at_kw("extract"):
            self.i += 1
            self.expect("(")
            field = self.ident().lower()
            self.expect_kw("from")
            operand = self.parse_expr()
            self.expect(")")
            return t.Extract(field, operand)
        if (
            tok.kind == "ident"
            and tok.text.lower() == "position"
            and self.peek().kind == "("
        ):
            # position(sub IN str) (SqlBase.g4 POSITION) = strpos(str, sub);
            # the plain position(str, sub) call form stays a normal call
            save = self.i
            self.i += 2
            # additive level: the IN here is the POSITION keyword form,
            # not the membership predicate
            sub = self.parse_additive()
            if self.accept_kw("in"):
                hay = self.parse_additive()
                self.expect(")")
                return t.FunctionCall("strpos", (hay, sub))
            self.i = save
        if self.at_kw("substring"):
            # substring(x FROM a [FOR b]) or substring(x, a, b)
            self.i += 1
            self.expect("(")
            val = self.parse_expr()
            if self.accept_kw("from"):
                start = self.parse_expr()
                args = [val, start]
                if self.accept_kw("for"):
                    args.append(self.parse_expr())
            else:
                args = [val]
                while self.accept(","):
                    args.append(self.parse_expr())
            self.expect(")")
            return t.FunctionCall("substr", tuple(args))
        if tok.kind == "(":
            self.i += 1
            if self.at_kw("select", "with"):
                q = self.parse_query()
                self.expect(")")
                return t.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect(")")
            return e
        if tok.kind == "ident" or (tok.kind == "kw" and tok.text in _NONRESERVED):
            # function call?
            if self.peek().kind == "(":
                name = self.ident().lower()
                self.i += 1  # '('
                return self.parse_call_tail(name)
            # qualified identifier
            parts = [self.ident()]
            while self.tok.kind == "." :
                self.i += 1
                parts.append(self.ident())
            return t.Identifier(tuple(parts))
        self.error("expected expression")

    def parse_call_tail(self, name: str) -> t.Node:
        distinct = False
        is_star = False
        args: List[t.Node] = []
        if self.accept("*"):
            is_star = True
        elif not self.accept(")"):
            if self.accept_kw("distinct"):
                distinct = True
            else:
                self.accept_kw("all")
            args.append(self._parse_arg())
            while self.accept(","):
                args.append(self._parse_arg())
            order_by = ()
            if self.accept_kw("order"):
                # agg(x ORDER BY k ...) (SqlBase.g4 aggregation orderBy)
                self.expect_kw("by")
                order_by = self.parse_sort_items()
            self.expect(")")
            return self._call_suffix(
                name, args, distinct, is_star, order_by
            )
        else:
            return self._call_suffix(name, args, distinct, is_star)
        self.expect(")")
        return self._call_suffix(name, args, distinct, is_star)

    def _parse_arg(self) -> t.Node:
        """Function argument: lambda `x -> e` / `(x, y) -> e`, or a
        plain expression."""
        if self.tok.kind == "ident" and self.peek().kind == "->":
            param = self.ident()
            self.expect("->")
            return t.LambdaExpr((param,), self.parse_expr())
        if self.tok.kind == "(":
            # lookahead for "(p [, p...]) ->"
            j = self.i + 1
            params = []
            ok = False
            while self.tokens[j].kind == "ident":
                params.append(self.tokens[j].text)
                j += 1
                if self.tokens[j].kind == ",":
                    j += 1
                    continue
                if self.tokens[j].kind == ")" and self.tokens[j + 1].kind == "->":
                    ok = True
                break
            if ok and params:
                self.i = j + 2  # past ') ->'
                return t.LambdaExpr(tuple(params), self.parse_expr())
        return self.parse_expr()

    def _call_suffix(self, name, args, distinct, is_star,
                     order_by=()) -> t.Node:
        filt = None
        if self.accept_kw("filter"):
            self.expect("(")
            self.expect_kw("where")
            filt = self.parse_expr()
            self.expect(")")
        window = None
        if self.accept_kw("over"):
            window = self.parse_window_spec()
        return t.FunctionCall(
            name, tuple(args), distinct, is_star, window, filt,
            tuple(order_by),
        )

    def parse_window_spec(self) -> t.WindowSpec:
        self.expect("(")
        partition: Tuple[t.Node, ...] = ()
        order: Tuple[t.SortItem, ...] = ()
        frame = None
        if self.accept_kw("partition"):
            self.expect_kw("by")
            ps = [self.parse_expr()]
            while self.accept(","):
                ps.append(self.parse_expr())
            partition = tuple(ps)
        if self.accept_kw("order"):
            self.expect_kw("by")
            order = self.parse_sort_items()
        if self.at_kw("rows", "range"):
            ftype = self.tok.text
            self.i += 1
            if self.accept_kw("between"):
                start = self.parse_frame_bound()
                self.expect_kw("and")
                end = self.parse_frame_bound()
            else:
                start = self.parse_frame_bound()
                end = "current row"
            frame = (ftype, start, end)
        self.expect(")")
        return t.WindowSpec(partition, order, frame)

    def parse_frame_bound(self) -> str:
        if self.accept_kw("unbounded"):
            if self.accept_kw("preceding"):
                return "unbounded preceding"
            self.expect_kw("following")
            return "unbounded following"
        if self.accept_kw("current"):
            self.expect_kw("row")
            return "current row"
        if self.tok.kind == "number":
            n = self.tok.text
            self.i += 1
            if self.accept_kw("preceding"):
                return f"{n} preceding"
            self.expect_kw("following")
            return f"{n} following"
        self.error("expected frame bound")

    def parse_case(self) -> t.Node:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        whens = []
        while self.accept_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            val = self.parse_expr()
            whens.append((cond, val))
        else_ = None
        if self.accept_kw("else"):
            else_ = self.parse_expr()
        self.expect_kw("end")
        return t.Case(operand, tuple(whens), else_)

    def parse_type_name(self) -> str:
        parts = [self.ident() if self.tok.kind == "ident" else self._kw_as_type()]
        # double precision
        if parts[0].lower() == "double" and self.tok.kind == "ident" and self.tok.text.lower() == "precision":
            self.i += 1
        if self.accept("("):
            nums = [self.tok.text]
            self.i += 1
            while self.accept(","):
                nums.append(self.tok.text)
                self.i += 1
            self.expect(")")
            return f"{parts[0]}({','.join(nums)})"
        return parts[0]

    def _kw_as_type(self) -> str:
        if self.tok.kind == "kw" and self.tok.text in ("date", "timestamp", "interval"):
            s = self.tok.text
            self.i += 1
            return s
        self.error("expected type name")


# keywords usable as plain identifiers (column/table names)
_NONRESERVED = {
    "date", "timestamp", "interval", "year", "month", "day", "hour", "minute",
    "second", "quarter", "first", "last", "tables", "columns", "show", "row",
    "range", "rows", "filter", "analyze", "substring",
    "start", "transaction", "begin", "commit", "rollback", "work",
}


def parse(sql: str) -> t.Node:
    """Parse one SQL statement into an AST."""
    return Parser(sql).parse_statement()
