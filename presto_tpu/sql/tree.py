"""SQL AST nodes.

Compact equivalent of the reference's ~170 classes under
presto-parser/src/main/java/com/facebook/presto/sql/tree/ — one frozen
dataclass per construct, only the analytic-SELECT surface. Every node is
hashable so analysis results can be keyed on nodes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Node:
    pass


# -- expressions ------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Identifier(Node):
    """Column reference, possibly qualified: parts = ('t', 'c') or ('c',)."""

    parts: Tuple[str, ...]

    @property
    def name(self) -> str:
        return self.parts[-1]


@dataclasses.dataclass(frozen=True)
class NumberLiteral(Node):
    text: str  # original text; analyzer decides integer/decimal/double


@dataclasses.dataclass(frozen=True)
class StringLiteral(Node):
    value: str


@dataclasses.dataclass(frozen=True)
class BooleanLiteral(Node):
    value: bool


@dataclasses.dataclass(frozen=True)
class NullLiteral(Node):
    pass


@dataclasses.dataclass(frozen=True)
class DateLiteral(Node):
    value: str  # 'YYYY-MM-DD'


@dataclasses.dataclass(frozen=True)
class TimestampLiteral(Node):
    value: str


@dataclasses.dataclass(frozen=True)
class IntervalLiteral(Node):
    value: str  # e.g. '3'
    unit: str  # day | month | year | hour | minute | second
    negative: bool = False


@dataclasses.dataclass(frozen=True)
class UnaryOp(Node):
    op: str  # '-' | '+'
    operand: Node


@dataclasses.dataclass(frozen=True)
class BinaryOp(Node):
    op: str  # + - * / % || and comparisons = <> < <= > >=
    left: Node
    right: Node


@dataclasses.dataclass(frozen=True)
class LogicalOp(Node):
    op: str  # and | or
    terms: Tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class NotOp(Node):
    operand: Node


@dataclasses.dataclass(frozen=True)
class IsNull(Node):
    operand: Node
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Between(Node):
    value: Node
    low: Node
    high: Node
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InList(Node):
    value: Node
    options: Tuple[Node, ...]
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InSubquery(Node):
    value: Node
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Exists(Node):
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class ScalarSubquery(Node):
    query: "Query"


def quantified_comparison(op: str, quantifier: str, value: Node,
                          query: "Query") -> Node:
    """x op ALL|ANY|SOME (subquery) desugared at parse time (reference
    quantifiedComparison + the TransformQuantifiedComparison rewrite):
    = ANY is IN, <> ALL is NOT IN; ordering comparisons reduce onto
    min/max/count aggregates of the subquery, with empty-set and NULL
    semantics expressed as a searched CASE."""
    if op == "=" and quantifier == "any":
        return InSubquery(value, query, False)
    if op == "<>" and quantifier == "all":
        return InSubquery(value, query, True)
    if op not in ("<", "<=", ">", ">="):
        raise ValueError(f"quantified {op} {quantifier.upper()} unsupported")
    rel = SubqueryRelation(query, "$qc", ("v",))
    v = Identifier(("v",))

    def agg(fn, star=False):
        sel = Select(
            (SelectItem(FunctionCall(fn, () if star else (v,), is_star=star)),),
            rel,
        )
        return ScalarSubquery(Query(sel))

    if quantifier == "all":
        bound = agg("max" if op in (">", ">=") else "min")
    else:
        bound = agg("min" if op in (">", ">=") else "max")
    cnt_all = agg("count", star=True)
    cnt_val = agg("count")
    zero = NumberLiteral("0")
    cmp_bound = BinaryOp(op, value, bound)
    has_null = BinaryOp("<>", cnt_all, cnt_val)
    if quantifier == "all":
        return Case(
            None,
            (
                (BinaryOp("=", cnt_all, zero), BooleanLiteral(True)),
                (IsNull(value, False), NullLiteral()),
                (NotOp(cmp_bound), BooleanLiteral(False)),
                (has_null, NullLiteral()),
            ),
            BooleanLiteral(True),
        )
    return Case(
        None,
        (
            (BinaryOp("=", cnt_all, zero), BooleanLiteral(False)),
            (IsNull(value, False), NullLiteral()),
            (cmp_bound, BooleanLiteral(True)),
            (has_null, NullLiteral()),
        ),
        BooleanLiteral(False),
    )


@dataclasses.dataclass(frozen=True)
class Like(Node):
    value: Node
    pattern: Node
    escape: Optional[Node] = None
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class LambdaExpr(Node):
    """`x -> body` / `(x, y) -> body` — argument to higher-order
    functions (reference sql/tree/LambdaExpression.java)."""

    params: Tuple[str, ...]
    body: Node


@dataclasses.dataclass(frozen=True)
class FunctionCall(Node):
    name: str  # lowercase
    args: Tuple[Node, ...]
    distinct: bool = False
    is_star: bool = False  # count(*)
    window: Optional["WindowSpec"] = None
    filter: Optional[Node] = None
    order_by: Tuple["SortItem", ...] = ()  # agg(x ORDER BY ...)


@dataclasses.dataclass(frozen=True)
class WindowSpec(Node):
    partition_by: Tuple[Node, ...] = ()
    order_by: Tuple["SortItem", ...] = ()
    frame: Optional[Tuple[str, str, str]] = None  # (type, start, end)


@dataclasses.dataclass(frozen=True)
class Case(Node):
    operand: Optional[Node]  # simple CASE operand or None for searched
    whens: Tuple[Tuple[Node, Node], ...]
    else_: Optional[Node]


@dataclasses.dataclass(frozen=True)
class Cast(Node):
    operand: Node
    type_name: str
    try_cast: bool = False


@dataclasses.dataclass(frozen=True)
class Extract(Node):
    field: str  # year | quarter | month | day | ...
    operand: Node


@dataclasses.dataclass(frozen=True)
class Star(Node):
    qualifier: Optional[str] = None  # t.* has qualifier 't'


# -- relations --------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Table(Node):
    name: str
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class TableSample(Node):
    """relation TABLESAMPLE BERNOULLI|SYSTEM (percentage)."""

    relation: Node
    method: str  # bernoulli | system
    percentage: float


@dataclasses.dataclass(frozen=True)
class SubqueryRelation(Node):
    query: "Query"
    alias: str
    column_aliases: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Join(Node):
    kind: str  # inner | left | right | full | cross
    left: Node
    right: Node
    condition: Optional[Node] = None  # ON expr
    using: Tuple[str, ...] = ()


# -- query structure --------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SelectItem(Node):
    expr: Node
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class SortItem(Node):
    expr: Node
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclasses.dataclass(frozen=True)
class Select(Node):
    items: Tuple[Node, ...]  # SelectItem | Star
    from_: Optional[Node]  # relation tree or None (SELECT 1)
    where: Optional[Node] = None
    group_by: Tuple[Node, ...] = ()
    having: Optional[Node] = None
    distinct: bool = False


@dataclasses.dataclass(frozen=True)
class WithItem(Node):
    name: str
    query: "Query"
    column_aliases: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Query(Node):
    """Full query: [WITH ...] body [ORDER BY ...] [LIMIT n]."""

    body: Node  # Select | SetOperation
    with_items: Tuple[WithItem, ...] = ()
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class SetOperation(Node):
    op: str  # union | union_all | intersect | except
    left: Node  # Select | SetOperation
    right: Node


# -- statements beyond SELECT ----------------------------------------------


@dataclasses.dataclass(frozen=True)
class Explain(Node):
    query: Query
    analyze: bool = False
    # reference grammar: EXPLAIN (TYPE LOGICAL|DISTRIBUTED|VALIDATE|IO)
    etype: str = "logical"


@dataclasses.dataclass(frozen=True)
class ShowTables(Node):
    like: "str | None" = None


@dataclasses.dataclass(frozen=True)
class ShowColumns(Node):
    table: str


@dataclasses.dataclass(frozen=True)
class Values(Node):
    """VALUES (r1c1, r1c2), (r2c1, r2c2) — usable as a query body or inline
    relation (reference sql/tree/Values.java)."""

    rows: Tuple[Tuple[Node, ...], ...]


@dataclasses.dataclass(frozen=True)
class ColumnDefinition(Node):
    name: str
    type_name: str


@dataclasses.dataclass(frozen=True)
class CreateTable(Node):
    """CREATE TABLE [IF NOT EXISTS] name (col type, ...) or AS <query>
    (reference sql/tree/CreateTable.java, CreateTableAsSelect.java)."""

    name: str
    columns: Tuple[ColumnDefinition, ...] = ()
    query: Optional[Query] = None
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class StartTransaction(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Commit(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Rollback(Node):
    pass


@dataclasses.dataclass(frozen=True)
class DropTable(Node):
    name: str
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class Insert(Node):
    """INSERT INTO name [(cols)] <query|VALUES> (reference sql/tree/Insert.java)."""

    table: str
    columns: Tuple[str, ...]  # () = positional, all table columns
    query: Node = None  # Query or Values


@dataclasses.dataclass(frozen=True)
class Delete(Node):
    """DELETE FROM name [WHERE p] (reference sql/tree/Delete.java)."""

    table: str
    where: Optional[Node] = None


@dataclasses.dataclass(frozen=True)
class GroupingSets(Node):
    """GROUP BY GROUPING SETS / ROLLUP / CUBE, normalized to explicit sets
    (reference sql/tree/GroupingSets.java, Rollup.java, Cube.java)."""

    sets: Tuple[Tuple[Node, ...], ...]


@dataclasses.dataclass(frozen=True)
class ArrayLiteral(Node):
    """ARRAY[e1, e2, ...] (reference sql/tree/ArrayConstructor.java)."""

    items: Tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class Unnest(Node):
    """UNNEST(a1, ...) [WITH ORDINALITY] [alias(cols)] relation
    (reference sql/tree/Unnest.java; multiple arrays zip by position)."""

    exprs: Tuple[Node, ...]
    alias: Optional[str] = None
    column_aliases: Tuple[str, ...] = ()
    ordinality: bool = False


# -- views / schemas / prepared statements / session / DDL breadth ---------
# (reference presto-main/.../execution/*Task.java: CreateViewTask,
# PrepareTask, DeallocateTask, SetSessionTask, ResetSessionTask,
# RenameTableTask, RenameColumnTask, AddColumnTask, DropColumnTask,
# GrantTask, RevokeTask, CreateSchemaTask, DropSchemaTask)


@dataclasses.dataclass(frozen=True)
class Parameter(Node):
    """A `?` placeholder; index assigned left-to-right from 0."""

    index: int


@dataclasses.dataclass(frozen=True)
class BoundParameter(Node):
    """A parameter bound to a literal AST for plan-skeleton caching
    (exec/qcache.py): the planner plans `inner` and tags the resulting
    ir.Literal with `index` so new EXECUTE values rebind the cached plan
    without re-planning."""

    index: int
    inner: Node


@dataclasses.dataclass(frozen=True)
class CreateView(Node):
    name: str
    query_sql: str  # original text of the view query
    or_replace: bool


@dataclasses.dataclass(frozen=True)
class DropView(Node):
    name: str
    if_exists: bool


@dataclasses.dataclass(frozen=True)
class ShowCreateView(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class CreateMaterializedView(Node):
    """CREATE MATERIALIZED VIEW [IF NOT EXISTS] name AS <query>
    (reference sql/tree/CreateMaterializedView.java)."""

    name: str
    query_sql: str  # original text of the view query
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class RefreshMaterializedView(Node):
    """REFRESH MATERIALIZED VIEW name [FULL] (reference
    sql/tree/RefreshMaterializedView.java; FULL forces recompute)."""

    name: str
    full: bool = False


@dataclasses.dataclass(frozen=True)
class DropMaterializedView(Node):
    name: str
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateSchema(Node):
    name: str
    if_not_exists: bool


@dataclasses.dataclass(frozen=True)
class DropSchema(Node):
    name: str
    if_exists: bool


@dataclasses.dataclass(frozen=True)
class ShowSchemas(Node):
    like: "str | None" = None


@dataclasses.dataclass(frozen=True)
class Prepare(Node):
    name: str
    statement_sql: str  # raw text; re-parsed (with parameters) at EXECUTE


@dataclasses.dataclass(frozen=True)
class ExecutePrepared(Node):
    name: str
    params: Tuple[Node, ...]  # literal ASTs from USING


@dataclasses.dataclass(frozen=True)
class Deallocate(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class DescribeInput(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class DescribeOutput(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class SetSession(Node):
    name: str
    value: Node  # literal


@dataclasses.dataclass(frozen=True)
class ResetSession(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class ShowSession(Node):
    pass


@dataclasses.dataclass(frozen=True)
class RenameTable(Node):
    name: str
    new_name: str


@dataclasses.dataclass(frozen=True)
class RenameColumn(Node):
    table: str
    name: str
    new_name: str


@dataclasses.dataclass(frozen=True)
class AddColumn(Node):
    table: str
    column: "ColumnDefinition"


@dataclasses.dataclass(frozen=True)
class DropColumn(Node):
    table: str
    name: str


@dataclasses.dataclass(frozen=True)
class Grant(Node):
    privilege: str  # select | all | ...
    table: str
    grantee: str


@dataclasses.dataclass(frozen=True)
class Revoke(Node):
    privilege: str
    table: str
    grantee: str


def substitute_parameters(node, params):
    """Rebuild an AST with Parameter(i) replaced by params[i] (the literal
    ASTs from EXECUTE ... USING) — reference sql/analyzer parameter
    rewriting via Analysis.getParameters."""
    if isinstance(node, Parameter):
        if node.index >= len(params):
            raise ValueError(
                f"no value supplied for parameter {node.index + 1}"
            )
        return params[node.index]
    if isinstance(node, Node):
        changes = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            nv = substitute_parameters(v, params)
            if nv is not v:
                changes[f.name] = nv
        return dataclasses.replace(node, **changes) if changes else node
    if isinstance(node, tuple):
        newt = tuple(substitute_parameters(v, params) for v in node)
        return newt if any(a is not b for a, b in zip(newt, node)) else node
    return node


def count_parameters(node) -> int:
    """Highest Parameter index + 1 anywhere in the AST."""
    if isinstance(node, Parameter):
        return node.index + 1
    n = 0
    if isinstance(node, Node):
        for f in dataclasses.fields(node):
            n = max(n, count_parameters(getattr(node, f.name)))
    elif isinstance(node, tuple):
        for v in node:
            n = max(n, count_parameters(v))
    return n


@dataclasses.dataclass(frozen=True)
class ShowFunctions(Node):
    like: "str | None" = None


@dataclasses.dataclass(frozen=True)
class ShowCatalogs(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowCreateTable(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class ShowStats(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class Use(Node):
    """USE [catalog.]schema (reference UseTask.java)."""

    catalog: "str | None"
    schema: str


@dataclasses.dataclass(frozen=True)
class Analyze(Node):
    """ANALYZE table (reference AnalyzeTask: collect table statistics)."""

    table: str


@dataclasses.dataclass(frozen=True)
class ShowGrants(Node):
    """SHOW GRANTS [ON [TABLE] t] (reference ShowQueriesRewrite over
    information_schema.table_privileges)."""

    table: "str | None" = None
