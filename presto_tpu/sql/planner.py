"""Analyzer + logical planner: AST -> typed PlanNode tree.

Combines the reference's Analyzer/StatementAnalyzer/ExpressionAnalyzer
(presto-main/.../sql/analyzer/) and LogicalPlanner + key optimizations
(sql/planner/LogicalPlanner.java, PlanOptimizers.java) into one pass that is
naturally "optimized-by-construction" for the common analytic shapes:

* predicate pushdown — WHERE conjuncts are classified while planning and
  single-relation filters land directly on their scan
  (reference PredicatePushDown.java)
* greedy join ordering over the equi-join graph using catalog row counts
  (reference ReorderJoins + DetermineJoinDistributionType, simplified)
* subquery decorrelation for the canonical patterns: uncorrelated scalar ->
  ScalarApply; correlated scalar aggregate -> group-by + left join
  (reference TransformCorrelatedScalarAggregationToJoin.java);
  [NOT] EXISTS / IN -> SemiJoin with optional residual
  (reference TransformExistsApplyToLateralNode + semi-join rewrites)
* count(DISTINCT x) -> count over Distinct (reference
  SingleDistinctAggregationToGroupBy.java)

Channel names (`name#K`) are globally unique per Planner — the reference's
SymbolAllocator.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from .. import types as T
from ..expr import ir
from ..expr.functions import FUNCTIONS
from ..ops.aggregate import AggSpec
from ..ops.sort import SortKey
from ..plan import nodes as N
from . import tree as t

AGG_FUNCS = {
    "count", "sum", "avg", "min", "max", "checksum", "approx_distinct",
    "min_by", "max_by", "approx_percentile",
    "array_agg", "map_agg", "histogram",
    "learn_linear_regression", "learn_regressor", "learn_classifier",
    "map_union", "multimap_agg", "numeric_histogram",
    "qdigest_agg", "approx_set", "merge",
}

# aggregates planned by rewriting onto the core set (reference: many of
# operator/aggregation/*'s 100+ functions decompose into sum/count states)
LAMBDA_FUNCS = {
    "transform", "filter", "reduce", "zip_with", "map_zip_with",
    "any_match", "all_match", "none_match",
    "map_filter", "transform_values", "transform_keys",
}

REWRITE_AGG_FUNCS = {
    "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop",
    "count_if", "bool_and", "bool_or", "every", "arbitrary",
    "geometric_mean", "covar_samp", "covar_pop", "corr",
    "skewness", "kurtosis", "regr_slope", "regr_intercept",
}

_BINOP_FN = {
    "+": "add",
    "-": "subtract",
    "*": "multiply",
    "/": "divide",
    "%": "modulus",
    "||": "concat",
    "=": "eq",
    "<>": "ne",
    "<": "lt",
    "<=": "le",
    ">": "gt",
    ">=": "ge",
}
_CMP_OPS = {"=", "<>", "<", "<=", ">", ">="}


class PlanningError(ValueError):
    pass


# ---------------------------------------------------------------------------
# scope
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FieldRef:
    qualifier: Optional[str]  # relation alias (or table name)
    name: str  # user-visible column name
    channel: str
    type: T.Type


class Scope:
    def __init__(self, fields: Sequence[FieldRef]):
        self.fields = list(fields)

    def resolve(self, parts: Tuple[str, ...]) -> Optional[FieldRef]:
        if len(parts) == 1:
            hits = [f for f in self.fields if f.name == parts[0]]
        else:
            q, name = parts[-2], parts[-1]
            hits = [
                f
                for f in self.fields
                if f.name == name and f.qualifier is not None and f.qualifier == q
            ]
        if len(hits) > 1:
            raise PlanningError(f"ambiguous column {'.'.join(parts)!r}")
        return hits[0] if hits else None

    def visible(self, qualifier: Optional[str] = None) -> List[FieldRef]:
        if qualifier is None:
            return list(self.fields)
        return [f for f in self.fields if f.qualifier == qualifier]


# ---------------------------------------------------------------------------
# catalog protocol
# ---------------------------------------------------------------------------


class Catalog:
    """Connector metadata interface (reference ConnectorMetadata +
    table statistics SPI)."""

    name = "catalog"

    def table_names(self) -> List[str]:
        raise NotImplementedError

    def schema(self, table: str) -> Dict[str, T.Type]:
        raise NotImplementedError

    def row_count(self, table: str) -> int:
        raise NotImplementedError

    def unique_columns(self, table: str) -> List[Tuple[str, ...]]:
        """Column sets known unique (primary keys) — enables n:1 joins."""
        return []


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RelationPlan:
    node: N.PlanNode
    scope: Scope
    # pre-projection scope (source columns), when ORDER BY may legally
    # reference columns that are not in the select list
    pre_scope: Optional[Scope] = None


class Planner:
    def __init__(self, catalog: Catalog, views=None):
        self.catalog = catalog
        self.views = views or {}  # name -> view query SQL text
        self._view_stack: set = set()
        self._counter = itertools.count()

    def channel(self, base: str) -> str:
        return f"{base}#{next(self._counter)}"

    @staticmethod
    def _limit_count(limit) -> int:
        """Coerce a bound LIMIT parameter to its integer count."""
        if isinstance(limit, t.BoundParameter):
            limit = limit.inner
        if isinstance(limit, t.Parameter):
            raise PlanningError("LIMIT parameter is not bound")
        if isinstance(limit, t.NumberLiteral) and "." not in limit.text \
                and "e" not in limit.text.lower():
            return int(limit.text)
        raise PlanningError("LIMIT must be an integer literal")

    # -- statements --
    def plan_statement(self, ast: t.Node) -> N.PlanNode:
        if isinstance(ast, t.Query):
            rp = self.plan_query(ast, outer=None, ctes={})
            return rp.node
        if isinstance(ast, t.Explain):
            return self.plan_statement(ast.query)
        raise PlanningError(f"unsupported statement {type(ast).__name__}")

    # -- queries --
    def plan_query(
        self, q: t.Query, outer: Optional["SelectContext"], ctes: Dict[str, t.WithItem]
    ) -> RelationPlan:
        if q.with_items:
            ctes = dict(ctes)
            for item in q.with_items:
                ctes[item.name.lower()] = item

        rp = self.plan_query_body(q.body, outer, ctes)
        if q.limit is not None and not isinstance(q.limit, int):
            # LIMIT ? bound at EXECUTE time (parser stores the Parameter;
            # substitution delivers a literal AST). The count is consumed
            # HERE, at plan time — a skeleton cache (exec/qcache.py) then
            # sees the parameter index missing from the plan and correctly
            # refuses to rebind across values.
            q = dataclasses.replace(q, limit=self._limit_count(q.limit))

        node, scope = rp.node, rp.scope
        if q.order_by:
            keys = []
            hidden: List[Tuple[ir.RowExpression, str]] = []
            for si in q.order_by:
                try:
                    e = self._order_expr(si.expr, scope, outer, ctes, node)
                except PlanningError:
                    # ORDER BY repeating a select-list expression verbatim
                    # (commonly an aggregate: ORDER BY count(*)) — match
                    # structurally against the items and reuse the output
                    # channel (reference: Analyzer orders on output fields)
                    matched = self._order_item_match(q.body, si.expr, scope)
                    if matched is not None:
                        keys.append(
                            SortKey(matched, si.ascending, si.nulls_first)
                        )
                        continue
                    # ORDER BY on a column NOT in the select list: extend
                    # the projection with a hidden sort channel, drop it
                    # after sorting (reference: LogicalPlanner orders on
                    # pre-projection symbols). Ordinals stay strict.
                    if (
                        rp.pre_scope is None
                        or not isinstance(node, N.Project)
                        or isinstance(si.expr, t.NumberLiteral)
                    ):
                        raise
                    pctx = SelectContext(self, [rp.pre_scope], outer, ctes, None)
                    e_src = pctx.translate(si.expr)
                    ch = self.channel("osort")
                    hidden.append((e_src, ch))
                    e = ir.ColumnRef(ch, e_src.type)
                keys.append(SortKey(e, si.ascending, si.nulls_first))
            if hidden:
                proj: N.Project = node
                node = N.Project(
                    proj.child,
                    proj.exprs + tuple(e for e, _ in hidden),
                    proj.names + tuple(ch for _, ch in hidden),
                )
            if q.limit is not None:
                node = N.TopN(node, tuple(keys), q.limit)
            else:
                node = N.Sort(node, tuple(keys))
            if hidden:  # re-project to the visible columns only
                node = N.Project(
                    node,
                    tuple(ir.ColumnRef(f.channel, f.type) for f in scope.fields),
                    tuple(f.channel for f in scope.fields),
                )
        elif q.limit is not None:
            node = N.Limit(node, q.limit)
        return RelationPlan(node, scope)

    @staticmethod
    def _expand_group_by(group_by):
        """Expand GROUP BY items containing GROUPING SETS/ROLLUP/CUBE into
        the cross product of grouping sets (reference: the analyzer's
        computeGroupingSetsCrossProduct). None when no construct appears."""
        if not any(isinstance(g, t.GroupingSets) for g in group_by):
            return None
        sets = [()]
        for g in group_by:
            options = (
                list(g.sets) if isinstance(g, t.GroupingSets) else [(g,)]
            )
            sets = [s + tuple(o) for s in sets for o in options]
            if len(sets) > 64:
                # each set re-plans and re-executes the source; cap like
                # the reference's max-grouping-sets session limit
                raise PlanningError(
                    "too many grouping sets (limit 64); reduce the "
                    "CUBE/GROUPING SETS cross product"
                )
        full: List[t.Node] = []
        for s in sets:
            for e in s:
                if isinstance(e, t.NumberLiteral):
                    raise PlanningError(
                        "ordinals are not supported inside "
                        "ROLLUP/CUBE/GROUPING SETS; name the column"
                    )
                if e not in full:
                    full.append(e)
        return sets, full

    def _plan_grouping_sets(
        self, sel: t.Select, sets, full, outer, ctes
    ) -> RelationPlan:
        """One Aggregate per grouping set, unioned; missing group columns
        are typed NULLs (reference plans this as GroupIdNode + one shared
        aggregation — re-designed as a union of independent aggregations,
        which XLA handles as parallel fused reductions)."""
        if sel.distinct:
            raise PlanningError("SELECT DISTINCT with GROUPING SETS")
        wins: List[t.FunctionCall] = []
        for it in sel.items:
            if isinstance(it, t.SelectItem):
                _collect_windows(it.expr, wins)
        if wins:
            # a window over grouping sets runs over the UNION of all sets:
            # rewrite into (inner: per-set aggregation union exposing group
            # columns, aggregates, and grouping() bits) -> (outer: windows
            # over the union). Reference: GroupIdNode feeding WindowNode.
            return self._plan_gs_with_windows(sel, full, outer, ctes)
        parts = [
            self.plan_select(
                dataclasses.replace(sel, group_by=tuple(s)),
                outer,
                ctes,
                gs_ctx=(s, tuple(e for e in full if e not in s), full),
            )
            for s in sets
        ]
        common = [ty for _, ty in parts[0].node.fields]
        for rp in parts[1:]:
            common = [
                T.common_super_type(a, ty)
                for a, (_, ty) in zip(common, rp.node.fields)
            ]
        first = self._coerce_columns(parts[0].node, common)
        first_names = tuple(n for n, _ in first.fields)
        nodes: List[N.PlanNode] = [first]
        for rp in parts[1:]:
            cn = self._coerce_columns(rp.node, common)
            exprs = tuple(ir.ColumnRef(n, ty) for n, ty in cn.fields)
            nodes.append(N.Project(cn, exprs, first_names))
        node = (
            nodes[0]
            if len(nodes) == 1
            else N.Union(tuple(nodes), distinct=False)
        )
        scope = Scope(
            [
                FieldRef(f.qualifier, f.name, ch, ty)
                for f, (ch, ty) in zip(parts[0].scope.fields, first.fields)
            ]
        )
        return RelationPlan(node, scope)

    def _plan_gs_with_windows(self, sel: t.Select, full, outer, ctes):
        """Split a grouping-sets SELECT containing window functions into an
        inner aggregation-only select (per-set union, existing path) and an
        outer select computing the windows over that union.

        Every group expression, aggregate call, and grouping() call is
        given an inner output alias; the outer expressions are the original
        ones with those subtrees replaced by alias references."""
        aggs: List[t.FunctionCall] = []
        grps: List[t.FunctionCall] = []
        for it in sel.items:
            if isinstance(it, t.SelectItem):
                _collect_aggregates(it.expr, aggs)
                _collect_grouping_calls(it.expr, grps)
        if sel.having is not None:
            _collect_aggregates(sel.having, aggs)

        mapping: Dict[t.Node, t.Node] = {}
        inner_items: List[t.SelectItem] = []

        def add_inner(expr: t.Node, alias: str) -> None:
            inner_items.append(t.SelectItem(expr, alias))
            mapping[expr] = t.Identifier((alias,))

        seen: set = set()
        used_aliases: set = set()
        for i, g in enumerate(full):
            if g in seen:
                continue
            seen.add(g)
            # bare identifiers keep their natural name; qualified ones
            # (a.x vs b.x would collide on 'x') and expressions get
            # positional aliases
            if isinstance(g, t.Identifier) and len(g.parts) == 1 and (
                g.parts[-1] not in used_aliases
            ):
                alias = g.parts[-1]
            else:
                alias = f"_gs{i}"
            used_aliases.add(alias)
            add_inner(g, alias)
        for i, a in enumerate(aggs):
            if a in seen:
                continue
            seen.add(a)
            add_inner(a, f"_agg{i}")
        for i, g in enumerate(grps):
            if g in seen:
                continue
            seen.add(g)
            add_inner(g, f"_grp{i}")

        inner_sel = dataclasses.replace(
            sel, items=tuple(inner_items), distinct=False
        )
        outer_items = tuple(
            t.SelectItem(_ast_replace(it.expr, mapping), it.alias)
            if isinstance(it, t.SelectItem)
            else it
            for it in sel.items
        )
        derived = t.SubqueryRelation(
            t.Query(body=inner_sel), alias="_gsw", column_aliases=()
        )
        outer_sel = t.Select(
            items=outer_items,
            from_=derived,
            where=None,
            group_by=(),
            having=None,
            distinct=sel.distinct,
        )
        return self.plan_select(outer_sel, outer, ctes)

    @staticmethod
    def _order_item_match(body, order_ast, scope) -> Optional[ir.ColumnRef]:
        """If `order_ast` structurally equals a select item's expression,
        return a ref to that item's output channel. Requires positional
        item/field alignment, so bails out when the select list has a *."""
        if not isinstance(body, t.Select):
            return None
        if isinstance(order_ast, t.NumberLiteral):
            return None  # ordinals stay strict — never match a literal item
        if any(isinstance(it, t.Star) for it in body.items):
            return None
        if len(body.items) != len(scope.fields):
            return None
        for it, f in zip(body.items, scope.fields):
            if isinstance(it, t.SelectItem) and it.expr == order_ast:
                return ir.ColumnRef(f.channel, f.type)
        return None

    def plan_query_body(self, body, outer, ctes) -> RelationPlan:
        if isinstance(body, t.Select):
            return self.plan_select(body, outer, ctes)
        if isinstance(body, t.SetOperation):
            return self.plan_set_op(body, outer, ctes)
        if isinstance(body, t.Query):
            return self.plan_query(body, outer, ctes)
        if isinstance(body, t.Values):
            return self.plan_values(body, outer, ctes)
        raise PlanningError(f"unsupported query body {type(body).__name__}")

    def plan_values(self, v: t.Values, outer, ctes) -> RelationPlan:
        """Each VALUES row becomes Project(SingleRow); rows are coerced to
        per-column common super types and unioned (reference: Values.java →
        ValuesNode with per-row constant expressions)."""
        if not v.rows:
            raise PlanningError("VALUES requires at least one row")
        width = len(v.rows[0])
        row_nodes: List[N.PlanNode] = []
        for row in v.rows:
            if len(row) != width:
                raise PlanningError("VALUES rows differ in column count")
            sctx = SelectContext(self, [Scope([])], outer, ctes, None)
            exprs = tuple(sctx.translate(cell) for cell in row)
            leaf = N.SingleRow(self.channel("singlerow"))
            names = tuple(self.channel(f"_col{i}") for i in range(width))
            row_nodes.append(N.Project(leaf, exprs, names))
        common = [ty for _, ty in row_nodes[0].fields]
        for rn in row_nodes[1:]:
            common = [
                T.common_super_type(a, ty)
                for a, (_, ty) in zip(common, rn.fields)
            ]
        first = self._coerce_columns(row_nodes[0], common)
        parts: List[N.PlanNode] = [first]
        first_names = tuple(n for n, _ in first.fields)
        for rn in row_nodes[1:]:
            cn = self._coerce_columns(rn, common)
            exprs = tuple(ir.ColumnRef(n, ty) for n, ty in cn.fields)
            parts.append(N.Project(cn, exprs, first_names))
        node: N.PlanNode = (
            parts[0] if len(parts) == 1 else N.Union(tuple(parts), distinct=False)
        )
        scope = Scope(
            [
                FieldRef(None, f"_col{i}", ch, ty)
                for i, (ch, ty) in enumerate(first.fields)
            ]
        )
        return RelationPlan(node, scope)

    def _order_expr(self, ast, scope: Scope, outer, ctes, node) -> ir.RowExpression:
        """ORDER BY resolves against output columns (aliases) first."""
        if isinstance(ast, t.Identifier) and len(ast.parts) == 1:
            f = scope.resolve(ast.parts)
            if f is not None:
                return ir.ColumnRef(f.channel, f.type)
        if isinstance(ast, t.NumberLiteral) and "." not in ast.text:
            idx = int(ast.text)
            if not 1 <= idx <= len(scope.fields):
                raise PlanningError(
                    f"ORDER BY position {idx} is not in select list "
                    f"(1..{len(scope.fields)})"
                )
            f = scope.fields[idx - 1]
            return ir.ColumnRef(f.channel, f.type)
        ctx = SelectContext(self, [scope], outer, ctes, None)
        return ctx.translate(ast)

    def plan_set_op(self, op: t.SetOperation, outer, ctes) -> RelationPlan:
        left = self.plan_query_body(op.left, outer, ctes)
        right = self.plan_query_body(op.right, outer, ctes)
        lf, rf = left.node.fields, right.node.fields
        if len(lf) != len(rf):
            raise PlanningError("set operation inputs differ in column count")
        # per-column common super type; coerce both sides where needed
        common = [
            T.common_super_type(lt, rt) for (_, lt), (_, rt) in zip(lf, rf)
        ]
        lnode = self._coerce_columns(left.node, common)
        # rename right channels to the (possibly coerced) left channels
        rnode = self._coerce_columns(right.node, common)
        exprs = tuple(ir.ColumnRef(n, ty) for n, ty in rnode.fields)
        renamed = N.Project(rnode, exprs, tuple(n for n, _ in lnode.fields))
        if op.op in ("union", "union_all"):
            node: N.PlanNode = N.Union((lnode, renamed), distinct=op.op == "union")
        elif op.op in ("intersect", "except"):
            node = self._plan_intersect_except(op.op, lnode, renamed)
        else:
            raise PlanningError(
                f"set operation {op.op.replace('_', ' ').upper()} "
                "is not supported (only the DISTINCT variants are)"
            )
        scope = Scope(
            [
                FieldRef(f.qualifier, f.name, ch, ty)
                for f, (ch, ty) in zip(left.scope.fields, lnode.fields)
            ]
        )
        return RelationPlan(node, scope)

    def _plan_intersect_except(self, op: str, lnode, rnode) -> N.PlanNode:
        """INTERSECT/EXCEPT (distinct) as a side-tagged union + grouped
        per-side counts + filter (reference: SetOperationNodeTranslator
        rewrites these the same way onto a marker-aggregation). GROUP BY
        treats NULL keys as equal, which is exactly the set-op semantics —
        no null-safe join machinery needed."""
        names = tuple(n for n, _ in lnode.fields)
        refs = tuple(ir.ColumnRef(n, ty) for n, ty in lnode.fields)
        side = self.channel("setop_side")
        lp = N.Project(
            lnode, refs + (ir.lit(0),), names + (side,)
        )
        rrefs = tuple(ir.ColumnRef(n, ty) for n, ty in rnode.fields)
        rp = N.Project(rnode, rrefs + (ir.lit(1),), names + (side,))
        u = N.Union((lp, rp), distinct=False)
        side_ref = ir.ColumnRef(side, T.BIGINT)
        cl = self.channel("cnt_l")
        cr = self.channel("cnt_r")

        def count_side(v):
            cond = ir.Call("eq", (side_ref, ir.lit(v)), T.BOOLEAN)
            return ir.Call(
                "if", (cond, ir.lit(1), ir.Literal(None, T.BIGINT)), T.BIGINT
            )

        agg = N.Aggregate(
            u,
            refs,
            names,
            (
                AggSpec("count", count_side(0), cl, T.BIGINT),
                AggSpec("count", count_side(1), cr, T.BIGINT),
            ),
        )
        cl_ref = ir.ColumnRef(cl, T.BIGINT)
        cr_ref = ir.ColumnRef(cr, T.BIGINT)
        zero = ir.lit(0)
        if op == "intersect":
            pred = ir.Call(
                "and",
                (
                    ir.Call("gt", (cl_ref, zero), T.BOOLEAN),
                    ir.Call("gt", (cr_ref, zero), T.BOOLEAN),
                ),
                T.BOOLEAN,
            )
        else:  # except
            pred = ir.Call(
                "and",
                (
                    ir.Call("gt", (cl_ref, zero), T.BOOLEAN),
                    ir.Call("eq", (cr_ref, zero), T.BOOLEAN),
                ),
                T.BOOLEAN,
            )
        flt = N.Filter(agg, pred)
        return N.Project(flt, refs, names)

    def _coerce_columns(self, node: N.PlanNode, target_types) -> N.PlanNode:
        if all(ty == tt for (_, ty), tt in zip(node.fields, target_types)):
            return node
        exprs = []
        names = []
        for (ch, ty), tt in zip(node.fields, target_types):
            ref = ir.ColumnRef(ch, ty)
            if ty == tt:
                exprs.append(ref)
                names.append(ch)
            else:
                exprs.append(ir.cast(ref, tt))
                names.append(self.channel("coerce"))
        return N.Project(node, tuple(exprs), tuple(names))

    def _resolve_table_name(self, name: str) -> str:
        """Resolve a possibly-qualified `[catalog.][schema.]table` against
        the session catalog (reference: MetadataManager qualified-name
        resolution; connectors here expose one implicit 'default' schema,
        except names the catalog itself registers with dots, e.g.
        system.runtime.queries)."""
        known = {t.lower() for t in self.catalog.table_names()}
        if name in known:
            return name
        parts = name.split(".")
        if len(parts) == 1:
            raise PlanningError(f"unknown table {name!r}")
        cat_name = str(getattr(self.catalog, "name", "")).lower()
        # a catalog store mounts members as dotted `<catalog>.<table>`
        # names; collapse the implicit `default` schema against those
        if (
            len(parts) == 3
            and parts[1] == "default"
            and f"{parts[0]}.{parts[2]}" in known
        ):
            return f"{parts[0]}.{parts[2]}"
        if len(parts) == 3 and parts[0] != cat_name:
            raise PlanningError(
                f"unknown catalog {parts[0]!r} (session catalog is "
                f"{cat_name!r})"
            )
        schema_part = parts[-2]
        if schema_part not in ("default", cat_name):
            raise PlanningError(f"unknown schema {schema_part!r}")
        if parts[-1] in known:
            return parts[-1]
        raise PlanningError(f"unknown table {name!r}")

    # -- relations --
    def plan_relation(self, rel, outer, ctes) -> RelationPlan:
        if isinstance(rel, t.TableSample):
            import random as _random

            inner = self.plan_relation(rel.relation, outer, ctes)
            if not 0.0 <= rel.percentage <= 100.0:
                # reference semantics: SAMPLE_PERCENTAGE_OUT_OF_RANGE
                # fails the query — clamping would silently change results
                raise PlanningError(
                    f"TABLESAMPLE percentage must be in [0, 100], got "
                    f"{rel.percentage!r}"
                )
            frac = rel.percentage / 100.0
            # plan-time seed: each query samples a fresh subset while the
            # compiled kernel stays deterministic (reference SampleNode)
            node = N.Sample(
                inner.node, frac, _random.getrandbits(62)
            )
            return RelationPlan(node, inner.scope)
        if isinstance(rel, t.Table):
            return self.plan_table(rel, ctes, outer)
        if isinstance(rel, t.SubqueryRelation):
            sub = self.plan_query(rel.query, outer, ctes)
            names = rel.column_aliases or tuple(
                f.name for f in sub.scope.fields
            )
            if len(names) != len(sub.scope.fields):
                raise PlanningError("subquery column alias count mismatch")
            scope = Scope(
                [
                    FieldRef(rel.alias, n, f.channel, f.type)
                    for n, f in zip(names, sub.scope.fields)
                ]
            )
            return RelationPlan(sub.node, scope)
        if isinstance(rel, t.Join):
            raise PlanningError("join nodes handled by plan_select")
        raise PlanningError(f"unsupported relation {type(rel).__name__}")

    def plan_table(self, rel: t.Table, ctes, outer) -> RelationPlan:
        name = rel.name.lower()
        if name in ctes:
            item = ctes[name]
            sub = self.plan_query(item.query, outer, {k: v for k, v in ctes.items() if k != name})
            names = item.column_aliases or tuple(f.name for f in sub.scope.fields)
            alias = rel.alias or rel.name
            scope = Scope(
                [
                    FieldRef(alias, n, f.channel, f.type)
                    for n, f in zip(names, sub.scope.fields)
                ]
            )
            return RelationPlan(sub.node, scope)
        if name in self.views:
            # planner-time view expansion (reference StatementAnalyzer
            # view resolution + execution/CreateViewTask.java): the stored
            # query text is parsed and planned inline like a CTE
            if name in self._view_stack:
                raise PlanningError(f"view {name!r} is recursive")
            from .parser import parse as _parse

            vast = _parse(self.views[name])
            if not isinstance(vast, t.Query):
                raise PlanningError(f"view {name!r} is not a SELECT query")
            self._view_stack.add(name)
            try:
                sub = self.plan_query(vast, outer, {})
            finally:
                self._view_stack.discard(name)
            alias = rel.alias or rel.name
            scope = Scope(
                [
                    FieldRef(alias, f.name, f.channel, f.type)
                    for f in sub.scope.fields
                ]
            )
            return RelationPlan(sub.node, scope)
        name = self._resolve_table_name(name)
        schema = self.catalog.schema(name)
        # qualified names default-alias to the last segment, so
        # `from system.runtime.queries` resolves `queries.state`
        alias = rel.alias or name.split(".")[-1]
        columns = []
        fields = []
        for cname, ctype in schema.items():
            ch = self.channel(cname)
            columns.append((ch, cname, ctype))
            fields.append(FieldRef(alias, cname, ch, ctype))
        node = N.TableScan(self.catalog.name, name, tuple(columns))
        return RelationPlan(node, Scope(fields))

    # -- SELECT --
    def plan_select(
        self, sel: t.Select, outer, ctes, gs_ctx=None
    ) -> RelationPlan:
        expanded = self._expand_group_by(sel.group_by)
        if expanded is not None:
            # always route through the grouping-sets planner (even a single
            # set) so GROUP BY () / ROLLUP() force aggregation semantics
            sets, full = expanded
            return self._plan_grouping_sets(sel, sets, full, outer, ctes)
        ctx = FromPlanner(self, outer, ctes)
        if sel.from_ is not None:
            ctx.add_relation(sel.from_)
        plan, scope = ctx.assemble(sel.where)

        holder = PlanHolder(plan)
        sctx = SelectContext(self, [scope], outer, ctes, holder)

        # apply deferred subquery conjuncts (EXISTS / IN / scalar comparisons)
        for conj in ctx.subquery_conjuncts:
            pred = sctx.translate(conj)
            if pred is not None:
                holder.plan = N.Filter(holder.plan, pred)

        # aggregate extraction over select items, HAVING, ORDER BY handled by
        # the caller via output scope
        items = self._expand_stars(sel.items, scope)
        agg_calls: List[t.FunctionCall] = []
        for item in items:
            _collect_aggregates(item.expr, agg_calls)
        if sel.having is not None:
            _collect_aggregates(sel.having, agg_calls)

        group_exprs: List[ir.RowExpression] = []
        group_names: List[str] = []
        # AST -> (channel, type) of each grouping expression, so select
        # items / HAVING containing the same expression resolve to the
        # grouped channel instead of re-translating (reference: the
        # analyzer's grouping-expression matching in AggregationAnalyzer)
        group_map: Dict[t.Node, Tuple[str, T.Type]] = {}
        if sel.group_by or agg_calls or gs_ctx is not None:
            for g in sel.group_by:
                ast_g = g
                if isinstance(g, t.NumberLiteral) and "." not in g.text:
                    idx = int(g.text)
                    if not 1 <= idx <= len(items):
                        raise PlanningError(
                            f"GROUP BY position {idx} is not in select list "
                            f"(1..{len(items)})"
                        )
                    ast_g = items[idx - 1].expr
                elif (
                    isinstance(g, t.Identifier)
                    and len(g.parts) == 1
                    and scope.resolve(g.parts) is None
                ):
                    # select-list alias (extension over the reference;
                    # ambiguity -> error below via normal resolution)
                    matches = [
                        it
                        for it in items
                        if (it.alias or "").lower() == g.parts[0].lower()
                    ]
                    if len(matches) == 1:
                        ast_g = matches[0].expr
                e = sctx.translate(ast_g)
                if isinstance(e, ir.ColumnRef):
                    ch = e.name
                else:
                    ch = self.channel("gk")
                group_exprs.append(e)
                group_names.append(ch)
                group_map[ast_g] = (ch, e.type)

            aggs, agg_map, agg_order = self._plan_aggregates(agg_calls, sctx)
            if not aggs and not group_exprs:
                # GROUP BY (): exactly one output row regardless of input
                # (the empty grouping set of a ROLLUP). A hidden count(*)
                # drives the global-aggregation machinery; nothing reads it.
                aggs = [
                    AggSpec("count_star", None, self.channel("gcount"), T.BIGINT)
                ]
            if agg_order is not None:
                holder.plan = N.Sort(holder.plan, agg_order)
            holder.plan, distinct_rewritten = self._build_aggregate(
                holder.plan, group_exprs, group_names, aggs
            )
            # post-aggregation scope: group channels + agg channels
            post_fields = []
            for e, ch, g in zip(group_exprs, group_names, sel.group_by):
                typ = e.type
                # keep user name resolvable: if group expr was a column,
                # reuse its field name/qualifier
                fr = _field_for_channel(scope, ch)
                if fr is not None:
                    post_fields.append(FieldRef(fr.qualifier, fr.name, ch, typ))
                else:
                    post_fields.append(FieldRef(None, ch, ch, typ))
            for a in aggs:
                post_fields.append(FieldRef(None, a.name, a.name, a.output_type))
            agg_scope = Scope(post_fields)
            pre_sctx = sctx
            sctx = SelectContext(self, [agg_scope], outer, ctes, holder, agg_map)
            sctx.group_map = group_map
            if gs_ctx is not None:
                cur_set, null_asts, full = gs_ctx
                # grouping-set columns absent from this set read as typed
                # NULLs; grouping() resolves to this set's bitmask
                sctx.group_null_map = {
                    a: pre_sctx.translate(a).type for a in null_asts
                }
                sctx.grouping_ctx = (tuple(full), tuple(cur_set))
            else:
                # plain GROUP BY: grouping() over grouped columns is 0
                plain = tuple(group_map)
                sctx.grouping_ctx = (plain, plain)

        if sel.having is not None:
            pred = sctx.translate(sel.having)
            holder.plan = N.Filter(holder.plan, pred)

        # window functions: computed after WHERE/GROUP BY/HAVING, before the
        # final projection (reference WindowNode placement in LogicalPlanner)
        window_calls: List[t.FunctionCall] = []
        for item in items:
            _collect_windows(item.expr, window_calls)
        if window_calls:
            # windows evaluate AFTER aggregation (reference: WindowNode
            # sits above AggregationNode in LogicalPlanner); over an
            # aggregated query, window inputs resolve through agg_map /
            # group channels of the post-aggregation context
            win_map = self._plan_windows(window_calls, sctx, holder)
            sctx.agg_map.update(win_map)

        # final projection
        out_exprs: List[ir.RowExpression] = []
        out_names: List[str] = []
        out_fields: List[FieldRef] = []
        for i, item in enumerate(items):
            e = sctx.translate(item.expr)
            name = item.alias or _derive_name(item.expr) or f"_col{i}"
            if isinstance(e, ir.ColumnRef):
                ch = e.name
            else:
                ch = self.channel(name)
            out_exprs.append(e)
            out_names.append(ch)
            out_fields.append(FieldRef(None, name, ch, e.type))
        node = N.Project(holder.plan, tuple(out_exprs), tuple(out_names))
        if sel.distinct:
            # SQL: ORDER BY under DISTINCT must use select-list columns
            return RelationPlan(N.Distinct(node), Scope(out_fields))
        return RelationPlan(node, Scope(out_fields), pre_scope=sctx.scopes[0])

    def _expand_stars(self, items, scope: Scope) -> List[t.SelectItem]:
        out = []
        for item in items:
            if isinstance(item, t.Star):
                for f in scope.visible(item.qualifier):
                    out.append(
                        t.SelectItem(t.Identifier((f.qualifier, f.name) if f.qualifier else (f.name,)), f.name)
                    )
            else:
                out.append(item)
        return out

    @staticmethod
    def _translate_frame(frame_spec, order):
        """(type, start, end) strings -> ops.window.Frame. RANGE offsets are
        scaled into the single order key's storage units (reference
        FrameInfo + RANGE frame value coercion)."""
        import decimal as _dec

        from ..ops.window import (
            CURRENT,
            FOLLOWING,
            PRECEDING,
            UNB_FOLLOWING,
            UNB_PRECEDING,
            Frame,
        )

        ftype, fstart, fend = frame_spec

        def key_unit(text: str):
            if ftype == "rows":
                v = int(text)
                if v < 0:
                    raise PlanningError("frame offset must be non-negative")
                return v
            if not order:
                raise PlanningError("RANGE offset frame requires ORDER BY")
            kt = order[0].expr.type
            if isinstance(kt, T.DecimalType):
                return int(_dec.Decimal(text).scaleb(kt.scale))
            if T.is_floating(kt):
                return float(text)
            return int(text)

        def bound(s: str):
            if s == "unbounded preceding":
                return UNB_PRECEDING, 0
            if s == "unbounded following":
                return UNB_FOLLOWING, 0
            if s == "current row":
                return CURRENT, 0
            num, _, kind = s.rpartition(" ")
            return (
                PRECEDING if kind == "preceding" else FOLLOWING,
                key_unit(num),
            )

        sk, so = bound(fstart)
        ek, eo = bound(fend)
        if sk == UNB_FOLLOWING or ek == UNB_PRECEDING:
            raise PlanningError(f"invalid window frame {frame_spec}")
        return Frame(ftype, sk, so, ek, eo)

    def _plan_windows(self, calls, sctx, holder) -> Dict:
        """Group window calls by spec, append one Window node per spec."""
        from ..ops.window import AGGREGATE, OFFSET, RANKING, VALUE, WindowFunc

        win_map: Dict[t.Node, Tuple[str, T.Type]] = {}
        by_spec: Dict[t.WindowSpec, List[t.FunctionCall]] = {}
        for c in calls:
            by_spec.setdefault(c.window, []).append(c)
        for spec, group in by_spec.items():
            part = tuple(sctx.translate(p) for p in spec.partition_by)
            order = tuple(
                SortKey(sctx.translate(si.expr), si.ascending, si.nulls_first)
                for si in spec.order_by
            )
            running_default = bool(spec.order_by)
            frame_obj = None
            if spec.frame is not None:
                frame_obj = self._translate_frame(spec.frame, order)
            funcs = []
            for c in group:
                if c in win_map:
                    continue
                name = c.name
                if c.filter is not None and name not in AGGREGATE:
                    raise PlanningError(
                        f"FILTER is not supported for window function {name!r}"
                    )
                ch = self.channel(name)
                if name in ("row_number", "rank", "dense_rank"):
                    wf = WindowFunc(name, None, ch, T.BIGINT)
                elif name in ("percent_rank", "cume_dist"):
                    wf = WindowFunc(name, None, ch, T.DOUBLE)
                elif name == "ntile":
                    n = c.args[0]
                    if not isinstance(n, t.NumberLiteral):
                        raise PlanningError("ntile requires a literal count")
                    wf = WindowFunc(name, None, ch, T.BIGINT, offset=int(n.text))
                elif name in OFFSET:
                    inp = sctx.translate(c.args[0])
                    off = 1
                    if len(c.args) > 1:
                        if not isinstance(c.args[1], t.NumberLiteral):
                            raise PlanningError(f"{name} offset must be literal")
                        off = int(c.args[1].text)
                    default = None
                    if len(c.args) > 2:
                        default = sctx.translate(c.args[2])
                        if default.type != inp.type:
                            default = ir.cast(default, inp.type)
                    wf = WindowFunc(
                        name, inp, ch, inp.type, offset=off, default=default
                    )
                elif name in VALUE:
                    inp = sctx.translate(c.args[0])
                    off = 1
                    if name == "nth_value":
                        if len(c.args) < 2 or not isinstance(
                            c.args[1], t.NumberLiteral
                        ):
                            raise PlanningError(
                                "nth_value requires a literal position"
                            )
                        off = int(c.args[1].text)
                        if off < 1:
                            raise PlanningError("nth_value position must be >= 1")
                    wf = WindowFunc(
                        name, inp, ch, inp.type, offset=off, frame=frame_obj
                    )
                elif name in AGGREGATE:
                    wfilt = None
                    if c.filter is not None:
                        wfilt = sctx.translate(c.filter)
                        if wfilt is None or wfilt.type != T.BOOLEAN:
                            raise PlanningError(
                                "FILTER (WHERE ...) must be boolean"
                            )
                    if c.is_star:
                        func = "count"
                        out_t = T.BIGINT
                        if wfilt is not None:
                            inp = ir.Call(
                                "if",
                                (wfilt, ir.lit(1), ir.Literal(None, T.BIGINT)),
                                T.BIGINT,
                            )
                        else:
                            inp = None
                    else:
                        inp = sctx.translate(c.args[0])
                        if wfilt is not None:
                            inp = ir.Call(
                                "if",
                                (wfilt, inp, ir.Literal(None, inp.type)),
                                inp.type,
                            )
                        func = "count" if name == "count" else name
                        if (
                            isinstance(inp.type, T.DecimalType)
                            and inp.type.is_long
                            and not (
                                frame_obj is not None
                                and func in ("sum", "avg", "min", "max")
                            )
                        ):
                            # unframed long-decimal windows compute in
                            # double (documented precision trade); FRAMED
                            # sum/avg/min/max stay exact — _frame_agg
                            # carries two-lane sums and the lexicographic
                            # sparse table covers framed min/max
                            inp = ir.cast(inp, T.DOUBLE)
                        out_t = AggSpec.infer_output_type(func, inp.type)
                    wf = WindowFunc(
                        func, inp, ch, out_t, running=running_default,
                        frame=frame_obj,
                    )
                else:
                    raise PlanningError(f"unknown window function {name!r}")
                funcs.append(wf)
                win_map[c] = (ch, wf.output_type)
            holder.plan = N.Window(holder.plan, part, order, tuple(funcs))
        return win_map

    def _plan_aggregates(
        self, agg_calls, sctx
    ) -> Tuple[List[AggSpec], Dict, Optional[tuple]]:
        """Returns (specs, call->channel map, agg-internal ORDER BY keys).
        The ordering is RETURNED, not stashed on the planner: mutable
        planner-wide state would leak stale sort keys into the next
        aggregation whenever a PlanningError fired between set and
        consume (ADVICE round-5)."""
        aggs: List[AggSpec] = []
        agg_map: Dict[t.Node, Tuple[str, T.Type]] = {}
        seen: Dict[t.Node, int] = {}
        agg_order: Optional[tuple] = None
        for call in agg_calls:
            if call in agg_map:
                continue
            fname = call.name
            orig_call = call
            if getattr(call, "order_by", ()) and call.window is None:
                # agg(x ORDER BY k): pre-sort the aggregation input; the
                # grouped machinery's stable group sort preserves the
                # within-group order (reference AggregationNode orderBy +
                # SortedAggregation)
                keys = tuple(
                    SortKey(
                        sctx.translate(si.expr), si.ascending, si.nulls_first
                    )
                    for si in call.order_by
                )
                if agg_order is not None and agg_order != keys:
                    raise PlanningError(
                        "aggregates with DIFFERENT ORDER BY orderings in "
                        "one aggregation are not supported"
                    )
                agg_order = keys
            if fname == "approx_distinct":
                # real HyperLogLog estimate (reference
                # ApproximateCountDistinctAggregations + airlift HLL) with
                # mergeable register partials for the distributed path.
                # The optional second argument (max standard error) is
                # dropped: the engine runs one register width (p=10).
                if not 1 <= len(call.args) <= 2:
                    raise PlanningError(
                        "approx_distinct takes 1 or 2 arguments"
                    )
                call = dataclasses.replace(call, args=call.args[:1])
            if fname in REWRITE_AGG_FUNCS:
                agg_map[call] = self._rewrite_aggregate(call, sctx, aggs)
                continue
            if fname not in AGG_FUNCS:
                raise PlanningError(f"unsupported aggregate {fname!r}")
            # agg(x) FILTER (WHERE p) masks the input to NULL where p is not
            # true (reference: AggregationNode mask channels); NULL inputs
            # never contribute, which is exactly FILTER's semantics.
            filt = None
            if call.filter is not None:
                filt = sctx.translate(call.filter)
                if filt is None or filt.type != T.BOOLEAN:
                    raise PlanningError("FILTER (WHERE ...) must be boolean")
            if call.is_star:
                if filt is not None:
                    inp = ir.Call(
                        "if",
                        (filt, ir.lit(1), ir.Literal(None, T.BIGINT)),
                        T.BIGINT,
                    )
                    spec = AggSpec("count", inp, self.channel("count"), T.BIGINT)
                else:
                    spec = AggSpec(
                        "count_star", None, self.channel("count"), T.BIGINT
                    )
            elif fname == "approx_percentile":
                # computed EXACTLY by selection (the reference's qdigest is
                # an estimate; exact satisfies the contract)
                if len(call.args) != 2:
                    raise PlanningError(
                        "approx_percentile takes (value, percentile); the "
                        "weighted/accuracy forms are not supported"
                    )
                if call.distinct:
                    raise PlanningError(
                        "approx_percentile does not support DISTINCT"
                    )
                import decimal as _dec

                e = sctx.translate(call.args[0])
                p = sctx.translate(call.args[1])
                if (
                    isinstance(p, ir.Call)
                    and p.name == "array_constructor"
                    and all(isinstance(x, ir.Literal) for x in p.args)
                ):
                    # approx_percentile(x, ARRAY[f...]) -> one percentile
                    # aggregate per fraction + an array post-formula
                    # (reference ApproximateLongPercentileArrayAggregations)
                    if filt is not None:
                        e = ir.Call(
                            "if", (filt, e, ir.Literal(None, e.type)),
                            e.type,
                        )
                    refs = []
                    for x in p.args:
                        frac = float(x.value)
                        if not 0.0 <= frac <= 1.0:
                            raise PlanningError(
                                "percentile must be in [0, 1]"
                            )
                        sp = AggSpec(
                            "percentile", e, self.channel(fname), e.type,
                            input2=ir.Literal(frac, T.DOUBLE),
                        )
                        aggs.append(sp)
                        refs.append(ir.ColumnRef(sp.name, sp.output_type))
                    agg_map[orig_call] = ir.Call(
                        "array_constructor",
                        tuple(refs),
                        T.ArrayType(e.type),
                    )
                    continue
                if not isinstance(p, ir.Literal) or not isinstance(
                    p.value, (int, float, _dec.Decimal)
                ):
                    raise PlanningError(
                        "approx_percentile requires a literal percentile"
                    )
                frac = float(p.value)
                if not 0.0 <= frac <= 1.0:
                    raise PlanningError("percentile must be in [0, 1]")
                unsupported = isinstance(
                    e.type,
                    (T.VarcharType, T.BooleanType, T.UnknownType, T.ArrayType),
                )
                if unsupported:
                    raise PlanningError(
                        f"approx_percentile over {e.type} is not supported"
                    )
                if filt is not None:
                    e = ir.Call(
                        "if", (filt, e, ir.Literal(None, e.type)), e.type
                    )
                spec = AggSpec(
                    "percentile", e, self.channel(fname), e.type,
                    input2=ir.Literal(frac, T.DOUBLE),
                )
            elif fname in ("learn_linear_regression", "learn_regressor",
                           "learn_classifier"):
                # presto-ml's learn_regressor(label, features) — model =
                # ARRAY(DOUBLE) weights via mergeable normal equations
                # (ops/mlreg.py); features is an ARRAY(DOUBLE)
                if len(call.args) != 2:
                    raise PlanningError(
                        f"{fname} takes (label, features)"
                    )
                if call.distinct:
                    raise PlanningError(
                        f"{fname} does not support DISTINCT"
                    )
                label = sctx.translate(call.args[0])
                feats = sctx.translate(call.args[1])
                if not isinstance(feats.type, T.ArrayType):
                    raise PlanningError(
                        f"{fname} features must be an array"
                    )
                if filt is not None:
                    label = ir.Call(
                        "if",
                        (filt, label, ir.Literal(None, label.type)),
                        label.type,
                    )
                spec = AggSpec(
                    "linreg", feats, self.channel(fname),
                    T.ArrayType(T.DOUBLE), input2=label,
                )
            elif fname == "map_union":
                if len(call.args) != 1:
                    raise PlanningError("map_union takes 1 argument")
                m = sctx.translate(call.args[0])
                if not isinstance(m.type, T.MapType):
                    raise PlanningError("map_union expects a map argument")
                if filt is not None:
                    m = ir.Call(
                        "if", (filt, m, ir.Literal(None, m.type)), m.type
                    )
                spec = AggSpec(
                    "map_union", m, self.channel(fname), m.type
                )
            elif fname == "multimap_agg":
                if len(call.args) != 2:
                    raise PlanningError("multimap_agg takes 2 arguments")
                k = sctx.translate(call.args[0])
                v = sctx.translate(call.args[1])
                if filt is not None:
                    k = ir.Call(
                        "if", (filt, k, ir.Literal(None, k.type)), k.type
                    )
                spec = AggSpec(
                    "multimap_agg", k, self.channel(fname),
                    T.MapType(k.type, T.ArrayType(v.type)), input2=v,
                )
            elif fname == "numeric_histogram":
                if len(call.args) != 2:
                    raise PlanningError(
                        "numeric_histogram takes (buckets, value)"
                    )
                b = sctx.translate(call.args[0])
                e = sctx.translate(call.args[1])
                if not isinstance(b, ir.Literal):
                    raise PlanningError(
                        "numeric_histogram bucket count must be a literal"
                    )
                if filt is not None:
                    e = ir.Call(
                        "if", (filt, e, ir.Literal(None, e.type)), e.type
                    )
                spec = AggSpec(
                    "num_hist", e, self.channel(fname),
                    T.MapType(T.DOUBLE, T.DOUBLE),
                    input2=ir.Literal(int(b.value), T.BIGINT),
                )
            elif fname == "qdigest_agg":
                e = sctx.translate(call.args[0])
                if filt is not None:
                    e = ir.Call(
                        "if", (filt, e, ir.Literal(None, e.type)), e.type
                    )
                spec = AggSpec(
                    "qsketch", e, self.channel(fname),
                    T.ArrayType(T.BIGINT),
                )
            elif fname == "approx_set":
                e = sctx.translate(call.args[0])
                if filt is not None:
                    e = ir.Call(
                        "if", (filt, e, ir.Literal(None, e.type)), e.type
                    )
                spec = AggSpec(
                    "hll_registers", e, self.channel(fname),
                    T.ArrayType(T.TINYINT, sketch="hll"),
                )
            elif fname == "merge":
                # merge(approx_set sketch) or merge(qdigest sketch):
                # dispatch on the sketch's element type
                e = sctx.translate(call.args[0])
                if not isinstance(e.type, T.ArrayType):
                    raise PlanningError("merge expects a sketch value")
                if filt is not None:
                    e = ir.Call(
                        "if", (filt, e, ir.Literal(None, e.type)), e.type
                    )
                if isinstance(e.type.element, T.TinyintType):
                    spec = AggSpec(
                        "hll_merge", e, self.channel(fname), e.type
                    )
                else:
                    spec = AggSpec(
                        "qsketch_merge", e, self.channel(fname), e.type
                    )
            elif fname == "map_agg":
                if len(call.args) != 2:
                    raise PlanningError("map_agg takes 2 arguments")
                if call.distinct:
                    raise PlanningError("map_agg does not support DISTINCT")
                k = sctx.translate(call.args[0])
                v = sctx.translate(call.args[1])
                if filt is not None:
                    k = ir.Call(
                        "if", (filt, k, ir.Literal(None, k.type)), k.type
                    )
                spec = AggSpec(
                    "map_agg", k, self.channel(fname),
                    T.MapType(k.type, v.type), input2=v,
                )
            elif fname in ("min_by", "max_by"):
                if len(call.args) != 2:
                    raise PlanningError(f"{fname} takes 2 arguments")
                if call.distinct:
                    raise PlanningError(f"{fname} does not support DISTINCT")
                e = sctx.translate(call.args[0])
                k = sctx.translate(call.args[1])
                if filt is not None:
                    # null ordering keys never contribute, so FILTER masks
                    # the key
                    k = ir.Call(
                        "if", (filt, k, ir.Literal(None, k.type)), k.type
                    )
                spec = AggSpec(
                    fname, e, self.channel(fname), e.type, input2=k
                )
            else:
                if len(call.args) == 2 and call.distinct and fname == "count":
                    # count(DISTINCT a, b): dedupe jointly over both
                    # channels (the Distinct-rewrite projects input AND
                    # input2), count tuples with no NULL component
                    e = sctx.translate(call.args[0])
                    e2 = sctx.translate(call.args[1])
                    if filt is not None:
                        e = ir.Call(
                            "if", (filt, e, ir.Literal(None, e.type)), e.type
                        )
                    spec = AggSpec(
                        "distinct_count", e, self.channel(fname), T.BIGINT,
                        input2=e2,
                    )
                    aggs.append(spec)
                    agg_map[orig_call] = (spec.name, spec.output_type)
                    continue
                if len(call.args) != 1:
                    raise PlanningError(
                        f"{fname} takes one argument"
                        + (
                            " (DISTINCT over more than 2 columns not "
                            "supported)" if call.distinct else ""
                        )
                    )
                (arg,) = call.args
                e = sctx.translate(arg)
                if filt is not None:
                    e = ir.Call("if", (filt, e, ir.Literal(None, e.type)), e.type)
                func = "count" if fname == "count" else fname
                out_t = AggSpec.infer_output_type(func, e.type)
                spec = AggSpec(func, e, self.channel(fname), out_t)
                if call.distinct:
                    spec = dataclasses.replace(spec, func=f"distinct_{func}")
            aggs.append(spec)
            agg_map[orig_call] = (spec.name, spec.output_type)
        return aggs, agg_map, agg_order

    def _rewrite_aggregate(self, call, sctx, aggs) -> ir.RowExpression:
        """Plan a derived aggregate as core aggregates + a post-formula
        (the reference compiles each as its own Accumulator,
        operator/aggregation/ — here the sum/count states are first-class
        aggregate columns and the finalizer is ordinary expression code that
        fuses into the post-aggregation projection)."""
        D = T.DOUBLE
        fname = call.name
        filt = None
        if call.filter is not None:
            filt = sctx.translate(call.filter)
            if filt is None or filt.type != T.BOOLEAN:
                raise PlanningError("FILTER (WHERE ...) must be boolean")

        def masked(e):
            if filt is None:
                return e
            return ir.Call("if", (filt, e, ir.Literal(None, e.type)), e.type)

        def emit(func, e, base):
            out_t = AggSpec.infer_output_type(func, None if e is None else e.type)
            sp = AggSpec(func, e, self.channel(base), out_t)
            aggs.append(sp)
            return ir.ColumnRef(sp.name, out_t)

        def c(name, *args, typ=D):
            return ir.Call(name, tuple(args), typ)

        def dlit(x):
            return ir.Literal(float(x), D)

        def null_if_under(n_ref, minimum, value):
            cond = c("gt", n_ref, ir.Literal(minimum - 1, T.BIGINT), typ=T.BOOLEAN)
            return ir.Call("if", (cond, value, ir.Literal(None, D)), D)

        def moments(arg_ast):
            # stable M2 from the central-moments accumulator — the raw
            # power-sum form (ss - s*s/n) cancels catastrophically for
            # large-mean data, same failure class as skewness/kurtosis
            x = masked(ir.cast(sctx.translate(arg_ast), D))
            arr_t = T.ArrayType(D)
            sp = AggSpec("cmoments", x, self.channel("mom"), arr_t)
            aggs.append(sp)
            mom = ir.ColumnRef(sp.name, arr_t)
            n = emit("count", x, "cnt")
            nd = ir.cast(n, D)
            num = ir.Call("element_at", (mom, ir.lit(3)), D)
            return n, nd, num

        if fname in ("stddev", "stddev_samp", "variance", "var_samp"):
            n, nd, num = moments(call.args[0])
            var = c("divide", num, c("subtract", nd, dlit(1.0)))
            out = var if fname in ("variance", "var_samp") else c("sqrt", var)
            return null_if_under(n, 2, out)
        if fname in ("stddev_pop", "var_pop"):
            n, nd, num = moments(call.args[0])
            var = c("divide", num, nd)
            out = var if fname == "var_pop" else c("sqrt", var)
            return null_if_under(n, 1, out)
        if fname in ("skewness", "kurtosis"):
            # stable central moments via the mergeable accumulator
            # (ops/moments.py; reference CentralMomentsAggregation,
            # operator/aggregation/AggregationUtils.java) — the old raw
            # power-sum rewrite catastrophically cancelled for large-mean
            # data (round-4 advisor: (nan, -inf) at mean ~1e9)
            x = masked(ir.cast(sctx.translate(call.args[0]), D))
            arr_t = T.ArrayType(D)
            sp = AggSpec("cmoments", x, self.channel("mom"), arr_t)
            aggs.append(sp)
            mom = ir.ColumnRef(sp.name, arr_t)
            n = emit("count", x, "cnt")
            nd = ir.cast(n, D)

            def elem(i):
                return ir.Call("element_at", (mom, ir.lit(i)), D)

            m2, m3, m4 = elem(3), elem(4), elem(5)
            if fname == "skewness":
                out = c(
                    "divide",
                    c("multiply", c("sqrt", nd), m3),
                    c("power", m2, dlit(1.5)),
                )
                return null_if_under(n, 3, out)
            out = c(
                "subtract",
                c("divide", c("multiply", nd, m4), c("multiply", m2, m2)),
                dlit(3.0),
            )
            return null_if_under(n, 4, out)
        if fname == "count_if":
            p = sctx.translate(call.args[0])
            inp = masked(
                ir.Call("if", (p, ir.lit(1), ir.Literal(None, T.BIGINT)), T.BIGINT)
            )
            return emit("count", inp, "count_if")
        if fname in ("bool_and", "every"):
            return emit("min", masked(sctx.translate(call.args[0])), "bool_and")
        if fname == "bool_or":
            return emit("max", masked(sctx.translate(call.args[0])), "bool_or")
        if fname == "arbitrary":
            return emit("min", masked(sctx.translate(call.args[0])), "arbitrary")
        if fname == "geometric_mean":
            xd = masked(ir.cast(sctx.translate(call.args[0]), D))
            a = emit("avg", c("ln", xd), "geomean")
            return c("exp", a)
        if fname in ("covar_samp", "covar_pop", "corr", "regr_slope",
                     "regr_intercept"):
            # regr_* (reference RealRegrSlopeAggregation family): both
            # args are (y, x) — slope = covar_pop(y,x)/var_pop(x),
            # intercept = avg(y) - slope * avg(x)
            x0 = ir.cast(sctx.translate(call.args[0]), D)
            y0 = ir.cast(sctx.translate(call.args[1]), D)
            both = c(
                "and",
                c("is_not_null", x0, typ=T.BOOLEAN),
                c("is_not_null", y0, typ=T.BOOLEAN),
                typ=T.BOOLEAN,
            )
            x = masked(ir.Call("if", (both, x0, ir.Literal(None, D)), D))
            y = masked(ir.Call("if", (both, y0, ir.Literal(None, D)), D))
            sx = emit("sum", x, "sx")
            sy = emit("sum", y, "sy")
            sxy = emit("sum", c("multiply", x, y), "sxy")
            n = emit("count", x, "cnt")
            nd = ir.cast(n, D)
            cov_num = c("subtract", sxy, c("divide", c("multiply", sx, sy), nd))
            if fname == "covar_pop":
                return null_if_under(n, 1, c("divide", cov_num, nd))
            if fname == "covar_samp":
                return null_if_under(
                    n, 2, c("divide", cov_num, c("subtract", nd, dlit(1.0)))
                )
            if fname in ("regr_slope", "regr_intercept"):
                # args are (y, x): x carries arg0=y, y carries arg1=x here
                sxx2 = emit("sum", c("multiply", y, y), "sxx")
                var_x = c(
                    "subtract", sxx2, c("divide", c("multiply", sy, sy), nd)
                )
                slope = c("divide", cov_num, var_x)
                cond = c("ne", var_x, dlit(0.0), typ=T.BOOLEAN)
                slope = ir.Call("if", (cond, slope, ir.Literal(None, D)), D)
                if fname == "regr_slope":
                    return null_if_under(n, 1, slope)
                mean_y = c("divide", sx, nd)
                mean_x = c("divide", sy, nd)
                out = c("subtract", mean_y, c("multiply", slope, mean_x))
                return null_if_under(n, 1, out)
            sxx = emit("sum", c("multiply", x, x), "sxx")
            syy = emit("sum", c("multiply", y, y), "syy")
            vx = c(
                "greatest",
                c("subtract", sxx, c("divide", c("multiply", sx, sx), nd)),
                dlit(0.0),
            )
            vy = c(
                "greatest",
                c("subtract", syy, c("divide", c("multiply", sy, sy), nd)),
                dlit(0.0),
            )
            denom = c("sqrt", c("multiply", vx, vy))
            corr = c("divide", cov_num, denom)
            cond = c("gt", denom, dlit(0.0), typ=T.BOOLEAN)
            return null_if_under(
                n, 2, ir.Call("if", (cond, corr, ir.Literal(None, D)), D)
            )
        raise PlanningError(f"unsupported aggregate {fname!r}")

    def _build_aggregate(self, child, group_exprs, group_names, aggs):
        """Build the Aggregate node, rewriting distinct aggregates as
        aggregation over Distinct (reference
        SingleDistinctAggregationToGroupBy)."""
        distinct_specs = [a for a in aggs if a.func.startswith("distinct_")]
        if not distinct_specs:
            return (
                N.Aggregate(child, tuple(group_exprs), tuple(group_names), tuple(aggs)),
                False,
            )
        if len({(a.input, a.input2) for a in distinct_specs}) > 1:
            # the dedupe below is joint over all distinct arguments; with
            # different arguments it would overcount — refuse loudly
            raise PlanningError(
                "multiple DISTINCT aggregates with different arguments "
                "are not supported"
            )
        if len(distinct_specs) != len(aggs):
            return self._build_mixed_distinct_aggregate(
                child, group_exprs, group_names, aggs, distinct_specs
            )
        # project group keys + distinct args, dedupe, then aggregate plainly
        proj_exprs = list(group_exprs)
        proj_names = list(group_names)
        inner_names = []
        pair_names = {}
        for a in distinct_specs:
            ch = self.channel("darg")
            proj_exprs.append(a.input)
            proj_names.append(ch)
            inner_names.append(ch)
            if a.input2 is not None:
                # multi-column DISTINCT: the second channel joins the
                # dedupe key (count(DISTINCT a, b) = distinct tuples)
                ch2 = self.channel("darg")
                proj_exprs.append(a.input2)
                proj_names.append(ch2)
                pair_names[ch] = (ch2, a.input2.type)
        pre = N.Distinct(N.Project(child, tuple(proj_exprs), tuple(proj_names)))
        new_groups = tuple(
            ir.ColumnRef(n, e.type) for n, e in zip(group_names, group_exprs)
        )

        def final_input(a, ch):
            inp = ir.ColumnRef(ch, a.input.type)
            if ch in pair_names:
                ch2, t2 = pair_names[ch]
                # SQL count over multiple args: tuples with ANY null
                # component do not count
                guard = ir.Call(
                    "and",
                    (
                        ir.Call("is_not_null", (inp,), T.BOOLEAN),
                        ir.Call(
                            "is_not_null",
                            (ir.ColumnRef(ch2, t2),),
                            T.BOOLEAN,
                        ),
                    ),
                    T.BOOLEAN,
                )
                return ir.Call(
                    "if", (guard, inp, ir.Literal(None, inp.type)),
                    inp.type,
                )
            return inp

        new_aggs = tuple(
            dataclasses.replace(
                a,
                func=a.func.replace("distinct_", ""),
                input=final_input(a, ch),
                input2=None,
            )
            for a, ch in zip(distinct_specs, inner_names)
        )
        return (
            N.Aggregate(pre, new_groups, tuple(group_names), new_aggs),
            True,
        )

    def _build_mixed_distinct_aggregate(
        self, child, group_exprs, group_names, aggs, distinct_specs
    ):
        """Mixed plain + DISTINCT aggregates: pre-aggregate grouped by
        (group keys, distinct argument) with decomposable partials, then
        finalize grouped by the group keys alone. Stage-2 counting of the
        distinct-argument channel IS the distinct count (reference:
        OptimizeMixedDistinctAggregations)."""
        plain_specs = [a for a in aggs if not a.func.startswith("distinct_")]
        mergeable = {"sum", "count", "count_star", "min", "max", "avg"}
        if any(a.func not in mergeable for a in plain_specs):
            raise PlanningError(
                "mixing DISTINCT with non-decomposable aggregates "
                "(checksum/min_by/...) is not supported"
            )
        darg = distinct_specs[0].input
        dch = self.channel("darg")
        # avg decomposes into (sum, count) partials merged by sum, divided
        # in a final projection — decimal divide rounds HALF_UP at the
        # output scale, identical to the engine's avg finalization
        s1_aggs: List[AggSpec] = []
        parts: Dict[str, tuple] = {}  # plain name -> partial spec(s)
        for a in plain_specs:
            if a.func == "avg":
                sum_t = AggSpec.infer_output_type("sum", a.input.type)
                s = AggSpec("sum", a.input, self.channel("part_sum"), sum_t)
                c = AggSpec("count", a.input, self.channel("part_cnt"), T.BIGINT)
                s1_aggs.extend((s, c))
                parts[a.name] = ("avg", s, c)
            else:
                p = AggSpec(
                    a.func, a.input, self.channel(f"part_{a.func}"),
                    a.output_type,
                )
                s1_aggs.append(p)
                parts[a.name] = ("simple", p)
        stage1 = N.Aggregate(
            child,
            tuple(group_exprs) + (darg,),
            tuple(group_names) + (dch,),
            tuple(s1_aggs),
        )
        s2_groups = tuple(
            ir.ColumnRef(n, e.type) for n, e in zip(group_names, group_exprs)
        )
        merge_func = {
            "sum": "sum", "count": "sum", "count_star": "sum",
            "min": "min", "max": "max",
        }
        s2_aggs = []
        for a in plain_specs:
            kind = parts[a.name]
            if kind[0] == "avg":
                _, s, c = kind
                s2_aggs.append(
                    AggSpec(
                        "sum", ir.ColumnRef(s.name, s.output_type),
                        s.name, s.output_type,
                    )
                )
                s2_aggs.append(
                    AggSpec(
                        "sum", ir.ColumnRef(c.name, T.BIGINT),
                        c.name, T.BIGINT,
                    )
                )
            else:
                p = kind[1]
                s2_aggs.append(
                    AggSpec(
                        merge_func[a.func],
                        ir.ColumnRef(p.name, p.output_type),
                        a.name,
                        a.output_type,
                    )
                )
        for a in distinct_specs:
            s2_aggs.append(
                dataclasses.replace(
                    a,
                    func=a.func.replace("distinct_", ""),
                    input=ir.ColumnRef(dch, darg.type),
                )
            )
        node = N.Aggregate(
            stage1, s2_groups, tuple(group_names), tuple(s2_aggs)
        )
        # final projection: original output order; avg = sum/count; empty
        # global input leaves merged counts NULL where SQL answers 0
        count_names = {
            a.name for a in plain_specs if a.func in ("count", "count_star")
        }
        avg_names = {a.name for a in plain_specs if a.func == "avg"}
        if count_names or avg_names:
            exprs, names = [], []
            for nm, e in zip(group_names, group_exprs):
                exprs.append(ir.ColumnRef(nm, e.type))
                names.append(nm)
            for a in aggs:
                if a.name in avg_names:
                    _, s, c = parts[a.name]
                    exprs.append(
                        ir.Call(
                            "divide",
                            (
                                ir.ColumnRef(s.name, s.output_type),
                                ir.ColumnRef(c.name, T.BIGINT),
                            ),
                            a.output_type,
                        )
                    )
                elif a.name in count_names:
                    ref = ir.ColumnRef(a.name, a.output_type)
                    exprs.append(
                        ir.Call(
                            "coalesce",
                            (ref, ir.Literal(0, a.output_type)),
                            a.output_type,
                        )
                    )
                else:
                    exprs.append(ir.ColumnRef(a.name, a.output_type))
                names.append(a.name)
            node = N.Project(node, tuple(exprs), tuple(names))
        return node, True


def _field_for_channel(scope: Scope, channel: str) -> Optional[FieldRef]:
    for f in scope.fields:
        if f.channel == channel:
            return f
    return None


def _derive_name(expr: t.Node) -> Optional[str]:
    if isinstance(expr, t.Identifier):
        return expr.name
    if isinstance(expr, t.FunctionCall):
        return expr.name
    return None


def _collect_windows(expr: t.Node, out: List[t.FunctionCall]):
    """Find window function calls (FunctionCall with an OVER clause)."""
    if isinstance(expr, t.FunctionCall) and expr.window is not None:
        out.append(expr)
        return
    if isinstance(expr, (t.ScalarSubquery, t.InSubquery, t.Exists)):
        return
    for f in dataclasses.fields(expr):
        v = getattr(expr, f.name)
        if isinstance(v, t.Node):
            _collect_windows(v, out)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, t.Node):
                    _collect_windows(x, out)


def _contains_subquery_pred(expr: t.Node) -> bool:
    """True if expr contains an EXISTS / IN-subquery predicate (these can only
    be planned as top-level WHERE conjuncts — they mutate the plan with a
    SemiJoin). Does not descend into nested subqueries' own bodies."""
    if isinstance(expr, (t.Exists, t.InSubquery)):
        return True
    if isinstance(expr, t.ScalarSubquery):
        return False
    for f in dataclasses.fields(expr):
        v = getattr(expr, f.name)
        if isinstance(v, t.Node) and _contains_subquery_pred(v):
            return True
        if isinstance(v, tuple):
            for x in v:
                if isinstance(x, t.Node) and _contains_subquery_pred(x):
                    return True
                if isinstance(x, tuple):  # e.g. Case.whens: ((cond, val), ...)
                    for y in x:
                        if isinstance(y, t.Node) and _contains_subquery_pred(y):
                            return True
    return False


def _collect_grouping_calls(expr: t.Node, out: List[t.FunctionCall]):
    """Find grouping(...) calls (grouping-sets level indicators)."""
    if isinstance(expr, t.FunctionCall) and expr.name == "grouping":
        out.append(expr)
        return
    if isinstance(expr, (t.ScalarSubquery, t.InSubquery, t.Exists)):
        return
    for f in dataclasses.fields(expr):
        v = getattr(expr, f.name)
        if isinstance(v, t.Node):
            _collect_grouping_calls(v, out)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, t.Node):
                    _collect_grouping_calls(x, out)


def _ast_replace(node: t.Node, mapping: Dict[t.Node, t.Node]) -> t.Node:
    """Structurally replace subtrees (equality-keyed) in a frozen AST; does
    not descend into nested subqueries."""
    if node in mapping:
        return mapping[node]
    if isinstance(node, (t.ScalarSubquery, t.InSubquery, t.Exists)):
        return node
    if not dataclasses.is_dataclass(node):
        return node
    changes = {}
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, t.Node):
            nv = _ast_replace(v, mapping)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple):
            nv = _tuple_replace(v, mapping)
            if nv != v:
                changes[f.name] = nv
    return dataclasses.replace(node, **changes) if changes else node


def _tuple_replace(v: tuple, mapping) -> tuple:
    out = []
    for x in v:
        if isinstance(x, t.Node):
            out.append(_ast_replace(x, mapping))
        elif isinstance(x, tuple):
            out.append(_tuple_replace(x, mapping))
        else:
            out.append(x)
    return tuple(out)


def _collect_aggregates(expr: t.Node, out: List[t.FunctionCall]):
    """Find aggregate function calls (not descending into subqueries)."""
    if isinstance(expr, t.FunctionCall):
        if (
            expr.name in AGG_FUNCS or expr.name in REWRITE_AGG_FUNCS
        ) and expr.window is None:
            out.append(expr)
            return  # aggregates cannot nest
    if isinstance(expr, (t.ScalarSubquery, t.InSubquery, t.Exists)):
        return
    for f in dataclasses.fields(expr):
        v = getattr(expr, f.name)
        if isinstance(v, t.Node):
            _collect_aggregates(v, out)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, t.Node):
                    _collect_aggregates(x, out)
                elif isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, t.Node):
                            _collect_aggregates(y, out)


def collect_channels(e: ir.RowExpression, out: set):
    if isinstance(e, ir.ColumnRef):
        out.add(e.name)
    elif isinstance(e, ir.Call):
        for a in e.args:
            collect_channels(a, out)


def _contains_subquery(expr: t.Node) -> bool:
    if isinstance(expr, (t.ScalarSubquery, t.InSubquery, t.Exists)):
        return True
    for f in dataclasses.fields(expr):
        v = getattr(expr, f.name)
        if isinstance(v, t.Node) and not isinstance(v, (t.Query,)):
            if _contains_subquery(v):
                return True
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, t.Node) and not isinstance(x, t.Query):
                    if _contains_subquery(x):
                        return True
                elif isinstance(x, tuple):
                    for y in x:
                        if (
                            isinstance(y, t.Node)
                            and not isinstance(y, t.Query)
                            and _contains_subquery(y)
                        ):
                            return True
    return False


def split_conjuncts(expr: Optional[t.Node]) -> List[t.Node]:
    if expr is None:
        return []
    if isinstance(expr, t.LogicalOp) and expr.op == "and":
        out = []
        for x in expr.terms:
            out.extend(split_conjuncts(x))
        return out
    return [expr]


def extract_common_or_conjuncts(conjuncts: List[t.Node]) -> List[t.Node]:
    """Factor conjuncts common to every OR disjunct up to the top level
    (reference ExtractCommonPredicatesExpressionRewriter): Q19's
    `(p=l and A...) or (p=l and B...)` exposes the p=l join key."""
    out: List[t.Node] = []
    for c in conjuncts:
        if isinstance(c, t.LogicalOp) and c.op == "or":
            dis = [split_conjuncts(d) for d in c.terms]
            common = [x for x in dis[0] if all(x in d for d in dis[1:])]
            if common:
                out.extend(common)
                rest_terms = []
                degenerate = False
                for d in dis:
                    rem = [x for x in d if x not in common]
                    if not rem:
                        degenerate = True
                        break
                    rest_terms.append(
                        rem[0] if len(rem) == 1 else t.LogicalOp("and", tuple(rem))
                    )
                if not degenerate:
                    out.append(t.LogicalOp("or", tuple(rest_terms)))
                continue
        out.append(c)
    return out


# ---------------------------------------------------------------------------
# FROM clause: relation pool + join graph assembly
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PoolItem:
    plan: RelationPlan
    channels: set
    estimate: float
    stats: object = None  # plan.stats.PlanStats


class FromPlanner:
    """Flattens the FROM clause into a relation pool + join edges, classifies
    WHERE conjuncts, and assembles a cost-based greedy join order: the next
    relation is the one whose join with the current tree has the smallest
    ESTIMATED OUTPUT (reference ReorderJoins + JoinStatsRule), with the
    smaller estimated side as the build side. Estimates come from the
    stats framework (plan/stats.py: connector NDV/min/max/null-fraction
    derived through filters)."""

    def __init__(self, planner: Planner, outer, ctes):
        self.p = planner
        self.outer = outer
        self.ctes = ctes
        self.pool: List[PoolItem] = []
        self.subquery_conjuncts: List[t.Node] = []
        self._pending_on: List[t.Node] = []
        self.unnests: List[t.Unnest] = []

    def add_relation(self, rel: t.Node):
        if isinstance(rel, t.Unnest):
            # lateral: applies to the joined FROM result (assemble())
            self.unnests.append(rel)
            return
        if isinstance(rel, t.Join) and rel.kind in ("cross", "inner"):
            self.add_relation(rel.left)
            self.add_relation(rel.right)
            if rel.condition is not None:
                self._pending_on.extend(split_conjuncts(rel.condition))
            if rel.using:
                raise PlanningError("USING joins not yet supported")
            return
        if isinstance(rel, t.Join):
            item = self._plan_outer_join(rel)
            self.pool.append(item)
            return
        rp = self.p.plan_relation(rel, self.outer, self.ctes)
        st = self._stats(rp.node)
        self.pool.append(
            PoolItem(
                rp, {f.channel for f in rp.scope.fields}, st.rows, st
            )
        )

    def _plan_join_side(self, rel) -> RelationPlan:
        """One side of an outer join may itself be a join tree (e.g.
        `a join b on .. left outer join c on ..` associates the whole
        inner-join chain as the outer join's left side): plan inner/cross
        chains through a nested FromPlanner (keeping the greedy join
        order), and nested outer joins recursively."""
        if isinstance(rel, t.Join) and rel.kind in ("cross", "inner"):
            sub = FromPlanner(self.p, self.outer, self.ctes)
            sub.add_relation(rel)
            node, scope = sub.assemble(None)
            # assemble() is what classifies ON conjuncts: only AFTER it can
            # we see subquery conjuncts, which nothing would consume here
            if sub.subquery_conjuncts or sub.unnests:
                raise PlanningError(
                    "subqueries/UNNEST inside a joined ON-side not supported"
                )
            return RelationPlan(node, scope)
        if isinstance(rel, t.Join):
            return self._plan_outer_join(rel).plan
        return self.p.plan_relation(rel, self.outer, self.ctes)

    def _plan_outer_join(self, rel: t.Join) -> PoolItem:
        kind = rel.kind
        if kind == "right":
            rel = t.Join("left", rel.right, rel.left, rel.condition, rel.using)
            kind = "left"
        left = self._plan_join_side(rel.left)
        right = self._plan_join_side(rel.right)
        combined = Scope(left.scope.fields + right.scope.fields)
        ctx = SelectContext(self.p, [combined], self.outer, self.ctes, None)
        left_chs = {f.channel for f in left.scope.fields}
        right_chs = {f.channel for f in right.scope.fields}
        lkeys, rkeys, residual = [], [], []
        rfilters = []
        for conj in split_conjuncts(rel.condition):
            e = ctx.translate(conj)
            refs: set = set()
            collect_channels(e, refs)
            if (
                isinstance(e, ir.Call)
                and e.name == "eq"
                and refs & left_chs
                and refs & right_chs
            ):
                a, b = e.args
                ra: set = set()
                collect_channels(a, ra)
                rb: set = set()
                collect_channels(b, rb)
                if ra <= left_chs and rb <= right_chs:
                    lkeys.append(a)
                    rkeys.append(b)
                    continue
                if rb <= left_chs and ra <= right_chs:
                    lkeys.append(b)
                    rkeys.append(a)
                    continue
            if kind == "left" and refs <= right_chs:
                rfilters.append(e)  # safe to push below a left join
            else:
                # full-outer: one-sided ON filters stay residual (pushing
                # them below would drop the side's unmatched rows)
                residual.append(e)
        rnode = right.node
        if rfilters:
            rnode = N.Filter(rnode, ir.and_(*rfilters) if len(rfilters) > 1 else rfilters[0])
        if not lkeys:
            raise PlanningError("outer join requires at least one equi condition")
        res = None
        if residual:
            res = ir.and_(*residual) if len(residual) > 1 else residual[0]
        unique = _build_side_unique(rnode, rkeys, self.p.catalog)
        node = N.Join(
            kind, left.node, rnode, tuple(lkeys), tuple(rkeys), res, unique
        )
        rp = RelationPlan(node, combined)
        st = self._stats(node)
        return PoolItem(rp, left_chs | right_chs, st.rows, st)

    def _stats(self, node: N.PlanNode):
        from ..plan.stats import derive

        return derive(node, self.p.catalog)

    def assemble(self, where: Optional[t.Node]) -> Tuple[N.PlanNode, Scope]:
        if not self.pool:
            # FROM-less SELECT (reference: Query without QuerySpecification
            # relation plans over a values row) and UNNEST of constants
            # both expand over a one-row base
            leaf = N.SingleRow(self.p.channel("singlerow"))
            self.pool.append(
                PoolItem(
                    RelationPlan(leaf, Scope([])), set(), 1.0
                )
            )

        combined = Scope([f for it in self.pool for f in it.plan.scope.fields])
        combined_chs = {f.channel for f in combined.fields}
        ctx = SelectContext(self.p, [combined], self.outer, self.ctes, None)

        # plan UNNEST relations against the joined FROM scope; their
        # output fields join the visible scope, and conjuncts referencing
        # them apply after the expansion
        unnest_specs = []  # (array_exprs, elem_channels, ord_channel)
        unnest_chs: set = set()
        unnest_fields: List[FieldRef] = []
        for un in self.unnests:
            exprs = tuple(ctx.translate(a) for a in un.exprs)
            for e in exprs:
                if not isinstance(e.type, T.ArrayType):
                    raise PlanningError(
                        f"UNNEST argument must be an array, got {e.type}"
                    )
            n_cols = len(exprs) + (1 if un.ordinality else 0)
            if un.column_aliases and len(un.column_aliases) != n_cols:
                raise PlanningError(
                    f"UNNEST alias has {len(un.column_aliases)} columns, "
                    f"expected {n_cols}"
                )
            names = list(un.column_aliases) or [
                f"_unnest{i}" for i in range(n_cols)
            ]
            chans = tuple(self.p.channel(nm) for nm in names[: len(exprs)])
            ord_ch = None
            if un.ordinality:
                ord_ch = self.p.channel(names[-1])
            unnest_specs.append((exprs, chans, ord_ch))
            alias = un.alias
            for nm, ch, e in zip(names, chans, exprs):
                unnest_fields.append(
                    FieldRef(alias, nm, ch, e.type.element)
                )
                unnest_chs.add(ch)
            if ord_ch is not None:
                unnest_fields.append(
                    FieldRef(alias, names[-1], ord_ch, T.BIGINT)
                )
                unnest_chs.add(ord_ch)
        post_unnest_filters: List[ir.RowExpression] = []
        if unnest_fields:
            combined = Scope(list(combined.fields) + unnest_fields)
            combined_chs = combined_chs | unnest_chs
            ctx = SelectContext(self.p, [combined], self.outer, self.ctes, None)

        conjuncts = extract_common_or_conjuncts(
            self._pending_on + split_conjuncts(where)
        )
        edges: List[Tuple[int, int, ir.RowExpression, ir.RowExpression]] = []
        residuals: List[Tuple[set, ir.RowExpression]] = []
        for conj in conjuncts:
            if _contains_subquery(conj):
                self.subquery_conjuncts.append(conj)
                continue
            e = ctx.translate(conj)
            refs: set = set()
            collect_channels(e, refs)
            outer_chs = refs - combined_chs
            if outer_chs:
                # correlated conjunct: record on the enclosing subquery
                # collector and keep it OUT of the local plan
                self._record_correlation(e, refs, combined_chs)
                continue
            if refs & unnest_chs:
                post_unnest_filters.append(e)
                continue
            owners = {
                i for i, it in enumerate(self.pool) if refs & it.channels
            }
            if len(owners) == 1:
                (i,) = owners
                it = self.pool[i]
                it.plan = RelationPlan(
                    N.Filter(it.plan.node, e), it.plan.scope
                )
                it.stats = self._stats(it.plan.node)
                it.estimate = it.stats.rows
                continue
            if len(owners) == 2 and isinstance(e, ir.Call) and e.name == "eq":
                a, b = e.args
                ra: set = set()
                collect_channels(a, ra)
                rb: set = set()
                collect_channels(b, rb)
                ia = {i for i, it in enumerate(self.pool) if ra & it.channels}
                ib = {i for i, it in enumerate(self.pool) if rb & it.channels}
                if len(ia) == 1 and len(ib) == 1 and ia != ib:
                    edges.append((next(iter(ia)), next(iter(ib)), a, b))
                    continue
            residuals.append((owners, e))

        def finish(plan: N.PlanNode) -> Tuple[N.PlanNode, Scope]:
            for exprs, chans, ord_ch in unnest_specs:
                plan = N.Unnest(plan, exprs, chans, ord_ch)
            for e in post_unnest_filters:
                plan = N.Filter(plan, e)
            return plan, combined

        # greedy assembly with COSTED ALTERNATIVES: build a complete greedy
        # order from each of the two smallest-estimate start relations,
        # cost each full order as the sum of estimated intermediate rows
        # (the dominant exchange+build volume on TPU), keep the cheaper —
        # the reference compares alternative join orders with
        # CostComparator inside ReorderJoins (sql/planner/iterative/rule/
        # ReorderJoins.java); two greedy seeds is the bounded analog.
        n_items = len(self.pool)
        if n_items == 1:
            plan = self.pool[0].plan.node
            for owners, e in residuals:
                plan = N.Filter(plan, e)
            return finish(plan)

        from ..plan.stats import join_output_rows

        def build_order(start: int) -> Tuple[N.PlanNode, float]:
            remaining = set(range(n_items))
            joined = {start}
            remaining.discard(start)
            plan = self.pool[start].plan.node
            cur_stats = self.pool[start].stats
            applied_res: set = set()
            cost = 0.0

            def edge_keys(nxt: int):
                """(tree-side, candidate-side) key expression lists."""
                lkeys, rkeys = [], []
                for (i, j, a, b) in edges:
                    if i in joined and j == nxt:
                        lkeys.append(a)
                        rkeys.append(b)
                    elif j in joined and i == nxt:
                        lkeys.append(b)
                        rkeys.append(a)
                return lkeys, rkeys

            while remaining:
                # candidates connected by an edge; pick the one whose join
                # with the current tree has the smallest estimated OUTPUT
                # (reference ReorderJoins cost comparison)
                cand = set()
                for (i, j, _, _) in edges:
                    if i in joined and j in remaining:
                        cand.add(j)
                    if j in joined and i in remaining:
                        cand.add(i)

                def join_est(c: int) -> float:
                    lk, rk = edge_keys(c)
                    return join_output_rows(
                        cur_stats, self.pool[c].stats, lk, rk, "inner"
                    )

                if cand:
                    nxt = min(
                        cand,
                        key=lambda i: (join_est(i), self.pool[i].estimate),
                    )
                else:
                    nxt = min(remaining, key=lambda i: self.pool[i].estimate)
                lkeys, rkeys = edge_keys(nxt)
                rnode = self.pool[nxt].plan.node
                # build side = smaller estimated side (reference: CBO flips
                # the join so the hash build is the cheaper input), except
                # keep a UNIQUE build side — the n:1 fast path beats a
                # smaller build
                tree_rows = cur_stats.rows if cur_stats else 1e9
                cand_rows = self.pool[nxt].estimate
                unique_r = _build_side_unique(rnode, rkeys, self.p.catalog)
                if not unique_r and cand_rows > tree_rows and lkeys:
                    unique_l = _build_side_unique(plan, lkeys, self.p.catalog)
                    plan = N.Join(
                        "inner",
                        rnode,
                        plan,
                        tuple(rkeys),
                        tuple(lkeys),
                        None,
                        unique_l,
                    )
                else:
                    plan = N.Join(
                        "inner",
                        plan,
                        rnode,
                        tuple(lkeys),
                        tuple(rkeys),
                        None,
                        unique_r,
                    )
                joined.add(nxt)
                remaining.discard(nxt)
                cur_stats = self._stats(plan)
                cost += cur_stats.rows if cur_stats else 0.0
                # apply residuals that became fully available
                for k, (owners, e) in enumerate(residuals):
                    if k in applied_res:
                        continue
                    if owners <= joined:
                        plan = N.Filter(plan, e)
                        applied_res.add(k)
            for k, (owners, e) in enumerate(residuals):
                if k not in applied_res:
                    plan = N.Filter(plan, e)
            return plan, cost

        by_size = sorted(range(n_items), key=lambda i: self.pool[i].estimate)
        starts = by_size[: (2 if n_items > 2 else 1)]
        best_plan: Optional[N.PlanNode] = None
        best_cost = float("inf")
        for s in starts:
            cand_plan, cand_cost = build_order(s)
            if cand_cost < best_cost:
                best_plan, best_cost = cand_plan, cand_cost
        return finish(best_plan)

    def _record_correlation(self, e: ir.RowExpression, refs: set, inner_chs: set):
        """Route a conjunct referencing outer channels to the enclosing
        CorrelationCollector: equality pairs become decorrelation keys,
        anything else a residual (used by EXISTS semi-joins)."""
        coll = self.outer
        if not isinstance(coll, CorrelationCollector):
            raise PlanningError(
                "correlated reference not supported in this context"
            )
        if isinstance(e, ir.Call) and e.name == "eq":
            a, b = e.args
            ra: set = set()
            collect_channels(a, ra)
            rb: set = set()
            collect_channels(b, rb)
            if ra <= inner_chs and not (rb & inner_chs) and isinstance(a, ir.ColumnRef):
                coll.pairs.append((a, b))
                return
            if rb <= inner_chs and not (ra & inner_chs) and isinstance(b, ir.ColumnRef):
                coll.pairs.append((b, a))
                return
        coll.residuals.append(e)


def _selectivity(e: ir.RowExpression) -> float:
    if isinstance(e, ir.Call):
        if e.name == "eq":
            return 0.05
        if e.name in ("lt", "le", "gt", "ge", "between"):
            return 0.35
        if e.name == "like":
            return 0.1
        if e.name == "in":
            return 0.2
        if e.name == "and":
            s = 1.0
            for a in e.args:
                s *= _selectivity(a)
            return s
    return 0.5


def _scan_under_filters(node: N.PlanNode) -> Optional[N.TableScan]:
    while isinstance(node, N.Filter):
        node = node.child
    return node if isinstance(node, N.TableScan) else None


def _build_side_unique(node: N.PlanNode, keys, catalog: Catalog) -> bool:
    """True if the join keys form a unique key of the build side."""
    scan = _scan_under_filters(node)
    if scan is None:
        if isinstance(node, N.Aggregate):
            # grouped output is unique on its group channels
            key_chs = {k.name for k in keys if isinstance(k, ir.ColumnRef)}
            return set(node.group_names) <= key_chs and len(keys) == len(
                node.group_names
            )
        return False
    cols = []
    for k in keys:
        if not isinstance(k, ir.ColumnRef):
            return False
        for ch, src, _ in scan.columns:
            if ch == k.name:
                cols.append(src)
                break
    key_set = set(cols)
    for uniq in catalog.unique_columns(scan.table):
        if set(uniq) <= key_set:
            return True
    return False


# ---------------------------------------------------------------------------
# expression translation
# ---------------------------------------------------------------------------


class PlanHolder:
    def __init__(self, plan: N.PlanNode):
        self.plan = plan


class SelectContext:
    """Translates AST expressions to RowExpressions against a scope chain.
    Mutates `holder.plan` when subqueries require joins/applies. Records
    outer-scope references for correlation detection."""

    def __init__(
        self,
        planner: Planner,
        scopes: List[Scope],
        outer: Optional["SelectContext"],
        ctes,
        holder: Optional[PlanHolder],
        agg_map: Optional[Dict] = None,
    ):
        self.p = planner
        self.scopes = scopes
        self.outer = outer
        self.ctes = ctes
        self.holder = holder
        self.agg_map = agg_map or {}
        self.outer_refs: List[ir.ColumnRef] = []

    # -- scope chain resolution --
    def resolve(self, parts) -> Tuple[FieldRef, bool]:
        for s in self.scopes:
            f = s.resolve(parts)
            if f is not None:
                return f, False
        if self.outer is not None:
            f, _ = self.outer.resolve(parts)
            return f, True
        raise PlanningError(f"cannot resolve column {'.'.join(parts)!r}")

    def translate(self, ast: t.Node) -> ir.RowExpression:
        # EXISTS/IN predicates plan as SemiJoins and are only legal when the
        # whole translated expression is one WHERE/HAVING conjunct: the root
        # node itself, or directly under a root-level NOT.
        self._conjunct_root = ast
        e = self._tr(ast)
        return e

    def _tr(self, ast: t.Node) -> ir.RowExpression:
        if ast in self.agg_map:
            v = self.agg_map[ast]
            if isinstance(v, ir.RowExpression):
                return v  # composite rewrite (stddev & co) over agg channels
            ch, typ = v
            return ir.ColumnRef(ch, typ)
        gnm = getattr(self, "group_null_map", None)
        if gnm is not None:
            ty = gnm.get(ast)
            if ty is not None:  # column not in this grouping set
                return ir.Literal(None, ty)
        gm = getattr(self, "group_map", None)
        if gm is not None and not isinstance(ast, t.Identifier):
            hit = gm.get(ast)
            if hit is not None:
                ch, typ = hit
                return ir.ColumnRef(ch, typ)
        if isinstance(ast, t.Identifier):
            f, is_outer = self.resolve(ast.parts)
            ref = ir.ColumnRef(f.channel, f.type)
            if is_outer:
                self.outer_refs.append(ref)
            return ref
        if isinstance(ast, t.BoundParameter):
            # EXECUTE parameter bound as a typed constant; tag the literal
            # with its index so plan skeletons rebind (exec/qcache.py). A
            # parameter planning to anything but a plain literal is left
            # untagged — the skeleton coverage check then disqualifies it.
            inner = self._tr(ast.inner)
            if isinstance(inner, ir.Literal) and inner.param is None:
                import dataclasses as _dc

                return _dc.replace(inner, param=ast.index)
            return inner
        if isinstance(ast, t.NumberLiteral):
            return _number_literal(ast.text)
        if isinstance(ast, t.StringLiteral):
            return ir.Literal(ast.value, T.VARCHAR)
        if isinstance(ast, t.BooleanLiteral):
            return ir.Literal(ast.value, T.BOOLEAN)
        if isinstance(ast, t.NullLiteral):
            return ir.Literal(None, T.UNKNOWN)
        if isinstance(ast, t.DateLiteral):
            return ir.Literal(ast.value, T.DATE)
        if isinstance(ast, t.TimestampLiteral):
            return ir.Literal(
                _parse_timestamp_literal(ast.value), T.TIMESTAMP
            )
        if isinstance(ast, t.IntervalLiteral):
            n = int(ast.value) * (-1 if ast.negative else 1)
            if ast.unit in ("year", "month"):
                months = n * (12 if ast.unit == "year" else 1)
                return ir.Literal(months, T.INTERVAL_YEAR_MONTH)
            if ast.unit == "day":
                return ir.Literal(n, T.INTERVAL_DAY)
            raise PlanningError(f"interval unit {ast.unit} not supported")
        if isinstance(ast, t.UnaryOp):
            v = self._tr(ast.operand)
            if ast.op == "-":
                if isinstance(v, ir.Literal) and isinstance(
                    v.value, (int, float)
                ) and v.param is None:
                    # fold so literal-argument functions see -n as a
                    # literal (param-tagged literals stay symbolic: the
                    # fold would detach the value from its rebind tag)
                    return ir.Literal(-v.value, v.type)
                return ir.Call("negate", (v,), v.type)
            return v
        if isinstance(ast, t.BinaryOp):
            if isinstance(ast.right, t.ScalarSubquery):
                right = self._scalar_subquery(ast.right)
            else:
                right = self._tr(ast.right)
            if isinstance(ast.left, t.ScalarSubquery):
                left = self._scalar_subquery(ast.left)
            else:
                left = self._tr(ast.left)
            fn = _BINOP_FN[ast.op]
            if ast.op in _CMP_OPS:
                return ir.Call(fn, (left, right), T.BOOLEAN)
            if ast.op == "||" and (
                isinstance(left.type, T.ArrayType)
                or isinstance(right.type, T.ArrayType)
            ):
                # ARRAY || ARRAY, elem || ARRAY, ARRAY || elem (reference
                # ArrayConcatFunction + the || operator on arrays)
                def as_array(e):
                    if isinstance(e.type, T.ArrayType):
                        return e
                    return ir.Call(
                        "array_constructor", (e,), T.ArrayType(e.type)
                    )

                la, ra = as_array(left), as_array(right)
                et = T.common_super_type(
                    la.type.element, ra.type.element
                )
                return ir.Call(
                    "array_concat", (la, ra), T.ArrayType(et)
                )
            return ir.Call(
                fn, (left, right), _infer(fn, (left.type, right.type))
            )
        if isinstance(ast, t.LogicalOp):
            # EXISTS/IN translate by mutating the plan with a SemiJoin and
            # returning None — only valid as top-level WHERE conjuncts.
            # Under OR, a direct EXISTS/IN term instead plans a MARK
            # semi-join (no filtering; a boolean membership column replaces
            # the predicate — reference semiJoinOutput). NOT IN stays
            # unsupported there: its NULL semantics differ from NOT mark.
            if ast.op == "or" and any(
                _contains_subquery_pred(x) for x in ast.terms
            ):
                marked = []
                for x in ast.terms:
                    if isinstance(x, t.Exists):
                        marked.append(self._subquery_mark(x, negate=False))
                    elif isinstance(x, t.InSubquery) and not getattr(
                        x, "negated", False
                    ):
                        marked.append(self._subquery_mark(x, negate=False))
                    elif isinstance(x, t.NotOp) and isinstance(
                        x.operand, t.Exists
                    ):
                        marked.append(
                            self._subquery_mark(x.operand, negate=True)
                        )
                    elif _contains_subquery_pred(x):
                        raise PlanningError(
                            "subquery under OR is only supported as a "
                            "direct EXISTS / IN / NOT EXISTS term"
                        )
                    else:
                        marked.append(self._tr(x))
                return ir.Call("or", tuple(marked), T.BOOLEAN)
            terms = tuple(self._tr(x) for x in ast.terms)
            if any(x is None for x in terms):
                raise PlanningError(
                    "EXISTS/IN subquery in this position is not supported"
                )
            return ir.Call(ast.op, terms, T.BOOLEAN)
        if isinstance(ast, t.NotOp):
            if isinstance(ast.operand, t.Exists):
                return self._exists(ast.operand, negate=True)
            if isinstance(ast.operand, t.InSubquery):
                return self._in_subquery(ast.operand, negate=True)
            if _contains_subquery_pred(ast.operand):
                raise PlanningError(
                    "EXISTS/IN subquery under NOT is only supported directly "
                    "(NOT EXISTS / NOT IN)"
                )
            return ir.not_(self._tr(ast.operand))
        if isinstance(ast, t.IsNull):
            inner = self._tr(ast.operand)
            e = ir.is_null(inner)
            return ir.not_(e) if ast.negated else e
        if isinstance(ast, t.Between):
            e = ir.between(self._tr(ast.value), self._tr(ast.low), self._tr(ast.high))
            return ir.not_(e) if ast.negated else e
        if isinstance(ast, t.InList):
            v = self._tr(ast.value)
            opts = tuple(self._tr(o) for o in ast.options)
            e = ir.Call("in", (v,) + opts, T.BOOLEAN)
            return ir.not_(e) if ast.negated else e
        if isinstance(ast, t.Like):
            v = self._tr(ast.value)
            pat = self._tr(ast.pattern)
            args = (v, pat)
            if ast.escape is not None:
                args = args + (self._tr(ast.escape),)
            e = ir.Call("like", args, T.BOOLEAN)
            return ir.not_(e) if ast.negated else e
        if isinstance(ast, t.Case):
            return self._case(ast)
        if isinstance(ast, t.Cast):
            v = self._tr(ast.operand)
            to = T.parse_type(ast.type_name)
            if ast.try_cast and to != v.type:
                # NULL instead of error on conversion failure — its own
                # special form so the kernel knows to map bad entries to
                # NULL rather than raise (compiler._cast_varchar_entries)
                return ir.Call("try_cast", (v,), to)
            return ir.cast(v, to)
        if isinstance(ast, t.Extract):
            v = self._tr(ast.operand)
            fields = (
                "year", "month", "day", "quarter", "hour", "minute",
                "second", "week", "day_of_week", "dow", "day_of_year",
                "doy", "year_of_week", "yow",
            )
            if ast.field not in fields:
                raise PlanningError(f"extract({ast.field}) not supported")
            return ir.Call(ast.field, (v,), T.BIGINT)
        if isinstance(ast, t.ArrayLiteral):
            if not ast.items:
                raise PlanningError("empty ARRAY[] requires a typed context")
            items = [self._tr(x) for x in ast.items]
            ct = items[0].type
            for x in items[1:]:
                ct = T.common_super_type(ct, x.type)
            items = [
                x if x.type == ct else ir.cast(x, ct) for x in items
            ]
            return ir.Call(
                "array_constructor", tuple(items), T.ArrayType(ct)
            )
        if isinstance(ast, t.FunctionCall):
            return self._function(ast)
        if isinstance(ast, t.ScalarSubquery):
            return self._scalar_subquery(ast)
        if isinstance(ast, t.Exists):
            return self._exists(ast, negate=False)
        if isinstance(ast, t.InSubquery):
            return self._in_subquery(ast, negate=ast.negated)
        raise PlanningError(f"unsupported expression {type(ast).__name__}")

    def _case(self, ast: t.Case) -> ir.RowExpression:
        whens = []
        for cond, val in ast.whens:
            if ast.operand is not None:
                c = ir.Call(
                    "eq", (self._tr(ast.operand), self._tr(cond)), T.BOOLEAN
                )
            else:
                c = self._tr(cond)
            whens.append((c, self._tr(val)))
        else_ = self._tr(ast.else_) if ast.else_ is not None else ir.Literal(None, T.UNKNOWN)
        out_t = else_.type
        for _, v in whens:
            out_t = T.common_super_type(out_t, v.type)
        args = []
        for c, v in whens:
            args += [c, v]
        args.append(else_)
        return ir.Call("case", tuple(args), out_t)

    def _translate_lambda(self, lam: t.LambdaExpr, param_types) -> ir.Lambda:
        """Bind lambda params as synthetic channels visible to the body
        (reference: LambdaExpression scoping in ExpressionAnalyzer)."""
        if len(lam.params) != len(param_types):
            raise PlanningError(
                f"lambda takes {len(lam.params)} parameters, "
                f"{len(param_types)} expected"
            )
        chans = tuple(self.p.channel(p) for p in lam.params)
        fields = [
            FieldRef(None, p, ch, ty)
            for p, ch, ty in zip(lam.params, chans, param_types)
        ]
        inner = SelectContext(
            self.p, [Scope(fields)] + list(self.scopes), self.outer,
            self.ctes, self.holder,
        )
        body = inner._tr(lam.body)
        return ir.Lambda(chans, body, tuple(param_types))

    def _lambda_function(self, ast: t.FunctionCall) -> ir.RowExpression:
        """Higher-order functions over arrays (reference
        operator/scalar/ArrayTransformFunction.java & friends)."""
        name = ast.name

        def elem(e: ir.RowExpression) -> T.Type:
            if not isinstance(e.type, T.ArrayType):
                raise PlanningError(f"{name} expects an array argument")
            return e.type.element

        if name in ("transform", "filter", "any_match", "all_match",
                    "none_match"):
            if len(ast.args) != 2 or not isinstance(ast.args[1], t.LambdaExpr):
                raise PlanningError(f"{name}(array, lambda) expected")
            arr = self._tr(ast.args[0])
            lam = self._translate_lambda(ast.args[1], (elem(arr),))
            if name == "transform":
                out = T.ArrayType(lam.body.type)
            elif name == "filter":
                out = arr.type
            else:
                out = T.BOOLEAN
            return ir.Call(name, (arr, lam), out)
        if name in ("map_filter", "transform_values", "transform_keys"):
            # map higher-order functions (reference MapFilterFunction,
            # MapTransformValuesFunction, MapTransformKeysFunction)
            if len(ast.args) != 2 or not isinstance(ast.args[1], t.LambdaExpr):
                raise PlanningError(f"{name}(map, (k, v) -> ...) expected")
            m = self._tr(ast.args[0])
            if not isinstance(m.type, T.MapType):
                raise PlanningError(f"{name} expects a map argument")
            lam = self._translate_lambda(
                ast.args[1], (m.type.key, m.type.value)
            )
            if name == "map_filter":
                if not isinstance(lam.body.type, T.BooleanType):
                    raise PlanningError(
                        "map_filter lambda must return boolean"
                    )
                out = m.type
            elif name == "transform_values":
                out = T.MapType(m.type.key, lam.body.type)
            else:
                out = T.MapType(lam.body.type, m.type.value)
            return ir.Call(name, (m, lam), out)
        if name == "zip_with":
            if len(ast.args) != 3 or not isinstance(ast.args[2], t.LambdaExpr):
                raise PlanningError("zip_with(array, array, lambda) expected")
            a = self._tr(ast.args[0])
            b = self._tr(ast.args[1])
            lam = self._translate_lambda(ast.args[2], (elem(a), elem(b)))
            return ir.Call(
                "zip_with", (a, b, lam), T.ArrayType(lam.body.type)
            )
        if name == "map_zip_with":
            # reference MapZipWithFunction: (K,V1), (K,V2), (K,V1,V2)->V3
            if len(ast.args) != 3 or not isinstance(ast.args[2], t.LambdaExpr):
                raise PlanningError(
                    "map_zip_with(map, map, (k, v1, v2) -> ...) expected"
                )
            a = self._tr(ast.args[0])
            b = self._tr(ast.args[1])
            if not isinstance(a.type, T.MapType) or not isinstance(
                b.type, T.MapType
            ):
                raise PlanningError("map_zip_with expects two map arguments")
            if a.type.key != b.type.key:
                raise PlanningError(
                    "map_zip_with maps must share the key type"
                )
            lam = self._translate_lambda(
                ast.args[2], (a.type.key, a.type.value, b.type.value)
            )
            return ir.Call(
                "map_zip_with",
                (a, b, lam),
                T.MapType(a.type.key, lam.body.type),
            )
        if name == "reduce":
            if len(ast.args) != 4 or not all(
                isinstance(a, t.LambdaExpr) for a in ast.args[2:]
            ):
                raise PlanningError(
                    "reduce(array, initialState, inputFn, outputFn) expected"
                )
            arr = self._tr(ast.args[0])
            init = self._tr(ast.args[1])
            input_fn = self._translate_lambda(
                ast.args[2], (init.type, elem(arr))
            )
            output_fn = self._translate_lambda(
                ast.args[3], (input_fn.body.type,)
            )
            return ir.Call(
                "reduce", (arr, init, input_fn, output_fn),
                output_fn.body.type,
            )
        raise PlanningError(f"unsupported higher-order function {name}")

    def _function(self, ast: t.FunctionCall) -> ir.RowExpression:
        name = ast.name
        if name == "concat" and len(ast.args) >= 2:
            args = [self._tr(a) for a in ast.args]
            if any(isinstance(a.type, T.ArrayType) for a in args):
                # variadic array concat folds left (ArrayConcatFunction)
                out = args[0]
                if not isinstance(out.type, T.ArrayType):
                    out = ir.Call(
                        "array_constructor", (out,), T.ArrayType(out.type)
                    )
                for nxt in args[1:]:
                    if not isinstance(nxt.type, T.ArrayType):
                        nxt = ir.Call(
                            "array_constructor", (nxt,),
                            T.ArrayType(nxt.type),
                        )
                    et = T.common_super_type(
                        out.type.element, nxt.type.element
                    )
                    out = ir.Call(
                        "array_concat", (out, nxt), T.ArrayType(et)
                    )
                return out
        if name == "try":
            # reference TryFunction: NULL instead of an error. Device
            # kernels never raise data-dependent errors (XLA semantics:
            # 1/0, overflow etc. produce values, not exceptions), so the
            # only TRY-visible failures are cast failures — route
            # try(cast(..)) onto try_cast; everything else passes through
            if len(ast.args) != 1:
                raise PlanningError("try() takes exactly one argument")
            arg = ast.args[0]
            if isinstance(arg, t.Cast):
                arg = dataclasses.replace(arg, try_cast=True)
            return self._tr(arg)
        if name in AGG_FUNCS or name in REWRITE_AGG_FUNCS:
            raise PlanningError(
                f"aggregate {name} in invalid context (window functions later)"
            )
        if name == "grouping":
            # bitmask of which arguments are aggregated away in this
            # grouping set (reference GroupingOperationFunction); plain
            # GROUP BY: every argument is grouped -> 0
            gctx = getattr(self, "grouping_ctx", None)
            n_args = len(ast.args)
            if gctx is None:
                raise PlanningError(
                    "grouping() is only allowed in the SELECT/HAVING of "
                    "an aggregation query"
                )
            full, cur = gctx
            value = 0
            for i, arg in enumerate(ast.args):
                if arg not in full:
                    raise PlanningError(
                        "grouping() arguments must be grouping columns"
                    )
                if arg not in cur:
                    value |= 1 << (n_args - 1 - i)
            return ir.Literal(value, T.BIGINT)
        if name in LAMBDA_FUNCS or any(
            isinstance(a, t.LambdaExpr) for a in ast.args
        ):
            return self._lambda_function(ast)
        args = tuple(self._tr(a) for a in ast.args)
        if name == "ceiling":
            name = "ceil"
        # special forms handled by the expression compiler, not the
        # registry (compiler.py SPECIAL_FORMS: coalesce/nullif/if)
        if name in ("coalesce", "if", "nullif"):
            return self._special_form(name, args)
        if name in ("e", "pi", "infinity", "nan") and not args:
            val = {
                "e": 2.718281828459045,
                "pi": 3.141592653589793,
                "infinity": float("inf"),
                "nan": float("nan"),
            }[name]
            return ir.Literal(val, T.DOUBLE)
        if name == "typeof" and len(args) == 1:
            return ir.Literal(str(args[0].type), T.VARCHAR)
        if name not in FUNCTIONS:
            raise PlanningError(f"unknown function {name!r}")
        return ir.Call(name, args, _infer(name, tuple(a.type for a in args)))

    def _special_form(self, name: str, args) -> ir.RowExpression:
        if name == "coalesce":
            if not args:
                raise PlanningError("coalesce requires arguments")
            out_t = args[0].type
            for a in args[1:]:
                out_t = T.common_super_type(out_t, a.type)
            coerced = tuple(
                a if a.type == out_t else ir.cast(a, out_t) for a in args
            )
            return ir.Call("coalesce", coerced, out_t)
        if name == "nullif":
            if len(args) != 2:
                raise PlanningError("nullif requires 2 arguments")
            return ir.Call("nullif", args, args[0].type)
        # if(cond, a [, b])
        if len(args) == 2:
            args = args + (ir.Literal(None, args[1].type),)
        if len(args) != 3:
            raise PlanningError("if requires 2 or 3 arguments")
        cond, a, b = args
        out_t = T.common_super_type(a.type, b.type)
        a = a if a.type == out_t else ir.cast(a, out_t)
        b = b if b.type == out_t else ir.cast(b, out_t)
        return ir.Call("if", (cond, a, b), out_t)

    # -- subqueries --
    def _plan_sub(self, q: t.Query):
        sub_planner_ctx = SelectContext(self.p, self.scopes, self.outer, self.ctes, None)
        rp = self.p.plan_query(q, sub_planner_ctx, self.ctes)
        return rp, sub_planner_ctx

    def _require_holder(self):
        if self.holder is None:
            raise PlanningError("subquery not allowed in this context")

    def _scalar_subquery(self, ast: t.ScalarSubquery) -> ir.RowExpression:
        self._require_holder()
        sub = SubqueryPlanner(self.p, self, self.ctes)
        return sub.plan_scalar(ast.query, self.holder)

    def _require_conjunct_position(self, ast: t.Node):
        """EXISTS/IN mutate the plan (SemiJoin) and return None — legal only
        when the expression being translated IS this predicate (optionally
        under a root-level NOT). Anywhere deeper (CASE, function args, ...)
        the None would corrupt the expression tree."""
        root = getattr(self, "_conjunct_root", None)
        if ast is root:
            return
        if isinstance(root, t.NotOp) and ast is root.operand:
            return
        raise PlanningError(
            "EXISTS/IN subquery is only supported as a top-level "
            "WHERE/HAVING conjunct"
        )

    def _exists(self, ast: t.Exists, negate: bool) -> Optional[ir.RowExpression]:
        self._require_holder()
        self._require_conjunct_position(ast)
        sub = SubqueryPlanner(self.p, self, self.ctes)
        sub.plan_exists(ast.query, self.holder, anti=negate)
        return None  # applied as a SemiJoin on the holder

    def _in_subquery(self, ast: t.InSubquery, negate: bool) -> Optional[ir.RowExpression]:
        self._require_holder()
        self._require_conjunct_position(ast)
        value = self._tr(ast.value)
        sub = SubqueryPlanner(self.p, self, self.ctes)
        sub.plan_in(ast.query, value, self.holder, anti=negate)
        return None

    def _subquery_mark(self, ast, negate: bool) -> ir.RowExpression:
        """Plan EXISTS / IN as a MARK semi-join and return the boolean
        membership column (usable inside OR, unlike the filtering form).
        EXISTS is two-valued, so NOT of the mark is exact."""
        self._require_holder()
        mark = self.p.channel("mark")
        sub = SubqueryPlanner(self.p, self, self.ctes)
        if isinstance(ast, t.Exists):
            sub.plan_exists(ast.query, self.holder, anti=False, mark=mark)
        else:
            value = self._tr(ast.value)
            sub.plan_in(ast.query, value, self.holder, anti=False, mark=mark)
        ref = ir.ColumnRef(mark, T.BOOLEAN)
        return ir.not_(ref) if negate else ref

    def translate_conjunct_or_apply(self, conj) -> Optional[ir.RowExpression]:
        return self.translate(conj)


_TS_FORMATS = (
    "%Y-%m-%d %H:%M:%S.%f",
    "%Y-%m-%d %H:%M:%S",
    "%Y-%m-%d %H:%M",  # Presto-legal no-seconds shape
    "%Y-%m-%d",
)


def _parse_timestamp_literal(value: str) -> int:
    """TIMESTAMP 'literal' -> epoch micros. Accepts every Presto-legal
    datetime shape (with/without seconds or fraction, date-only) plus a
    trailing numeric zone offset (+HH:MM / -HHMM / Z / UTC — normalized
    to UTC micros). Exhaustion raises a PlanningError instead of leaking
    a raw strptime ValueError; named zones are rejected explicitly."""
    import datetime as _dt
    import re as _re

    txt = value.strip()
    off_us = 0
    m = _re.search(r"\s*(Z|UTC|[+-]\d{2}:?\d{2})$", txt)
    if m:
        z = m.group(1)
        if z not in ("Z", "UTC"):
            sign = 1 if z[0] == "+" else -1
            hh, mm = int(z[1:3]), int(z[-2:])
            off_us = sign * (hh * 3600 + mm * 60) * 1_000_000
        txt = txt[: m.start()].strip()
    elif _re.search(r"[ ]\d{1,2}:\d{2}.*[ ][A-Za-z][\w/+_-]*$", txt):
        raise PlanningError(
            f"invalid timestamp literal {value!r}: named time zones are "
            "not supported (use a numeric offset like +05:30)"
        )
    epoch = _dt.datetime(1970, 1, 1)
    for fmt in _TS_FORMATS:
        try:
            dt = _dt.datetime.strptime(txt, fmt)
        except ValueError:
            continue
        return int((dt - epoch).total_seconds() * 1_000_000) - off_us
    raise PlanningError(f"invalid timestamp literal {value!r}")


def _number_literal(text: str) -> ir.Literal:
    if "e" in text.lower():
        return ir.Literal(float(text), T.DOUBLE)
    if "." in text:
        whole, frac = text.split(".")
        scale = len(frac)
        digits = len(whole.lstrip("-")) + scale
        if digits > 15:
            # beyond double's exact-integer range: carry the literal as
            # an exact Decimal and type it long (two-lane storage)
            import decimal as _dec

            return ir.Literal(
                _dec.Decimal(text), T.DecimalType(max(digits, 19), scale)
            )
        return ir.Literal(float(text), T.DecimalType(18, scale))
    return ir.Literal(int(text), T.BIGINT)


def _infer(fn: str, arg_types) -> T.Type:
    from ..expr.functions import infer_call_type

    return infer_call_type(fn, tuple(arg_types))


# ---------------------------------------------------------------------------
# subquery planning / decorrelation
# ---------------------------------------------------------------------------


class SubqueryPlanner:
    """Plans subqueries appearing in expressions, decorrelating the
    canonical TPC-H patterns (see module docstring)."""

    def __init__(self, planner: Planner, parent_ctx: SelectContext, ctes):
        self.p = planner
        self.parent = parent_ctx
        self.ctes = ctes

    def _plan_with_correlation(self, q: t.Query):
        """Plan `q` with the parent select as outer scope. Returns
        (RelationPlan, correlations) where correlations are
        (inner ColumnRef, outer RowExpression) equality pairs removed from
        the subquery plan, plus residual correlated predicates."""
        outer_ctx = self.parent
        collector = CorrelationCollector(outer_ctx)
        rp = self.p.plan_query(q, collector, self.ctes)
        return rp, collector

    def plan_scalar(self, q: t.Query, holder: PlanHolder) -> ir.RowExpression:
        rp, corr = self._plan_with_correlation(q)
        if len(rp.node.fields) != 1:
            raise PlanningError("scalar subquery must return one column")
        if corr.residuals:
            raise PlanningError(
                "correlated scalar subquery with non-equality correlation"
            )
        if not corr.pairs:
            holder.plan = N.ScalarApply(holder.plan, rp.node)
            (name, typ) = rp.node.fields[0]
            return ir.ColumnRef(name, typ)
        # correlated scalar aggregate -> group by correlation keys + left join
        node = rp.node
        out_name, out_type = node.fields[0]
        node, group_refs = _regroup_for_correlation(node, corr.pairs)
        holder.plan = N.Join(
            "left",
            holder.plan,
            node,
            tuple(outer for (_inner, outer) in corr.pairs),
            tuple(group_refs),
            None,
            True,
        )
        return ir.ColumnRef(out_name, out_type)

    def plan_exists(self, q: t.Query, holder: PlanHolder, anti: bool,
                    mark: Optional[str] = None):
        rp, corr = self._plan_with_correlation(q)
        if not corr.pairs:
            raise PlanningError("uncorrelated EXISTS not yet supported")
        residual = None
        if corr.residuals:
            residual = (
                ir.and_(*corr.residuals)
                if len(corr.residuals) > 1
                else corr.residuals[0]
            )
        # the EXISTS select list is irrelevant; the source plan must expose
        # the correlation-key channels (and residual's inner channels), which
        # the subquery's final Project may have dropped — e.g.
        # `exists (select 1 from ...)`
        needed = {inner.name for (inner, _o) in corr.pairs}
        if residual is not None:
            res_chs: set = set()
            collect_channels(residual, res_chs)
            # residuals mix probe- and source-side channels; only the ones
            # not provided by the probe plan must come from the source
            needed |= res_chs - set(holder.plan.field_names())
        source = _ensure_channels(rp.node, needed)
        holder.plan = N.SemiJoin(
            holder.plan,
            source,
            tuple(outer for (_inner, outer) in corr.pairs),
            tuple(inner for (inner, _outer) in corr.pairs),
            anti=anti,
            residual=residual,
            mark=mark,
        )

    def plan_in(self, q: t.Query, value: ir.RowExpression, holder: PlanHolder,
                anti: bool, mark: Optional[str] = None):
        rp, corr = self._plan_with_correlation(q)
        if corr.pairs or corr.residuals:
            raise PlanningError("correlated IN subquery not yet supported")
        if len(rp.node.fields) != 1:
            raise PlanningError("IN subquery must return one column")
        (name, typ) = rp.node.fields[0]
        holder.plan = N.SemiJoin(
            holder.plan,
            rp.node,
            (value,),
            (ir.ColumnRef(name, typ),),
            anti=anti,
            mark=mark,
        )


def _ensure_channels(node: N.PlanNode, needed: set) -> N.PlanNode:
    """Make sure `needed` channels appear in the node's output, widening a
    top Project (under optional Distinct/Limit wrappers) that dropped them.
    The EXISTS rewrite only cares about existence, so for a bare Project we
    can equivalently use its child."""
    missing = needed - set(node.field_names())
    if not missing:
        return node
    if isinstance(node, N.Project):
        child_have = set(node.child.field_names())
        if missing <= child_have:
            extra = tuple(
                ir.ColumnRef(ch, node.child.field_type(ch)) for ch in sorted(missing)
            )
            return N.Project(
                node.child,
                node.exprs + extra,
                node.names + tuple(sorted(missing)),
            )
        return _ensure_channels(node.child, needed)
    if isinstance(node, (N.Distinct, N.Limit)):
        # existence is unchanged by dedup/limit's column set; recurse
        inner = _ensure_channels(node.children[0], needed)
        return inner
    raise PlanningError(
        f"EXISTS subquery does not expose correlation columns {sorted(missing)}"
    )


def _regroup_for_correlation(node: N.PlanNode, pairs):
    """Rewrite a global-aggregate subquery plan into a grouped one over the
    correlation keys (reference
    TransformCorrelatedScalarAggregationToJoin.java). `pairs` items are
    (inner ColumnRef, outer expr); inner refs must be available below the
    Aggregate."""
    proj = None
    ag = node
    if isinstance(ag, N.Project):
        proj, ag = ag, ag.child
    if not isinstance(ag, N.Aggregate) or ag.group_exprs:
        raise PlanningError(
            "correlated scalar subquery must be a single aggregate"
        )
    group_refs = tuple(inner for (inner, _outer) in pairs)
    group_names = tuple(r.name for r in group_refs)
    new_ag = N.Aggregate(ag.child, group_refs, group_names, ag.aggs)
    if proj is not None:
        new_node: N.PlanNode = N.Project(
            new_ag,
            proj.exprs + group_refs,
            proj.names + group_names,
        )
    else:
        new_node = new_ag
    return new_node, group_refs


class CorrelationCollector(SelectContext):
    """Acts as the 'outer context' for a subquery plan: resolves outer
    columns through the true parent and records correlation predicates.

    The subquery's FromPlanner classifies each WHERE conjunct; conjuncts
    referencing outer channels surface here via resolve(). The planner's
    conjunct classification calls back into `note_correlated` through
    translate when a conjunct mixes scopes.
    """

    def __init__(self, parent: SelectContext):
        super().__init__(
            parent.p, parent.scopes, parent.outer, parent.ctes, None
        )
        self.pairs: List[Tuple[ir.ColumnRef, ir.RowExpression]] = []
        self.residuals: List[ir.RowExpression] = []
