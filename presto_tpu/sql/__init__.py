"""SQL frontend: lexer, parser, analyzer, logical planner.

Re-designed equivalent of the reference's presto-parser (ANTLR4 SqlBase.g4,
762 lines, ~170 AST classes under sql/tree/) and presto-main's
sql/analyzer + sql/planner. Scope-first: the grammar targets the analytic
SELECT dialect TPC-H/TPC-DS need (CTEs, joins, subqueries, aggregates,
window functions) and grows from there; the planner emits the PlanNode
vocabulary of SURVEY.md §1 L4 which maps 1:1 onto kernel calls and mesh
shardings.
"""

from .parser import parse  # noqa: F401
