"""Page wire serde: device Pages <-> bytes for cross-process transport.

Re-designed equivalent of the reference's SerializedPage + PagesSerde
(presto-main/.../execution/buffer/PagesSerde.java:39 — block-encoded
binary pages with optional LZ4). TPU-first differences: blocks are
fixed-width numpy arrays, so the encoding is a small JSON header (schema,
types, dictionary payloads) + column buffers, compressed with the native
C++ LZ4 block codec (presto_tpu/native/ — the same codec role as
airlift's aircompressor LZ4), falling back to stdlib zlib where no
toolchain exists, or raw for incompressible pages.

Wire format v2 (magic ``PTP2``) adds two layers the reference keeps in
its block encodings + PagesSerde framing:

* **Light-weight columnar encodings** chosen per buffer from cheap
  vectorized stats BEFORE the general codec (the analog of the
  reference's RunLengthEncodedBlock / DictionaryBlock / int packing):
  constant blocks, run-length encoding, dictionary encoding for low-NDV
  integer buffers, zigzag delta + byte-width packing for integer/date
  buffers, offset + byte-width packing, and bit-packed null bitmaps
  (``np.packbits``). Each shrinks the bytes LZ4/zstd has to chew, which
  is where the serialize wall time goes.
* **Striped parallel compression**: the raw body is split into fixed
  stripes compressed concurrently on a shared thread pool (the native
  LZ4 codec, zlib and zstd all release the GIL), with a framed stripe
  header so the receiving side decompresses concurrently too.

v1 frames (magic ``PTP1``) are still produced when a peer negotiates
down (see `negotiate`) and always decodable, so mixed fleets keep
working mid-upgrade.

Pages on the pull-based exchange path are SELF-CONTAINED: dictionaries
ship with every page (buffers are produced before their consumers are
known, so sender-side per-receiver dedup cannot apply there). For
long-lived point-to-point connections, pass a DictionaryCache on both
ends: the sender then ships each dictionary once and references it by id
afterwards — the cross-process answer to dict_ids being process-local.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page, dictionary_by_id, intern_dictionary

_MAGIC = b"PTP1"
_MAGIC2 = b"PTP2"
WIRE_VERSION = 2

# absolute cap on one deserialized wire page (untrusted input bound; the
# exchange sends pages far smaller than this — it exists so a corrupt or
# malicious header/stream cannot demand unbounded memory)
MAX_PAGE_BYTES = 1 << 30
# stripe-count bound: a corrupt v2 frame cannot demand an absurd header
MAX_STRIPES = 1 << 16

# knobs (docs/tuning.md "Exchange and wire format")
_STRIPE_BYTES = max(
    int(os.environ.get("PRESTO_TPU_STRIPE_BYTES", str(1 << 20))), 64 << 10
)
_ENCODINGS_ON = os.environ.get("PRESTO_TPU_ENCODINGS", "1") != "0"
_FORCE_V1 = os.environ.get("PRESTO_TPU_WIRE_V1", "0") == "1"
# skip the general codec when encodings already shrank the body below
# this fraction of the logical bytes (compress-once: delta/dict packed
# buffers are near-incompressible, so the codec pass would cost wall
# time for single-digit-% wins)
_SKIP_CODEC_RATIO = float(
    os.environ.get("PRESTO_TPU_ENCODED_SKIP_CODEC", "0.55")
)

# zstd (codec 3) is optional: gate on import so the serde stays
# dependency-free where the wheel is absent. (De)compressor objects are
# NOT thread-safe — the exchange path serializes from producer threads
# and deserializes from puller threads concurrently — so instances live
# in thread-local storage. `_zstd_c` stays a truthy sentinel for the
# codec-availability checks (tests monkeypatch it to None).
try:
    import threading as _threading

    import zstandard as _zstd

    _zstd_c = _zstd.ZstdCompressor(level=1)  # availability sentinel
    _zstd_d = _zstd.ZstdDecompressor()
    _zstd_tls = _threading.local()

    def _zstd_compress(raw: bytes) -> bytes:
        c = getattr(_zstd_tls, "c", None)
        if c is None:
            c = _zstd_tls.c = _zstd.ZstdCompressor(level=1)
        return c.compress(raw)

    def _zstd_decompress(data: bytes, max_output_size: int) -> bytes:
        d = getattr(_zstd_tls, "d", None)
        if d is None:
            d = _zstd_tls.d = _zstd.ZstdDecompressor()
        return d.decompress(data, max_output_size=max_output_size)

except Exception:  # noqa: BLE001 — zstd missing/broken disables the
    # codec; capability negotiation routes around it fleet-wide
    _zstd_c = _zstd_d = None


# ---------------------------------------------------------------------------
# capability negotiation (the exchange.max-response-size era's analog of
# the Accept header: mixed fleets must agree on a wire format instead of
# failing at deserialize — ADVICE round-5)
# ---------------------------------------------------------------------------

# the codec set ANY peer can decode without optional wheels or a
# toolchain: codec-2 LZ4 has a pure-python decode fallback, zlib and raw
# are stdlib. Used when a peer advertises nothing (old build).
_BASELINE_CODECS = ("lz4", "zlib", "raw")
_CODEC_PREFERENCE = ("zstd", "lz4", "zlib", "raw")


def local_capabilities() -> dict:
    """Codecs + wire version THIS process can decode, advertised through
    the worker /v1/status handshake. The ``hier`` advert says this build
    understands the hierarchical exchange's ragged paged wire unit
    (server/hier.py) — producers only take the hierarchical path when
    EVERY fleet member advertises it (negotiate intersects), so a host
    without collective support degrades the fleet to flat PTP2."""
    codecs = (["zstd"] if _zstd_d is not None else []) + list(_BASELINE_CODECS)
    return {
        "version": 1 if _FORCE_V1 else WIRE_VERSION,
        "codecs": codecs,
        "hier": {"ragged": True},
    }


def baseline_capabilities() -> dict:
    """The wire format EVERY build (past or present) can decode: v1
    frames + the stdlib/pure-python codec floor. The right assumption
    for a consumer that did not negotiate (e.g. a task spec posted by an
    old coordinator without a \"wire\" field)."""
    return {"version": 1, "codecs": list(_BASELINE_CODECS)}


def negotiate(peer_caps: Sequence[Optional[dict]]) -> dict:
    """Intersect this process's capabilities with every peer's advertised
    set. A peer that advertises nothing (None — an old build, or a status
    probe that failed) degrades the fleet to wire v1 + baseline codecs,
    so the exchange keeps flowing instead of failing on deserialize."""
    caps = local_capabilities()
    version = caps["version"]
    codecs = set(caps["codecs"])
    hier = bool((caps.get("hier") or {}).get("ragged"))
    for pc in peer_caps:
        if not isinstance(pc, dict):
            version = 1
            codecs &= set(_BASELINE_CODECS)
            hier = False
            continue
        version = min(version, int(pc.get("version", 1)))
        codecs &= set(pc.get("codecs", _BASELINE_CODECS))
        # hierarchical exchange is all-or-nothing: one worker without
        # the advert (old build, no collective support) degrades every
        # producer to the flat PTP2 loop — monotonic, never mixed
        hier = hier and bool((pc.get("hier") or {}).get("ragged"))
    codecs.add("raw")  # raw is the universal floor
    out = {
        "version": max(version, 1),
        "codecs": [c for c in _CODEC_PREFERENCE if c in codecs],
    }
    if hier:
        out["hier"] = {"ragged": True}
    return out


# ---------------------------------------------------------------------------
# wire stats (EXPLAIN ANALYZE / scheduler observability)
# ---------------------------------------------------------------------------


class WireStats:
    """Thread-safe encode/decode accounting for one exchange endpoint
    (a task's output serializer, a pull client's decoder). `raw_bytes`
    is the logical (pre-encoding) buffer size, so wire_bytes/raw_bytes
    is the end-to-end compression ratio the wire achieved."""

    def __init__(self):
        self._lock = threading.Lock()
        self.pages = 0
        self.raw_bytes = 0
        self.wire_bytes = 0  # ENCODE-side bytes put on the wire
        self.decoded_pages = 0
        self.decoded_bytes = 0  # DECODE-side bytes read off the wire —
        # kept separate so a process that both serializes and
        # deserializes (every worker) never double-counts wire traffic
        # or halves its compression ratio
        self.encode_s = 0.0
        self.decode_s = 0.0
        self.encodings: Dict[str, int] = {}

    def record_encode(self, raw: int, wire: int, seconds: float,
                      encodings: Optional[Sequence[str]] = None) -> None:
        with self._lock:
            self.pages += 1
            self.raw_bytes += raw
            self.wire_bytes += wire
            self.encode_s += seconds
            for e in encodings or ():
                self.encodings[e] = self.encodings.get(e, 0) + 1

    def record_decode(self, wire: int, seconds: float) -> None:
        with self._lock:
            self.decoded_pages += 1
            self.decoded_bytes += wire
            self.decode_s += seconds

    def merge_snapshot(self, snap: dict) -> None:
        """Fold a peer's snapshot() (e.g. a worker's status-reported
        encode stats) into this accumulator."""
        with self._lock:
            self.pages += snap.get("pages") or 0
            self.raw_bytes += snap.get("raw_bytes") or 0
            self.wire_bytes += snap.get("wire_bytes") or 0
            self.decoded_pages += snap.get("decoded_pages") or 0
            self.decoded_bytes += snap.get("decoded_bytes") or 0
            self.encode_s += (snap.get("encode_ms") or 0) / 1e3
            self.decode_s += (snap.get("decode_ms") or 0) / 1e3
            for k, v in (snap.get("encodings") or {}).items():
                self.encodings[k] = self.encodings.get(k, 0) + v

    def snapshot(self) -> dict:
        with self._lock:
            ratio = (
                round(self.raw_bytes / self.wire_bytes, 2)
                if self.wire_bytes and self.raw_bytes
                else None
            )
            return {
                "pages": self.pages,
                "raw_bytes": self.raw_bytes,
                "wire_bytes": self.wire_bytes,
                "decoded_pages": self.decoded_pages,
                "decoded_bytes": self.decoded_bytes,
                "encode_ms": round(self.encode_s * 1e3, 2),
                "decode_ms": round(self.decode_s * 1e3, 2),
                "compression_ratio": ratio,
                "encodings": dict(self.encodings),
            }


# process-wide accumulator (benchmark drivers snapshot deltas around a
# query to report per-query wire traffic; zero on paths that never
# serialize, e.g. single-process ICI execution)
GLOBAL_WIRE_STATS = WireStats()


# ---------------------------------------------------------------------------
# striped parallel compression
# ---------------------------------------------------------------------------

_pool_lock = threading.Lock()
_pool = None
_pool_unavailable = False


def _stripe_pool():
    """Shared worker pool for stripe (de)compression. The native LZ4
    ctypes calls, zlib and zstd all release the GIL, so stripes genuinely
    overlap. None on single-core boxes (striping still frames, the work
    just runs inline)."""
    global _pool, _pool_unavailable
    if _pool is not None or _pool_unavailable:
        return _pool
    with _pool_lock:
        if _pool is None and not _pool_unavailable:
            workers = min(os.cpu_count() or 1, 8)
            if workers < 2:
                _pool_unavailable = True
                return None
            from concurrent.futures import ThreadPoolExecutor

            _pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="ptpu-stripe"
            )
    return _pool


_CODEC_IDS = {"raw": 0, "zlib": 1, "lz4": 2, "zstd": 3}


def _pick_codec(caps: Optional[dict]) -> str:
    """First codec this process can ENCODE that every negotiated peer can
    decode. (Decode support is wider than encode support: lz4 decode has
    a pure-python fallback, but compression needs the native library.)"""
    allowed = (caps or local_capabilities()).get("codecs") or _BASELINE_CODECS
    from .. import native

    for c in _CODEC_PREFERENCE:
        if c not in allowed:
            continue
        if c == "zstd" and _zstd_c is None:
            continue
        if c == "lz4" and not native.available():
            continue
        return c
    return "raw"


def _compress_one(codec: str, data: bytes) -> bytes:
    if codec == "zstd":
        return _zstd_compress(data)
    if codec == "lz4":
        from .. import native

        return native.lz4_compress(data)
    if codec == "zlib":
        return zlib.compress(data, 1)
    return data


def _decompress_one(codec: str, blob: bytes, orig: int) -> bytes:
    if codec == "zstd":
        if _zstd_d is None:
            raise ValueError("zstd page received but zstandard missing")
        return _zstd_decompress(blob, orig)
    if codec == "lz4":
        from .. import native

        return native.lz4_decompress(blob, orig)
    if codec == "zlib":
        d = zlib.decompressobj()
        out = d.decompress(blob, orig)
        if d.unconsumed_tail or len(out) != orig:
            raise ValueError("zlib stripe inflated to an unexpected size")
        return out
    return blob


def _frame_v2(raw: bytes, codec: str) -> bytes:
    """PTP2 | codec u8 | nstripes u32 | (orig u32, comp u32)* | blobs.

    Stripes compress concurrently on the shared pool; if the compressed
    total is not smaller than the input the frame degrades to one raw
    stripe (incompressible page)."""
    n = len(raw)
    if codec != "raw" and n > 0:
        view = memoryview(raw)
        stripes = [
            bytes(view[i : i + _STRIPE_BYTES])
            for i in range(0, n, _STRIPE_BYTES)
        ]
        pool = _stripe_pool()
        if pool is not None and len(stripes) > 1:
            blobs = list(pool.map(lambda s: _compress_one(codec, s), stripes))
        else:
            blobs = [_compress_one(codec, s) for s in stripes]
        if sum(len(b) for b in blobs) < n:
            out = io.BytesIO()
            out.write(_MAGIC2)
            out.write(bytes([_CODEC_IDS[codec]]))
            out.write(len(stripes).to_bytes(4, "little"))
            for s, b in zip(stripes, blobs):
                out.write(len(s).to_bytes(4, "little"))
                out.write(len(b).to_bytes(4, "little"))
            for b in blobs:
                out.write(b)
            return out.getvalue()
    return (
        _MAGIC2
        + b"\x00"
        + (1).to_bytes(4, "little")
        + len(raw).to_bytes(4, "little")
        + len(raw).to_bytes(4, "little")
        + raw
    )


def _deframe_v2(data: bytes) -> bytes:
    """Parse + validate a PTP2 stripe frame, decompressing stripes
    concurrently. Every field is untrusted wire input: stripe counts and
    sizes are bounded BEFORE any allocation."""
    codec_id = data[4]
    codec = {v: k for k, v in _CODEC_IDS.items()}.get(codec_id)
    if codec is None:
        raise ValueError(f"unknown page codec {codec_id}")
    nstripes = int.from_bytes(data[5:9], "little")
    if not 1 <= nstripes <= MAX_STRIPES:
        raise ValueError(f"implausible stripe count {nstripes}")
    head_end = 9 + 8 * nstripes
    if len(data) < head_end:
        raise ValueError("truncated stripe header")
    origs: List[int] = []
    comps: List[int] = []
    total_orig = 0
    for i in range(nstripes):
        o = int.from_bytes(data[9 + 8 * i : 13 + 8 * i], "little")
        c = int.from_bytes(data[13 + 8 * i : 17 + 8 * i], "little")
        total_orig += o
        if total_orig > MAX_PAGE_BYTES:
            raise ValueError(
                f"stripe header declares more than the {MAX_PAGE_BYTES}-byte "
                "page cap"
            )
        # LZ4/zlib/zstd block expansion is far below 256x; a corrupt
        # header cannot demand an implausible inflation
        if codec != "raw" and o > max(256 * max(c, 1), 1 << 12):
            raise ValueError(
                f"stripe {i} declares implausible size {o} for {c} "
                "compressed bytes"
            )
        origs.append(o)
        comps.append(c)
    if len(data) - head_end != sum(comps):
        raise ValueError("stripe payload length mismatch")
    view = memoryview(data)
    blobs = []
    off = head_end
    for c in comps:
        blobs.append(bytes(view[off : off + c]))
        off += c
    pool = _stripe_pool()
    if codec == "raw":
        parts = blobs
    elif pool is not None and nstripes > 1:
        parts = list(
            pool.map(lambda t: _decompress_one(codec, t[0], t[1]),
                     zip(blobs, origs))
        )
    else:
        parts = [_decompress_one(codec, b, o) for b, o in zip(blobs, origs)]
    for p, o in zip(parts, origs):
        if len(p) != o:
            raise ValueError("stripe inflated to an unexpected size")
    return b"".join(parts)


# ---------------------------------------------------------------------------
# light-weight columnar encodings
# ---------------------------------------------------------------------------


def _width_dtype(maxval: int) -> np.dtype:
    if maxval < (1 << 8):
        return np.dtype("<u1")
    if maxval < (1 << 16):
        return np.dtype("<u2")
    if maxval < (1 << 32):
        return np.dtype("<u4")
    return np.dtype("<u8")


def _to_u64(flat: np.ndarray) -> np.ndarray:
    """View/convert any integer array into the modular uint64 domain
    (sign-extended), where offset/delta arithmetic is exact for every
    input — including full-range int64."""
    if flat.dtype == np.uint64:
        return flat
    if flat.dtype == np.int64:
        return flat.view(np.uint64)
    return flat.astype(np.int64).view(np.uint64)


def _from_u64(u: np.ndarray, dtype: np.dtype) -> np.ndarray:
    """Inverse of `_to_u64`: modular cast back to the original dtype
    (two's-complement truncation — exact because the encoded values fit)."""
    if dtype == np.uint64:
        return u
    if np.dtype(dtype).kind == "i":
        return u.astype(np.uint64).view(np.int64).astype(dtype)
    return u.astype(dtype)


_DICT_RANGE_MAX = 1 << 16  # bincount-based NDV probe stays O(n) + small


def _signed_width(lo: int, hi: int) -> int:
    """Smallest byte width whose SIGNED range holds [lo, hi]."""
    for w in (1, 2, 4):
        if -(1 << (8 * w - 1)) <= lo and hi < (1 << (8 * w - 1)):
            return w
    return 8


def _encode_array(arr: np.ndarray):
    """(encoding descriptor | None, payload ndarray-or-bytes). None means
    raw C-order bytes.

    Encodings are chosen by exact byte cost from one cheap vectorized
    stats pass (min/max, modular deltas, bincount NDV for small ranges) —
    the per-column analog of the reference's block encodings
    (RunLengthEncodedBlock, DictionaryBlock, int packing), applied on the
    wire where this engine's device pages are plain fixed-width arrays.
    All integer arithmetic runs modulo 2^64 on both ends, so truncation
    is exact for every input including full-range int64. The hot path is
    deliberately few-pass: one reduction pair for min/max, one diff, one
    truncating store — serialize wall time IS this function."""
    a = np.ascontiguousarray(arr)
    n = a.size
    if n < 64 or not _ENCODINGS_ON:
        return None, a
    if a.dtype == np.bool_:
        # bit-packed bitmap: 8x smaller before the codec ever runs
        return {"k": "bits"}, np.packbits(a.reshape(-1))
    kind = a.dtype.kind
    if kind == "f":
        # floats: constant detection only (bitwise, NaN-safe)
        bits = a.view(_width_dtype((1 << (8 * a.dtype.itemsize)) - 1)).reshape(-1)
        if bits.min() == bits.max():
            return {"k": "const"}, a.reshape(-1)[:1]
        return None, a
    if kind not in "iu":
        return None, a

    # integer lanes: multi-dim arrays (decimal limbs, collection widths)
    # encode lane-contiguous (Fortran flatten) so deltas run down a lane
    fortran = a.ndim > 1
    flat = np.ascontiguousarray(a.T).reshape(-1) if fortran else a.reshape(-1)
    mn_s, mx_s = int(flat.min()), int(flat.max())
    base = {"F": 1} if fortran else {}
    if mn_s == mx_s:
        return {"k": "const", **base}, flat[:1]
    u = _to_u64(flat)
    off_u = np.uint64(mn_s & 0xFFFFFFFFFFFFFFFF)
    rng = mx_s - mn_s  # exact python int — never overflows
    off_dt = _width_dtype(rng)
    itemsize = a.dtype.itemsize
    best_kind = None
    best_cost = n * itemsize  # raw
    if off_dt.itemsize < itemsize:
        best_kind, best_cost = "off", n * off_dt.itemsize

    # probe delta/RLE/NDV stats on contiguous sample chunks first: the
    # full-array diff/bincount temporaries are the expensive part of a
    # serialize (multi-MB allocations), so they only run when the sample
    # says the encoding can plausibly win. The probe only GATES — every
    # chosen encoding is verified on exact full-array stats below.
    if n > 65536:
        step = n // 8
        chunks = [u[i * step : i * step + 512] for i in range(8)]
        dsamp = np.concatenate([np.diff(c) for c in chunks])
        ssamp = np.concatenate(chunks)
    else:
        dsamp = np.diff(u)
        ssamp = u
    dss = dsamp.view(np.int64)
    dw_est = _signed_width(int(dss.min()), int(dss.max())) if dss.size else 1
    run_frac = (
        np.count_nonzero(dsamp) / dsamp.size if dsamp.size else 1.0
    )

    # modular delta, stored sign-truncated: sorted/clustered ints (keys,
    # dates, row ids) shrink to their STEP width
    d = dw = None
    nruns = None
    probe_delta = itemsize + n * dw_est < best_cost
    probe_rle = run_frac < 0.25
    if (probe_delta or probe_rle) and n > 1:
        d = np.diff(u)
        ds = d.view(np.int64)
        dw = _signed_width(int(ds.min()), int(ds.max()))
        delta_cost = itemsize + ds.size * dw
        if delta_cost < best_cost:
            best_kind, best_cost = "delta", delta_cost
        # run-length: few runs of repeated values (sorted keys, flags)
        nruns = int(np.count_nonzero(d)) + 1
        if nruns * 4 < n:
            run_dt = _width_dtype(n)
            rle_cost = nruns * (off_dt.itemsize + run_dt.itemsize)
            if rle_cost < best_cost:
                best_kind, best_cost = "rle", rle_cost

    # dictionary: low NDV over a bounded range (bincount keeps the NDV
    # probe O(n) — wide-range low-NDV columns fall through to delta/raw).
    # Gate on the sampled NDV so high-NDV columns skip the code build.
    counts = vals = None
    if (
        rng <= _DICT_RANGE_MAX
        and off_dt.itemsize > 1
        and np.unique(ssamp).size <= 512
    ):
        vals = np.subtract(u, off_u, dtype=np.int64, casting="unsafe")
        counts = np.bincount(vals, minlength=rng + 1)
        nu = int(np.count_nonzero(counts))
        code_dt = _width_dtype(max(nu - 1, 0))
        dict_cost = nu * off_dt.itemsize + n * code_dt.itemsize
        if dict_cost < best_cost:
            best_kind, best_cost = "dict", dict_cost

    if best_kind is None:
        return None, a  # raw keeps C order (the no-descriptor contract)
    if best_kind == "off":
        # modular homomorphism: truncate-then-subtract == subtract-then-
        # truncate, so the whole encode is ONE casting ufunc pass
        vals = np.subtract(u, off_u, dtype=off_dt, casting="unsafe")
        return {"k": "off", "o": mn_s, "w": off_dt.itemsize, **base}, vals
    if best_kind == "delta":
        return (
            {"k": "delta", "f": int(flat[0]), "w": dw, **base},
            d.astype(_u_dt(dw)),  # modular truncate; exact by width check
        )
    if best_kind == "rle":
        run_dt = _width_dtype(n)
        starts = np.concatenate([np.zeros(1, np.int64), np.flatnonzero(d) + 1])
        lengths = np.diff(np.append(starts, n)).astype(run_dt)
        rvals = (u[starts] - off_u).astype(off_dt)
        return (
            {"k": "rle", "o": mn_s, "w": off_dt.itemsize,
             "rw": run_dt.itemsize, "nr": nruns, **base},
            rvals.tobytes() + lengths.tobytes(),
        )
    # dict
    code_map = np.cumsum(counts > 0) - 1
    nu = int(np.count_nonzero(counts))
    code_dt = _width_dtype(max(nu - 1, 0))
    codes = code_map[vals].astype(code_dt)
    uniq = np.flatnonzero(counts).astype(off_dt)
    return (
        {"k": "dict", "o": mn_s, "w": off_dt.itemsize,
         "cw": code_dt.itemsize, "nu": nu, **base},
        uniq.tobytes() + codes.tobytes(),
    )


def _u_dt(width: int) -> np.dtype:
    return {1: np.dtype("<u1"), 2: np.dtype("<u2"),
            4: np.dtype("<u4"), 8: np.dtype("<u8")}[int(width)]


def _decode_array(desc: Optional[dict], buf, dtype: np.dtype,
                  shape: Sequence[int],
                  budget: Optional[dict] = None) -> np.ndarray:
    """Inverse of `_encode_array`. `buf` and the header-declared shape
    are untrusted wire input: frombuffer raises on short payloads, and
    the MATERIALIZED size is bounded — const/rle/dict expand beyond the
    (stripe-bounded) wire bytes, so a corrupt header must not be able to
    demand a huge allocation. `budget` ({"left": bytes}) caps the whole
    page cumulatively across its columns."""
    dtype = np.dtype(dtype)
    shape = tuple(int(s) for s in shape)
    n = 1
    for s in shape:
        n *= s
    nbytes = n * dtype.itemsize
    if n < 0 or nbytes > MAX_PAGE_BYTES:
        raise ValueError(
            f"column declares {n} elements ({nbytes} bytes), "
            f"past the {MAX_PAGE_BYTES}-byte page cap"
        )
    if budget is not None:
        budget["left"] -= nbytes
        if budget["left"] < 0:
            raise ValueError(
                f"page columns declare more than the {MAX_PAGE_BYTES}-byte "
                "page cap in total"
            )
    if desc is None:
        arr = np.frombuffer(buf, dtype=dtype, count=n)
        return arr.reshape(shape)
    k = desc.get("k")
    fortran = bool(desc.get("F"))

    def out_shape(flat):
        if fortran:
            return flat.reshape(tuple(reversed(shape))).T
        return flat.reshape(shape)

    if k == "bits":
        flat = np.unpackbits(
            np.frombuffer(buf, dtype=np.uint8), count=n
        ).astype(np.bool_)
        return flat.reshape(shape)
    if k == "const":
        v = np.frombuffer(buf, dtype=dtype, count=1)
        return out_shape(np.broadcast_to(v, (n,)).copy())
    if k == "off":
        vals = np.frombuffer(buf, dtype=_u_dt(desc["w"]), count=n)
        u = vals.astype(np.uint64) + np.uint64(int(desc["o"]) & 0xFFFFFFFFFFFFFFFF)
        return out_shape(_from_u64(u, dtype))
    if k == "delta":
        if n == 0:
            return out_shape(np.zeros(0, dtype))
        w = int(desc["w"])
        st = np.frombuffer(buf, dtype=_u_dt(w), count=max(n - 1, 0))
        sdt = {1: np.int8, 2: np.int16, 4: np.int32, 8: np.int64}[w]
        # sign-extend the truncated modular deltas, then rebuild the
        # absolutes by modular cumsum from the first value
        ds = st.view(sdt).astype(np.int64).view(np.uint64)
        u = np.empty(n, np.uint64)
        u[0] = np.uint64(int(desc["f"]) & 0xFFFFFFFFFFFFFFFF)
        if n > 1:
            np.cumsum(ds, out=u[1:])
            u[1:] += u[0]
        return out_shape(_from_u64(u, dtype))
    if k == "rle":
        nr = int(desc["nr"])
        vw, rw = int(desc["w"]), int(desc["rw"])
        if nr < 0 or nr > n:
            raise ValueError(f"implausible run count {nr}")
        rvals = np.frombuffer(buf, dtype=_u_dt(vw), count=nr, offset=0)
        lengths = np.frombuffer(
            buf, dtype=_u_dt(rw), count=nr, offset=nr * vw
        ).astype(np.int64)
        if int(lengths.sum()) != n:
            raise ValueError("run lengths do not cover the buffer")
        u = np.repeat(
            rvals.astype(np.uint64)
            + np.uint64(int(desc["o"]) & 0xFFFFFFFFFFFFFFFF),
            lengths,
        )
        return out_shape(_from_u64(u, dtype))
    if k == "dict":
        nu = int(desc["nu"])
        vw, cw = int(desc["w"]), int(desc["cw"])
        if nu <= 0 or nu > n:
            raise ValueError(f"implausible dictionary size {nu}")
        uniq = np.frombuffer(buf, dtype=_u_dt(vw), count=nu, offset=0)
        codes = np.frombuffer(
            buf, dtype=_u_dt(cw), count=n, offset=nu * vw
        ).astype(np.int64)
        if codes.size and int(codes.max()) >= nu:
            raise ValueError("dictionary code out of range")
        u = uniq.astype(np.uint64)[codes] + np.uint64(
            int(desc["o"]) & 0xFFFFFFFFFFFFFFFF
        )
        return out_shape(_from_u64(u, dtype))
    raise ValueError(f"unknown buffer encoding {k!r}")


def _type_to_wire(t: T.Type) -> str:
    return t.display()


def _type_from_wire(s: str) -> T.Type:
    return T.parse_type(s)


class DictionaryCache:
    """Tracks which interned dictionaries the peer has already received
    (sender side) or holds local ids for remote ids (receiver side)."""

    def __init__(self):
        self.sent: Set[int] = set()
        self.remote_to_local: Dict[int, int] = {}


# ---------------------------------------------------------------------------
# serialize
# ---------------------------------------------------------------------------


def serialize_page(
    page: Page,
    cache: Optional[DictionaryCache] = None,
    compress: bool = True,
    caps: Optional[dict] = None,
    stats: Optional[WireStats] = None,
) -> bytes:
    """Page -> bytes. Live rows only (the wire never carries dead slots).

    `caps` is the NEGOTIATED capability set for the receiving fleet (see
    `negotiate`); None means "assume a peer like this process". Version-1
    peers get the legacy PTP1 frame; v2 peers get per-buffer light-weight
    encodings + the striped frame."""
    t0 = time.perf_counter()
    if caps is None:
        caps = local_capabilities()
    v2 = int(caps.get("version", 1)) >= 2 and not _FORCE_V1
    n = int(page.count)
    cols = []
    arrays: List[np.ndarray] = []  # buffers in wire order, pre-encoding
    fixups = []  # (entry, [array indices]) to fill enc descriptors
    dict_payloads = {}
    raw_logical = 0

    def push_buffer(arr: np.ndarray) -> int:
        nonlocal raw_logical
        raw_logical += arr.nbytes
        arrays.append(arr)
        return len(arrays) - 1

    def encode_block(name, b):
        data = np.asarray(b.data)[:n]
        valid = None if b.valid is None else np.asarray(b.valid)[:n]
        lengths = None if b.lengths is None else np.asarray(b.lengths)[:n]
        ev = None if b.elem_valid is None else np.asarray(b.elem_valid)[:n]
        entry = {
            "name": name,
            "type": _type_to_wire(b.type),
            "dtype": data.dtype.str,
            "shape": list(data.shape),
            "valid": valid is not None,
            "dict_id": b.dict_id,
            "lengths": lengths is not None,
            "elem_valid": ev is not None,
        }
        if b.dict_id is not None:
            needs = cache is None or b.dict_id not in cache.sent
            if needs:
                d = dictionary_by_id(b.dict_id)
                dict_payloads[str(b.dict_id)] = list(d)
                if cache is not None:
                    cache.sent.add(b.dict_id)
        idxs = [push_buffer(data)]
        if valid is not None:
            idxs.append(push_buffer(valid))
        if lengths is not None:
            idxs.append(push_buffer(lengths.astype(np.int32)))
        if ev is not None:
            idxs.append(push_buffer(ev))
        fixups.append((entry, idxs))
        if b.key_block is not None:
            entry["key"] = encode_block(f"{name}$keys", b.key_block)
        return entry

    for name, b in zip(page.names, page.blocks):
        cols.append(encode_block(name, b))

    encs: List[str] = []
    if v2:
        # per-column light-weight encodings, fanned out on the stripe
        # pool — numpy reductions/casts release the GIL, so columns of
        # one page encode concurrently like stripes compress
        pool = _stripe_pool()
        big = sum(a.nbytes for a in arrays) >= (1 << 20)
        if pool is not None and big and len(arrays) > 1:
            encoded = list(pool.map(_encode_array, arrays))
        else:
            encoded = [_encode_array(a) for a in arrays]
        payloads = []
        descs_by_idx: List[Optional[dict]] = []
        for desc, payload in encoded:
            descs_by_idx.append(desc)
            payloads.append(payload)
            if desc is not None:
                encs.append(desc["k"])
        for entry, idxs in fixups:
            descs = [descs_by_idx[i] for i in idxs]
            if any(d is not None for d in descs):
                entry["enc"] = descs
    else:
        payloads = [np.ascontiguousarray(a) for a in arrays]

    header = json.dumps(
        {"count": n, "columns": cols, "dictionaries": dict_payloads}
    ).encode()
    parts: List[bytes] = [len(header).to_bytes(4, "little"), header]
    for buf in payloads:
        nbytes = buf.nbytes if isinstance(buf, np.ndarray) else len(buf)
        parts.append(nbytes.to_bytes(8, "little"))
        parts.append(buf.data if isinstance(buf, np.ndarray) else buf)
    raw = b"".join(parts)
    raw_logical += len(header)

    if v2:
        # compress-once policy: when the encodings already shrank the
        # body well below the logical bytes, the general codec has little
        # left to chew — skip it and save its wall time
        already_compact = len(raw) < raw_logical * _SKIP_CODEC_RATIO
        codec = (
            "raw"
            if not compress or already_compact
            else _pick_codec(caps)
        )
        out = _frame_v2(raw, codec)
        for s in (stats, GLOBAL_WIRE_STATS):
            if s is not None:
                s.record_encode(
                    raw_logical, len(out), time.perf_counter() - t0, encs
                )
        return out

    out = _serialize_v1_tail(raw, caps if compress else {"codecs": ["raw"]})
    for s in (stats, GLOBAL_WIRE_STATS):
        if s is not None:
            s.record_encode(raw_logical, len(out), time.perf_counter() - t0)
    return out


def _serialize_v1_tail(raw: bytes, caps: Optional[dict]) -> bytes:
    """Legacy PTP1 codec selection over an unencoded body, now bounded by
    the negotiated codec set (a v1 peer without the zstd wheel must not
    receive codec 3). The codec byte keeps old readers' frames decodable."""
    codec = _pick_codec(caps)
    if codec == "zstd":
        packed = _zstd_compress(raw)
        if len(packed) < len(raw):
            return _MAGIC + b"\x03" + packed
        return _MAGIC + b"\x00" + raw
    if codec == "lz4":
        from .. import native

        packed = native.lz4_compress(raw)
        if len(packed) + 8 < len(raw):
            return _MAGIC + b"\x02" + len(raw).to_bytes(8, "little") + packed
        return _MAGIC + b"\x00" + raw
    if codec == "zlib":
        payload = zlib.compress(raw, 1)
        if len(payload) < len(raw):
            return _MAGIC + b"\x01" + payload
        return _MAGIC + b"\x00" + raw
    return _MAGIC + b"\x00" + raw


# ---------------------------------------------------------------------------
# deserialize
# ---------------------------------------------------------------------------


def deserialize_page(
    data: bytes, cache: Optional[DictionaryCache] = None,
    stats: Optional[WireStats] = None,
) -> Page:
    t0 = time.perf_counter()
    magic = data[:4]
    if magic == _MAGIC2:
        raw = _deframe_v2(data)
    elif magic == _MAGIC:
        raw = _deframe_v1(data)
    else:
        raise AssertionError("bad page magic")
    page = _decode_body(raw, cache)
    for s in (stats, GLOBAL_WIRE_STATS):
        if s is not None:
            s.record_decode(len(data), time.perf_counter() - t0)
    return page


def _deframe_v1(data: bytes) -> bytes:
    codec = data[4]
    if codec == 0:
        return data[5:]
    if codec == 1:
        # untrusted wire input: bound the inflated size (a zlib bomb can
        # expand ~1000x, so a ratio bound would reject legitimately
        # compressible pages — use the absolute page cap instead)
        d = zlib.decompressobj()
        raw = d.decompress(data[5:], MAX_PAGE_BYTES)
        if d.unconsumed_tail:
            raise ValueError(
                f"zlib page exceeds the {MAX_PAGE_BYTES}-byte page cap"
            )
        return raw
    if codec == 2:
        from .. import native

        orig = int.from_bytes(data[5:13], "little")
        # the size header is untrusted wire input: bound it before the
        # decompressor allocates (LZ4 block expansion is < 256x; also cap
        # absolutely so a corrupt header cannot demand 2^64 bytes)
        if orig > max(256 * (len(data) - 13), 1 << 12) or orig > MAX_PAGE_BYTES:
            raise ValueError(
                f"lz4 page declares implausible size {orig} "
                f"for {len(data) - 13} compressed bytes"
            )
        return native.lz4_decompress(data[13:], orig)
    if codec == 3:
        if _zstd_d is None:
            raise ValueError("zstd page received but zstandard missing")
        # untrusted wire input: stream-bound the inflated size like zlib
        return _zstd_decompress(data[5:], MAX_PAGE_BYTES)
    raise ValueError(f"unknown page codec {codec}")


def _decode_body(raw: bytes, cache: Optional[DictionaryCache]) -> Page:
    view = memoryview(raw)
    hlen = int.from_bytes(view[:4], "little")
    header = json.loads(bytes(view[4 : 4 + hlen]))
    off = 4 + hlen

    def read_buf():
        nonlocal off
        blen = int.from_bytes(view[off : off + 8], "little")
        off += 8
        buf = view[off : off + blen]
        off += blen
        return buf

    n = header["count"]
    blocks = []
    names = []
    # cumulative materialization cap across ALL of the page's buffers
    # (per-column checks alone would let a many-column corrupt header
    # amplify const/rle payload bytes into N separate huge allocations)
    budget = {"left": MAX_PAGE_BYTES}
    import jax.numpy as jnp

    def decode_block(col):
        typ = _type_from_wire(col["type"])
        encs = col.get("enc") or [None] * 4
        ei = iter(encs)
        dtype = np.dtype(col["dtype"])
        shape = col["shape"]
        arr = _decode_array(next(ei, None), read_buf(), dtype, shape, budget)
        valid = None
        if col["valid"]:
            valid = _decode_array(
                next(ei, None), read_buf(), np.dtype(np.bool_), (shape[0],),
                budget,
            )
        lengths = None
        if col.get("lengths"):
            lengths = _decode_array(
                next(ei, None), read_buf(), np.dtype(np.int32), (shape[0],),
                budget,
            )
        ev = None
        if col.get("elem_valid"):
            ev = _decode_array(
                next(ei, None), read_buf(), np.dtype(np.bool_), shape[:2],
                budget,
            )
        dict_id = col["dict_id"]
        local_dict = None
        if dict_id is not None:
            payload = header["dictionaries"].get(str(dict_id))
            if payload is not None:
                local = intern_dictionary(tuple(payload))
                if cache is not None:
                    cache.remote_to_local[dict_id] = local
                local_dict = local
            elif cache is not None:
                local_dict = cache.remote_to_local[dict_id]
            else:
                raise KeyError(
                    f"dictionary {dict_id} not in payload and no cache"
                )
        key_block = None
        if col.get("key") is not None:
            key_block = decode_block(col["key"])
        return Block(
            jnp.asarray(arr),
            typ,
            None if valid is None else jnp.asarray(valid),
            local_dict,
            lengths=None if lengths is None else jnp.asarray(lengths),
            elem_valid=None if ev is None else jnp.asarray(ev),
            key_block=key_block,
        )

    for col in header["columns"]:
        blocks.append(decode_block(col))
        names.append(col["name"])
    return Page.from_blocks(blocks, names, count=n)
