"""Page wire serde: device Pages <-> bytes for cross-process transport.

Re-designed equivalent of the reference's SerializedPage + PagesSerde
(presto-main/.../execution/buffer/PagesSerde.java:39 — block-encoded
binary pages with optional LZ4). TPU-first differences: blocks are
fixed-width numpy arrays, so the encoding is a small JSON header (schema,
types, dictionary payloads) + raw little-endian column buffers,
compressed with the native C++ LZ4 block codec (presto_tpu/native/ —
the same codec role as airlift's aircompressor LZ4), falling back to
stdlib zlib where no toolchain exists, or raw for incompressible pages.

Pages on the pull-based exchange path are SELF-CONTAINED: dictionaries
ship with every page (buffers are produced before their consumers are
known, so sender-side per-receiver dedup cannot apply there). For
long-lived point-to-point connections, pass a DictionaryCache on both
ends: the sender then ships each dictionary once and references it by id
afterwards — the cross-process answer to dict_ids being process-local.
"""

from __future__ import annotations

import io
import json
import zlib
from typing import Dict, Optional, Sequence, Set, Tuple

import numpy as np

from .. import types as T
from ..page import Block, Page, dictionary_by_id, intern_dictionary

_MAGIC = b"PTP1"

# absolute cap on one deserialized wire page (untrusted input bound; the
# exchange sends pages far smaller than this — it exists so a corrupt or
# malicious header/stream cannot demand unbounded memory)
MAX_PAGE_BYTES = 1 << 30

# zstd (codec 3) is optional: gate on import so the serde stays
# dependency-free where the wheel is absent. (De)compressor objects are
# NOT thread-safe — the exchange path serializes from producer threads
# and deserializes from puller threads concurrently — so instances live
# in thread-local storage. `_zstd_c` stays a truthy sentinel for the
# codec-availability checks (tests monkeypatch it to None).
try:
    import threading as _threading

    import zstandard as _zstd

    _zstd_c = _zstd.ZstdCompressor(level=1)  # availability sentinel
    _zstd_d = _zstd.ZstdDecompressor()
    _zstd_tls = _threading.local()

    def _zstd_compress(raw: bytes) -> bytes:
        c = getattr(_zstd_tls, "c", None)
        if c is None:
            c = _zstd_tls.c = _zstd.ZstdCompressor(level=1)
        return c.compress(raw)

    def _zstd_decompress(data: bytes, max_output_size: int) -> bytes:
        d = getattr(_zstd_tls, "d", None)
        if d is None:
            d = _zstd_tls.d = _zstd.ZstdDecompressor()
        return d.decompress(data, max_output_size=max_output_size)

except Exception:  # noqa: BLE001
    _zstd_c = _zstd_d = None


def _type_to_wire(t: T.Type) -> str:
    return t.display()


def _type_from_wire(s: str) -> T.Type:
    return T.parse_type(s)


class DictionaryCache:
    """Tracks which interned dictionaries the peer has already received
    (sender side) or holds local ids for remote ids (receiver side)."""

    def __init__(self):
        self.sent: Set[int] = set()
        self.remote_to_local: Dict[int, int] = {}


def serialize_page(
    page: Page, cache: Optional[DictionaryCache] = None, compress: bool = True
) -> bytes:
    """Page -> bytes. Live rows only (the wire never carries dead slots)."""
    n = int(page.count)
    cols = []
    buffers = []
    dict_payloads = {}

    def encode_block(name, b):
        data = np.asarray(b.data[:n])
        valid = None if b.valid is None else np.asarray(b.valid[:n])
        lengths = None if b.lengths is None else np.asarray(b.lengths[:n])
        ev = None if b.elem_valid is None else np.asarray(b.elem_valid[:n])
        entry = {
            "name": name,
            "type": _type_to_wire(b.type),
            "dtype": data.dtype.str,
            "shape": list(data.shape),
            "valid": valid is not None,
            "dict_id": b.dict_id,
            "lengths": lengths is not None,
            "elem_valid": ev is not None,
        }
        if b.dict_id is not None:
            needs = cache is None or b.dict_id not in cache.sent
            if needs:
                d = dictionary_by_id(b.dict_id)
                dict_payloads[str(b.dict_id)] = list(d)
                if cache is not None:
                    cache.sent.add(b.dict_id)
        buffers.append(data.tobytes())
        if valid is not None:
            buffers.append(valid.tobytes())
        if lengths is not None:
            buffers.append(lengths.astype(np.int32).tobytes())
        if ev is not None:
            buffers.append(ev.tobytes())
        if b.key_block is not None:
            entry["key"] = encode_block(f"{name}$keys", b.key_block)
        return entry

    for name, b in zip(page.names, page.blocks):
        cols.append(encode_block(name, b))
    header = json.dumps(
        {"count": n, "columns": cols, "dictionaries": dict_payloads}
    ).encode()
    body = io.BytesIO()
    body.write(len(header).to_bytes(4, "little"))
    body.write(header)
    for buf in buffers:
        body.write(len(buf).to_bytes(8, "little"))
        body.write(buf)
    raw = body.getvalue()
    if not compress:
        return _MAGIC + b"\x00" + raw
    # codec preference: zstd level 1 (fastest wire codec available in
    # this image — ~4x the from-scratch LZ4's throughput on the serde
    # micro) > native LZ4 (native/lz4.cpp, the aircompressor-analog) >
    # zlib > raw-if-incompressible. The codec byte keeps old readers'
    # frames decodable either way.
    if _zstd_c is not None:
        packed = _zstd_compress(raw)
        if len(packed) < len(raw):
            return _MAGIC + b"\x03" + packed
        return _MAGIC + b"\x00" + raw
    from .. import native

    if native.available():
        packed = native.lz4_compress(raw)
        if len(packed) + 8 < len(raw):
            return (
                _MAGIC + b"\x02" + len(raw).to_bytes(8, "little") + packed
            )
        return _MAGIC + b"\x00" + raw
    payload = zlib.compress(raw, 1)
    if len(payload) < len(raw):
        return _MAGIC + b"\x01" + payload
    return _MAGIC + b"\x00" + raw


def deserialize_page(
    data: bytes, cache: Optional[DictionaryCache] = None
) -> Page:
    assert data[:4] == _MAGIC, "bad page magic"
    codec = data[4]
    if codec == 0:
        raw = data[5:]
    elif codec == 1:
        # untrusted wire input: bound the inflated size (a zlib bomb can
        # expand ~1000x, so a ratio bound would reject legitimately
        # compressible pages — use the absolute page cap instead)
        d = zlib.decompressobj()
        raw = d.decompress(data[5:], MAX_PAGE_BYTES)
        if d.unconsumed_tail:
            raise ValueError(
                f"zlib page exceeds the {MAX_PAGE_BYTES}-byte page cap"
            )
    elif codec == 2:
        from .. import native

        orig = int.from_bytes(data[5:13], "little")
        # the size header is untrusted wire input: bound it before the
        # decompressor allocates (LZ4 block expansion is < 256x; also cap
        # absolutely so a corrupt header cannot demand 2^64 bytes)
        if orig > max(256 * (len(data) - 13), 1 << 12) or orig > MAX_PAGE_BYTES:
            raise ValueError(
                f"lz4 page declares implausible size {orig} "
                f"for {len(data) - 13} compressed bytes"
            )
        raw = native.lz4_decompress(data[13:], orig)
    elif codec == 3:
        if _zstd_d is None:
            raise ValueError("zstd page received but zstandard missing")
        # untrusted wire input: stream-bound the inflated size like zlib
        raw = _zstd_decompress(data[5:], MAX_PAGE_BYTES)
    else:
        raise ValueError(f"unknown page codec {codec}")
    view = memoryview(raw)
    hlen = int.from_bytes(view[:4], "little")
    header = json.loads(bytes(view[4 : 4 + hlen]))
    off = 4 + hlen

    def read_buf():
        nonlocal off
        blen = int.from_bytes(view[off : off + 8], "little")
        off += 8
        buf = view[off : off + blen]
        off += blen
        return buf

    n = header["count"]
    blocks = []
    names = []
    import jax.numpy as jnp

    def decode_block(col):
        typ = _type_from_wire(col["type"])
        arr = np.frombuffer(read_buf(), dtype=np.dtype(col["dtype"]))
        arr = arr.reshape(col["shape"])
        valid = None
        if col["valid"]:
            valid = np.frombuffer(read_buf(), dtype=np.bool_)
        lengths = None
        if col.get("lengths"):
            lengths = np.frombuffer(read_buf(), dtype=np.int32)
        ev = None
        if col.get("elem_valid"):
            ev = np.frombuffer(read_buf(), dtype=np.bool_).reshape(
                col["shape"][:2]
            )
        dict_id = col["dict_id"]
        local_dict = None
        if dict_id is not None:
            payload = header["dictionaries"].get(str(dict_id))
            if payload is not None:
                local = intern_dictionary(tuple(payload))
                if cache is not None:
                    cache.remote_to_local[dict_id] = local
                local_dict = local
            elif cache is not None:
                local_dict = cache.remote_to_local[dict_id]
            else:
                raise KeyError(
                    f"dictionary {dict_id} not in payload and no cache"
                )
        key_block = None
        if col.get("key") is not None:
            key_block = decode_block(col["key"])
        return Block(
            jnp.asarray(arr),
            typ,
            None if valid is None else jnp.asarray(valid),
            local_dict,
            lengths=None if lengths is None else jnp.asarray(lengths),
            elem_valid=None if ev is None else jnp.asarray(ev),
            key_block=key_block,
        )

    for col in header["columns"]:
        blocks.append(decode_block(col))
        names.append(col["name"])
    return Page.from_blocks(blocks, names, count=n)
