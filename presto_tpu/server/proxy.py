"""Authenticating pass-through proxy for the statement protocol.

Re-designed equivalent of presto-proxy (893 LoC: a Jetty forwarder that
authenticates clients, signs/forwards requests to the real coordinator,
and rewrites response URIs so clients keep talking to the proxy). Same
contract here over stdlib HTTP: the proxy terminates client auth (its
own password file), then forwards upstream with the proxy's backend
credentials — clients never hold coordinator credentials — and rewrites
every nextUri/infoUri in responses to point at itself."""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class ProxyServer:
    def __init__(
        self,
        backend_uri: str,
        host: str = "127.0.0.1",
        port: int = 0,
        authenticator=None,
        backend_user: Optional[str] = None,
        backend_password: Optional[str] = None,
        backend_cafile: Optional[str] = None,
    ):
        self.backend = backend_uri.rstrip("/")
        self.authenticator = authenticator
        self._backend_auth = None
        if backend_user is not None:
            from .auth import basic_auth_header

            self._backend_auth = basic_auth_header(
                backend_user, backend_password or ""
            )
        self._ssl_ctx = None
        if self.backend.startswith("https"):
            from .auth import client_ssl_context

            self._ssl_ctx = client_ssl_context(backend_cafile)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reject(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                if code == 401:
                    self.send_header(
                        "WWW-Authenticate", 'Basic realm="presto-proxy"'
                    )
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _client_principal(self):
                """Authenticated client identity, or None after a 401;
                (None, True) means auth is disabled."""
                if outer.authenticator is None:
                    return self.headers.get("X-Presto-User"), True
                from .auth import AuthenticationError, parse_basic_auth

                creds = parse_basic_auth(self.headers.get("Authorization"))
                if creds is None:
                    self._reject(401, {"error": "credentials required"})
                    return None, False
                try:
                    return outer.authenticator.authenticate(*creds), True
                except AuthenticationError as e:
                    self._reject(401, {"error": str(e)})
                    return None, False

            def _forward(self, method: str):
                principal, ok = self._client_principal()
                if not ok:
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else None
                req = urllib.request.Request(
                    outer.backend + self.path, data=body, method=method
                )
                for h in ("X-Presto-Session", "X-Presto-Source"):
                    v = self.headers.get(h)
                    if v:
                        req.add_header(h, v)
                # the PROXY-authenticated identity is what flows upstream
                # (the coordinator authorizes the backend principal to
                # impersonate via impersonation_principals) — never the
                # client's self-asserted header
                if principal:
                    req.add_header("X-Presto-User", principal)
                if outer._backend_auth:
                    req.add_header("Authorization", outer._backend_auth)
                try:
                    with urllib.request.urlopen(
                        req, timeout=60, context=outer._ssl_ctx
                    ) as resp:
                        payload = resp.read()
                        code = resp.status
                        ctype = resp.headers.get(
                            "Content-Type", "application/json"
                        )
                except urllib.error.HTTPError as e:
                    payload = e.read()
                    code = e.code
                    ctype = e.headers.get("Content-Type", "application/json")
                except urllib.error.URLError as e:
                    self._reject(
                        502, {"error": f"backend unreachable: {e.reason}"}
                    )
                    return
                payload = outer._rewrite(payload)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._forward("GET")

            def do_POST(self):
                self._forward("POST")

            def do_DELETE(self):
                self._forward("DELETE")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    def _rewrite(self, payload: bytes) -> bytes:
        """Point response URIs (nextUri etc.) back at the proxy so the
        client's whole conversation stays on this listener."""
        try:
            doc = json.loads(payload)
        except (ValueError, UnicodeDecodeError):
            return payload
        me = f"http://{self.host}:{self.port}"

        def walk(v):
            if isinstance(v, dict):
                return {k: walk(x) for k, x in v.items()}
            if isinstance(v, list):
                return [walk(x) for x in v]
            if isinstance(v, str) and v.startswith(self.backend):
                return me + v[len(self.backend):]
            return v

        return json.dumps(walk(doc)).encode()

    def start(self) -> "ProxyServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def uri(self) -> str:
        return f"http://{self.host}:{self.port}"
