"""HTTP cluster execution: node discovery, failure detection, and the
stage scheduler that runs fragmented plans across worker processes.

Re-designed equivalents (SURVEY L3 + L11 + §2.7):
* NodeManager — DiscoveryNodeManager + HeartbeatFailureDetector
  (failureDetector/HeartbeatFailureDetector.java:77): periodic /v1/status
  probes, consecutive-failure threshold marks a worker FAILED and excludes
  it from scheduling.
* HttpScheduler — SqlQueryScheduler + SqlStageExecution + HttpRemoteTask
  (execution/scheduler/SqlQueryScheduler.java:112): cuts the fragmented
  plan (plan/fragment.py Exchange tree) at exchange boundaries into
  stages, runs leaf stages as one task per worker over row-range splits,
  links consumer tasks to producer output buffers (worker w pulls hash
  partition w from every producer — the pull-based FIXED_HASH shuffle),
  and executes the root single-distribution fragment on the coordinator.

This is the DCN/multi-host data path; exec/dist.py's shard_map collectives
remain the intra-slice ICI path. No mid-query recovery: a failed task
fails the query (the reference behaves the same, SURVEY §5)."""

from __future__ import annotations

import base64
import itertools
import json
import pickle
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..plan import nodes as N
from ..plan.fragment import Exchange
from .worker import FragmentExecutor, RemoteSource, _pull_buffer
from .serde import deserialize_page


class NodeManager:
    """Tracks worker liveness via heartbeats; failed nodes are excluded
    from scheduling until they respond again."""

    def __init__(self, worker_uris: List[str], interval: float = 5.0,
                 failure_threshold: int = 3):
        self.workers = {u: {"state": "ACTIVE", "failures": 0} for u in worker_uris}
        self.interval = interval
        self.failure_threshold = failure_threshold
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "NodeManager":
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def active_workers(self) -> List[str]:
        return [u for u, s in self.workers.items() if s["state"] == "ACTIVE"]

    def probe_all(self):
        for uri, st in self.workers.items():
            try:
                with urllib.request.urlopen(f"{uri}/v1/status", timeout=2) as r:
                    ok = json.loads(r.read()).get("state") == "ACTIVE"
            except Exception:  # noqa: BLE001 - network failure IS the signal
                ok = False
            if ok:
                st["failures"] = 0
                st["state"] = "ACTIVE"
            else:
                st["failures"] += 1
                if st["failures"] >= self.failure_threshold:
                    st["state"] = "FAILED"

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.probe_all()


class TaskFailure(RuntimeError):
    pass


class HttpScheduler:
    """Executes a fragmented plan over HTTP workers; the coordinator runs
    the root fragment locally (its catalog serves coordinator-side scans
    of single-distribution subtrees, e.g. tiny dimension tables)."""

    def __init__(self, catalog, nodes: NodeManager):
        self.catalog = catalog
        self.nodes = nodes
        self._task_ids = itertools.count(1)

    # -- public --

    def run(self, root: N.PlanNode, query_id: Optional[str] = None):
        # snapshot membership for the whole query (threaded explicitly so
        # concurrent queries can't clobber each other): producer partition
        # counts must match consumer task counts even if a node fails
        # mid-query (the query then fails on the task, not on skew)
        workers = self.nodes.active_workers()
        if not workers:
            raise TaskFailure("no active workers")
        all_tasks: List[Tuple[str, str]] = []
        if query_id is None:
            import uuid

            # unique across sessions sharing these workers: per-query
            # memory accounting must never merge two queries
            query_id = f"q_{uuid.uuid4().hex[:12]}"
        try:
            fragment, specs = self._cut(root)
            sources = self._resolve_sources(
                specs, False, workers, all_tasks, query_id
            )
            ex = FragmentExecutor(self.catalog, {}, sources)
            return ex.run(fragment)
        finally:
            # free worker-side output buffers (reference: task results are
            # acknowledged and deleted after consumption)
            for uri, task_id in all_tasks:
                try:
                    req = urllib.request.Request(
                        f"{uri}/v1/task/{task_id}", method="DELETE"
                    )
                    urllib.request.urlopen(req, timeout=5).read()
                except Exception:  # noqa: BLE001 - cleanup is best-effort
                    pass

    # -- plan cutting --

    def _cut(self, node: N.PlanNode):
        """Replace each Exchange child with a RemoteSource; returns
        (fragment, {source_id: Exchange})."""
        specs: Dict[str, Exchange] = {}

        def walk(n):
            import dataclasses as dc

            if isinstance(n, Exchange):
                sid = f"s{len(specs)}"
                specs[sid] = n
                return RemoteSource(sid, tuple(n.fields))
            replace = {}
            for f in dc.fields(n):
                v = getattr(n, f.name)
                if isinstance(v, N.PlanNode):
                    nv = walk(v)
                    if nv is not v:
                        replace[f.name] = nv
                elif isinstance(v, tuple) and v and isinstance(v[0], N.PlanNode):
                    nv = tuple(walk(c) for c in v)
                    if nv != v:
                        replace[f.name] = nv
            return dc.replace(n, **replace) if replace else n

        return walk(node), specs

    @staticmethod
    def _has_scan(node: N.PlanNode) -> bool:
        if isinstance(node, N.TableScan):
            return True
        return any(HttpScheduler._has_scan(c) for c in node.children)

    # -- stage execution --

    def _resolve_sources(self, specs, sharded_consumer: bool,
                         workers: List[str], all_tasks,
                         query_id: Optional[str] = None):
        """Run producer stages for each exchange; returns either
        {sid: (kind, handles)} (sharded consumer) or {sid: [pages]}
        (coordinator consumer)."""
        resolved = {}
        for sid, ex in specs.items():
            if ex.kind == "repartition" and sharded_consumer:
                handles = self._run_sharded_stage(
                    ex.child, ("hash", ex.keys), workers, all_tasks, query_id
                )
                resolved[sid] = ("repartition", handles)
            else:
                # gather / replicate — and repartition consumed by the
                # coordinator itself, which reads everything anyway (hash
                # partitioning there would just drop partitions != 0).
                # Replicated outputs are pulled by EVERY consumer without
                # acks, so their producer buffers must be unbounded.
                handles = self._run_sharded_stage(
                    ex.child, ("single",), workers, all_tasks, query_id,
                    unbounded_output=(
                        sharded_consumer and ex.kind == "replicate"
                    ),
                )
                resolved[sid] = ("gather", handles)
        if sharded_consumer:
            return resolved
        # coordinator-side: materialize every source into Pages now
        out = {}
        for sid, (kind, handles) in resolved.items():
            pages = []
            for uri, task in handles:
                for data in _pull_buffer(uri, task, 0):
                    pages.append(deserialize_page(data))
            out[sid] = pages
        return out

    def _run_sharded_stage(self, node: N.PlanNode, output,
                           all_workers: List[str], all_tasks,
                           query_id: Optional[str] = None,
                           unbounded_output: bool = False) -> List[Tuple[str, str]]:
        """One task per worker for sharded stages (splits/repartition
        inputs); scan-less single-distribution stages run as ONE task so
        rows are never duplicated. Returns [(worker_uri, task_id)]."""
        nw = len(all_workers)
        fragment, specs = self._cut(node)
        sharded = self._has_scan(fragment) or any(
            ex.kind == "repartition" for ex in specs.values()
        )
        workers = all_workers if sharded else all_workers[:1]
        child_resolved = self._resolve_sources(
            specs, True, all_workers, all_tasks, query_id
        )

        # row-range splits per scanned table
        tables = self._scan_tables(fragment)
        ranges = {}
        for t in tables:
            total = self.catalog.row_count(t)
            exact = getattr(self.catalog, "exact_row_count", None)
            if exact is not None:
                total = exact(t)
            per = -(-total // nw)
            ranges[t] = [
                (w * per, min((w + 1) * per, total)) for w in range(nw)
            ]

        frag_b64 = base64.b64encode(pickle.dumps(fragment)).decode()
        part_keys_b64 = None
        nparts = 1
        if output[0] == "hash":
            part_keys_b64 = base64.b64encode(pickle.dumps(output[1])).decode()
            nparts = nw

        handles = []
        for w, uri in enumerate(workers):
            sources = {}
            for sid, (kind, child_handles) in child_resolved.items():
                if kind == "repartition":
                    # partition w has exactly ONE consumer: acks may free
                    # producer pages as this task consumes them
                    locs = [(u, t, w) for (u, t) in child_handles]
                    exclusive = True
                else:  # gather/replicate: every consumer pulls buffer 0
                    locs = [(u, t, 0) for (u, t) in child_handles]
                    exclusive = len(workers) == 1
                sources[sid] = {"locations": locs, "exclusive": exclusive}
            spec = {
                "fragment": frag_b64,
                "splits": {t: list(ranges[t][w]) for t in tables},
                "sources": sources,
                "partition_keys": part_keys_b64,
                "num_partitions": nparts,
                "query_id": query_id,
                "buffer_unbounded": unbounded_output,
            }
            task_id = f"t_{next(self._task_ids)}"
            self._post_task(uri, task_id, spec)
            handles.append((uri, task_id))
            all_tasks.append((uri, task_id))
        # surface task failures eagerly (fail the query like the reference)
        for uri, task_id in handles:
            status = self._task_status(uri, task_id)
            if status.get("state") == "FAILED":
                raise TaskFailure(
                    f"task {task_id} on {uri} failed:\n{status.get('error')}"
                )
        return handles

    @staticmethod
    def _scan_tables(node: N.PlanNode) -> List[str]:
        out = []

        def walk(n):
            if isinstance(n, N.TableScan):
                out.append(n.table)
            for c in n.children:
                walk(c)

        walk(node)
        return sorted(set(out))

    # -- HTTP --

    @staticmethod
    def _post_task(uri: str, task_id: str, spec: dict):
        body = json.dumps(spec).encode()
        req = urllib.request.Request(
            f"{uri}/v1/task/{task_id}", data=body, method="POST"
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    @staticmethod
    def _task_status(uri: str, task_id: str) -> dict:
        with urllib.request.urlopen(
            f"{uri}/v1/task/{task_id}", timeout=300
        ) as resp:
            return json.loads(resp.read())


class ClusterMemoryManager:
    """Coordinator-side cluster memory management (reference
    memory/ClusterMemoryManager.java:89,210 + LowMemoryKiller.java:26):
    polls every worker's /v1/memory, aggregates per-query reservation
    across the cluster, and when any worker is memory-blocked kills the
    query with the LARGEST total reservation (the TotalReservation
    strategy) by aborting its tasks on every worker."""

    def __init__(self, nodes: NodeManager, interval: float = 0.25,
                 on_kill=None, grace_polls: int = 4):
        self.nodes = nodes
        self.interval = interval
        self.on_kill = on_kill
        self.grace_polls = grace_polls  # sustained blockage before a kill
        self._blocked_streak = 0
        self.killed: List[str] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "ClusterMemoryManager":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - keep polling
                pass

    def poll_once(self) -> Optional[str]:
        """One manager cycle; returns the killed query id, if any."""
        states = []
        for uri in self.nodes.active_workers():
            try:
                with urllib.request.urlopen(
                    f"{uri}/v1/memory", timeout=5
                ) as resp:
                    states.append((uri, json.loads(resp.read())))
            except Exception:  # noqa: BLE001 - failure detector's job
                continue
        # live gauge snapshot for system.jmx.memory
        self.last_snapshot = {
            uri: {
                "reserved": int(st.get("reserved") or 0),
                "limit": st.get("limit") or 0,
                "blocked": len(st.get("blocked") or ()),
            }
            for uri, st in states
        }
        blocked = any(st.get("blocked") for _, st in states)
        if not blocked:
            self._blocked_streak = 0
            return None
        # transient blocking is normal flow control (acks free bytes
        # continuously); only SUSTAINED exhaustion triggers the killer
        self._blocked_streak += 1
        if self._blocked_streak < self.grace_polls:
            return None
        self._blocked_streak = 0
        victim = self.choose_victim(states)
        if victim is None:
            return None
        self.kill(victim)
        return victim

    @staticmethod
    def choose_victim(states) -> Optional[str]:
        """TotalReservation: the query holding the most bytes cluster-wide
        (blocked-but-unreserved queries are victims of last resort)."""
        totals: Dict[str, int] = {}
        for _uri, st in states:
            for qid, nbytes in (st.get("queries") or {}).items():
                totals[qid] = totals.get(qid, 0) + int(nbytes)
            for qid in st.get("blocked") or ():
                totals.setdefault(qid, 0)
        if not totals:
            return None
        return max(totals, key=lambda q: (totals[q], q))

    def kill(self, query_id: str) -> None:
        for uri in self.nodes.active_workers():
            try:
                req = urllib.request.Request(
                    f"{uri}/v1/query/{query_id}", method="DELETE"
                )
                urllib.request.urlopen(req, timeout=5).read()
            except Exception:  # noqa: BLE001 - best effort per worker
                pass
        self.killed.append(query_id)
        if self.on_kill is not None:
            self.on_kill(query_id)


class HttpClusterSession:
    """Session facade executing SQL over an HTTP worker cluster — the
    DistributedQueryRunner analog for the DCN path."""

    def __init__(self, catalog, nodes: NodeManager,
                 broadcast_threshold=None,  # None = cost-based
                 memory_manager: bool = False):
        from ..session import Session

        self._planner = Session(catalog)  # reuse parse/plan/fragment
        self._planner.mesh = None
        self.catalog = catalog
        self.broadcast_threshold = broadcast_threshold
        self.scheduler = HttpScheduler(catalog, nodes)
        self._query_ids = itertools.count(1)
        self.memory_manager = (
            ClusterMemoryManager(nodes).start() if memory_manager else None
        )

    def query(self, sql: str):
        from ..plan.fragment import fragment_plan
        from ..session import QueryResult

        node = self._planner.plan(sql)
        node = fragment_plan(node, self.catalog, self.broadcast_threshold,
                             num_workers=max(len(self.scheduler.nodes.active_workers()), 2))
        page = self.scheduler.run(node, query_id=f"q_{next(self._query_ids)}")
        return QueryResult(page, node.titles)

    def close(self):
        if self.memory_manager is not None:
            self.memory_manager.stop()
