"""HTTP cluster execution: node discovery, failure detection, and the
stage scheduler that runs fragmented plans across worker processes.

Re-designed equivalents (SURVEY L3 + L11 + §2.7):
* NodeManager — DiscoveryNodeManager + HeartbeatFailureDetector
  (failureDetector/HeartbeatFailureDetector.java:77): periodic /v1/status
  probes, consecutive-failure threshold marks a worker FAILED and excludes
  it from scheduling. Consecutive TASK failures additionally BLACKLIST a
  worker (drained from scheduling even though its /v1/status is healthy —
  the round-5 failure mode was exactly a live-but-faulting worker); after
  `blacklist_recovery` seconds a healthy probe re-admits it. State
  transitions emit worker-up/down events through server/events.py.
* HttpScheduler — SqlQueryScheduler + SqlStageExecution + HttpRemoteTask
  (execution/scheduler/SqlQueryScheduler.java:112): cuts the fragmented
  plan (plan/fragment.py Exchange tree) at exchange boundaries into
  stages, runs leaf stages as one task per worker over row-range splits,
  links consumer tasks to producer output buffers (worker w pulls hash
  partition w from every producer — the pull-based FIXED_HASH shuffle),
  and executes the root single-distribution fragment on the coordinator.

Fault tolerance (docs/fault-tolerance.md): unlike the reference (a worker
loss fails the whole query, SURVEY §5), tasks that fail to START — POST
refused, or FAILED at the eager status check with a retryable cause — are
retried with exponential backoff + jitter onto an alternate healthy
worker, up to `max_task_retries` alternates. Failures past that point
(mid-stream faults surfacing on the results pull) trigger a bounded
QUERY-level re-execution against a fresh worker snapshot. Fatal causes
(low-memory kill, memory exhaustion, protocol violations) are never
retried. Sibling tasks of an unrecoverable failure are canceled eagerly.

This is the DCN/multi-host data path; exec/dist.py's shard_map collectives
remain the intra-slice ICI path."""

from __future__ import annotations

import base64
import dataclasses
import itertools
import json
import os
import pickle
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..plan import nodes as N
from ..plan.fragment import Exchange
from . import knobs
from .exchange import ExchangeClient, ExchangeError, ExchangeStats
from .serde import WireStats, negotiate
from .worker import (
    _FATAL_MARKERS,
    FragmentExecutor,
    RemoteSource,
)


def _retryable_message(msg: str) -> bool:
    """Classify an unstructured failure message: fatal causes would recur
    identically on any worker / attempt (see worker._classify_failure)."""
    return not any(m in msg for m in _FATAL_MARKERS)


def _http_error_details(e: "urllib.error.HTTPError") -> Tuple[str, bool]:
    """(detail, retryable) from a worker's structured error response —
    a POST 500 carries errorInfo.retryable, which must not be blindly
    retried away when it says false."""
    try:
        payload = json.loads(e.read())
    except Exception:  # noqa: BLE001 — unparseable error body: fall back
        # to classifying the HTTPError's own message below
        payload = {}
    if not isinstance(payload, dict):
        payload = {}
    detail = payload.get("error") or str(e)
    info = payload.get("errorInfo") or {}
    return detail, bool(info.get("retryable", _retryable_message(detail)))


class NodeManager:
    """Tracks worker liveness via heartbeats; failed nodes are excluded
    from scheduling until they respond again. Consecutive task failures
    blacklist (drain) a worker with timed re-admission."""

    def __init__(self, worker_uris: List[str], interval: float = 5.0,
                 failure_threshold: int = 3,
                 task_failure_threshold: int = 3,
                 blacklist_recovery: float = 30.0,
                 event_bus=None):
        self.workers = {
            u: {"state": "ACTIVE", "failures": 0, "task_failures": 0,
                "blacklisted_at": None, "wire": None}
            for u in worker_uris
        }
        self.interval = interval
        self.failure_threshold = failure_threshold
        self.task_failure_threshold = task_failure_threshold
        self.blacklist_recovery = blacklist_recovery
        self.event_bus = event_bus
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "NodeManager":
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def active_workers(self) -> List[str]:
        with self._lock:
            return [
                u for u, s in self.workers.items() if s["state"] == "ACTIVE"
            ]

    def all_workers(self) -> List[str]:
        with self._lock:
            return list(self.workers)

    # -- state transitions (events fire outside the lock) --

    def _set_state(self, uri: str, state: str, reason: str) -> None:
        with self._lock:
            st = self.workers[uri]
            if st["state"] == state:
                return
            st["state"] = state
            if state == "BLACKLISTED":
                st["blacklisted_at"] = time.time()
            elif state == "ACTIVE":
                st["failures"] = 0
                st["task_failures"] = 0
                st["blacklisted_at"] = None
        if self.event_bus is not None:
            self.event_bus.fire_worker_state(uri, state, reason)

    def record_task_failure(self, uri: str, reason: str = "") -> None:
        """A task on this worker failed to start/run. N consecutive
        failures drain the worker (reference analog: the coordinator
        operator manually shutting down a flaky node)."""
        with self._lock:
            st = self.workers.get(uri)
            if st is None:
                return
            st["task_failures"] += 1
            drain = (
                st["state"] == "ACTIVE"
                and st["task_failures"] >= self.task_failure_threshold
            )
        if drain:
            self._set_state(
                uri, "BLACKLISTED",
                f"{self.task_failure_threshold} consecutive task failures"
                + (f": {reason[:120]}" if reason else ""),
            )

    def record_task_success(self, uri: str) -> None:
        with self._lock:
            st = self.workers.get(uri)
            if st is not None:
                st["task_failures"] = 0

    def wire_caps(self, uri: str) -> Optional[dict]:
        """Cached wire capabilities a worker advertised through its
        status handshake; fetched once on demand when the heartbeat loop
        has not probed yet. None = unknown (negotiation degrades to the
        baseline wire format for the whole fleet). A failed probe is
        negatively cached for one heartbeat interval so an unreachable
        worker costs ONE query a 2s stall, not every query."""
        with self._lock:
            st = self.workers.get(uri)
            if st is None:
                return None
            cached = st.get("wire")
            failed_at = st.get("wire_probe_failed_at")
        if cached is not None:
            return cached
        if failed_at is not None and time.time() - failed_at < self.interval:
            return None
        caps = None
        try:
            with urllib.request.urlopen(f"{uri}/v1/status", timeout=2) as r:
                caps = json.loads(r.read()).get("wire")
        except Exception:  # noqa: BLE001 - unknown peer stays baseline
            caps = None
        with self._lock:
            st = self.workers.get(uri)
            if st is not None:
                if isinstance(caps, dict):
                    st["wire"] = caps
                    st.pop("wire_probe_failed_at", None)
                else:
                    st["wire_probe_failed_at"] = time.time()
        return caps if isinstance(caps, dict) else None

    def wire_caps_all(self, uris: List[str]) -> List[Optional[dict]]:
        """wire_caps for a worker snapshot, fetching the uncached ones
        CONCURRENTLY — query submit must not pay a serial 2s-per-worker
        stall while the heartbeat cache warms up. A probe that misses
        the join window reports None (baseline degradation) instead of
        being re-issued serially; the daemon thread still warms the
        cache for the next query."""
        results: Dict[str, Optional[dict]] = {}
        with self._lock:
            for u in uris:
                st = self.workers.get(u)
                if st is not None and st.get("wire") is not None:
                    results[u] = st["wire"]
        missing = [u for u in uris if u not in results]
        if len(missing) == 1:
            results[missing[0]] = self.wire_caps(missing[0])
        elif missing:
            def probe(u):
                results[u] = self.wire_caps(u)

            threads = [
                threading.Thread(target=probe, args=(u,), daemon=True)
                for u in missing
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=3)
        return [results.get(u) for u in uris]

    def probe_all(self):
        for uri in self.all_workers():
            try:
                with urllib.request.urlopen(f"{uri}/v1/status", timeout=2) as r:
                    payload = json.loads(r.read())
                    ok = payload.get("state") == "ACTIVE"
                    # cache what the worker advertises NOW — including
                    # clearing a stale entry when a rolled-back build at
                    # the same URI stops advertising caps (else peers
                    # would keep sending it undecodable v2 pages)
                    caps = payload.get("wire")
                    with self._lock:
                        st = self.workers.get(uri)
                        if st is not None:
                            st["wire"] = (
                                caps if isinstance(caps, dict) else None
                            )
            except Exception:  # noqa: BLE001 - network failure IS the signal
                ok = False
            with self._lock:
                st = self.workers[uri]
                state = st["state"]
                if ok:
                    st["failures"] = 0
                else:
                    st["failures"] += 1
                # only an ACTIVE worker degrades to FAILED: a BLACKLISTED
                # worker keeps serving its drain penalty (otherwise a
                # restart would launder BLACKLISTED -> FAILED -> ACTIVE
                # and skip the recovery window)
                probe_failed = (
                    not ok
                    and state == "ACTIVE"
                    and st["failures"] >= self.failure_threshold
                )
                blacklist_done = (
                    ok
                    and state == "BLACKLISTED"
                    and st["blacklisted_at"] is not None
                    and time.time() - st["blacklisted_at"]
                    >= self.blacklist_recovery
                )
            if probe_failed:
                self._set_state(uri, "FAILED", "heartbeat probes exhausted")
            elif ok and state == "FAILED":
                self._set_state(uri, "ACTIVE", "heartbeat recovered")
            elif blacklist_done:
                # drained worker served its penalty and probes healthy:
                # re-admit (half-open — the next task failure streak
                # drains it again)
                self._set_state(uri, "ACTIVE", "blacklist recovery elapsed")

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.probe_all()


def _has_remote_source(node) -> bool:
    """True when a producer subtree pulls from a deeper exchange — its
    static estimate would bottom out at the RemoteSource default, so
    observed-vs-estimated comparisons there are meaningless."""
    if isinstance(node, RemoteSource):
        return True
    return any(_has_remote_source(c) for c in node.children)


class TaskFailure(RuntimeError):
    """A task (or its stage) failed. Carries the worker URI, task id,
    attempt number, and whether the cause is retryable on another
    worker / query attempt."""

    def __init__(self, message: str, uri: str = "", task_id: str = "",
                 attempt: int = 1, retryable: bool = True):
        super().__init__(message)
        self.uri = uri
        self.task_id = task_id
        self.attempt = attempt
        self.retryable = retryable


@dataclasses.dataclass
class SchedulerStats:
    """Observable retry accounting (acceptance: retries must be visible,
    not inferred from timing)."""

    task_retries: int = 0
    query_retries: int = 0
    tasks_failed: int = 0
    worker_failures: Dict[str, int] = dataclasses.field(default_factory=dict)
    last_error: str = ""
    # cross-task dynamic filtering (exec/dynfilter.py): filters shipped
    # from build stages into probe-stage task specs, seconds spent in the
    # bounded wait, and waits that expired (proceed-without-filter)
    dynfilters_shipped: int = 0
    dynfilter_wait_s: float = 0.0
    dynfilter_timeouts: int = 0
    # mid-query adaptive replans (plan/history.py): attempts abandoned
    # at an exchange boundary because the observed stage output
    # contradicted the estimate grossly enough to re-plan downstream
    adaptive_replans: int = 0
    # pipelined exchange observability (server/exchange.py): per-source
    # pull stats of the LAST query attempt (coordinator-side gathers) +
    # best-effort producer-side encode stats polled from task statuses,
    # and the wire capability set the attempt negotiated
    exchange: Dict[str, dict] = dataclasses.field(default_factory=dict)
    wire_caps: Optional[dict] = None
    # memory-arbitration rollup polled from task statuses (worker-side
    # memoryStats/spillStats): disk bytes spilled, revocations absorbed,
    # spill events seen — the cluster half of EXPLAIN ANALYZE's memory line
    memory: Dict[str, object] = dataclasses.field(default_factory=dict)
    # hierarchical-exchange rollup (server/hier.py) of the LAST query:
    # mid-tree repartition producers are never pulled by the coordinator
    # (their consumers are other workers), so their hier snapshots are
    # folded query-wide by the final status sweep (_collect_task_obs)
    hier: Dict[str, object] = dataclasses.field(default_factory=dict)
    # serving-cache counters (exec/qcache.py snapshot_all) refreshed after
    # every cluster query — plan/result hits the coordinator served plus
    # the process-wide kernel cache
    caches: Optional[dict] = None

    def snapshot(self) -> dict:
        return dataclasses.asdict(self)


class HttpScheduler:
    """Executes a fragmented plan over HTTP workers; the coordinator runs
    the root fragment locally (its catalog serves coordinator-side scans
    of single-distribution subtrees, e.g. tiny dimension tables)."""

    def __init__(self, catalog, nodes: NodeManager,
                 max_task_retries: Optional[int] = None,
                 max_query_retries: Optional[int] = None,
                 task_deadline: Optional[float] = None,
                 status_deadline: float = 10.0,
                 status_timeout: float = 15.0,
                 backoff_base: float = 0.2,
                 backoff_cap: float = 5.0):
        self.catalog = catalog
        self.nodes = nodes
        self._task_ids = itertools.count(1)
        env = os.environ.get
        self.max_task_retries = (
            int(env("PRESTO_TPU_TASK_RETRIES", "3"))
            if max_task_retries is None else max_task_retries
        )
        self.max_query_retries = (
            int(env("PRESTO_TPU_QUERY_RETRIES", "2"))
            if max_query_retries is None else max_query_retries
        )
        # wall ceiling on any single task's results stream: a wedged
        # worker (RUNNING forever, producing nothing) fails the pull
        # instead of hanging the coordinator — the round-5 relay stall
        self.task_deadline = (
            knobs.task_deadline_s()
            if task_deadline is None else task_deadline
        )
        self.status_deadline = status_deadline
        self.status_timeout = status_timeout
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        # bounded wait for a build stage to publish dynamic-filter
        # summaries before the probe stage launches; expiry degrades to
        # proceed-without-filter (reference: dynamic filtering's
        # collection timeout). 0 disables cross-task shipping.
        self.dynfilter_wait = float(env("PRESTO_TPU_DYNFILTER_WAIT_S", "10"))
        self.stats = SchedulerStats()
        self._lock = threading.Lock()

    # -- public --

    def record_caches(self, snapshot: dict) -> None:
        """Publish serving-cache counters into stats. Sessions call this
        after every query, concurrent with worker status polls mutating
        stats under _lock — the write must take the same lock."""
        with self._lock:
            self.stats.caches = snapshot
            from ..obs.export import export_scheduler_stats

            # republish the cumulative scheduler counters as gauges once
            # per query (idempotent; the registry takes its own lock
            # inside ours, never the reverse)
            export_scheduler_stats(self.stats)

    def stats_snapshot(self) -> dict:
        """Point-in-time copy of SchedulerStats for EXPLAIN ANALYZE and
        the stats surfaces; reading fields off the live object would
        race the pollers mid-update."""
        with self._lock:
            return self.stats.snapshot()

    def run(self, root: N.PlanNode, query_id: Optional[str] = None,
            trace_ctx: Optional[tuple] = None, adapt: bool = True):
        """Execute with bounded query-level re-execution: a retryable
        failure that escaped per-task retry (e.g. a mid-stream worker
        loss) re-runs the whole plan against a fresh worker snapshot.

        `trace_ctx` is the observability plane's (Trace, parent span_id)
        pair (docs/observability.md): each query-level attempt gets its
        own child span, so a retried query shows up as SIBLING attempt
        subtrees, never an overwrite."""
        if query_id is None:
            import uuid

            # unique across sessions sharing these workers: per-query
            # memory accounting must never merge two queries
            query_id = f"q_{uuid.uuid4().hex[:12]}"
        trace = trace_ctx[0] if trace_ctx else None
        for attempt in range(self.max_query_retries + 1):
            # distinct per-attempt query id: a prior attempt's dying
            # tasks must not share memory accounting with the re-run
            qid = query_id if attempt == 0 else f"{query_id}.r{attempt}"
            aspan = None
            if trace is not None:
                aspan = trace.begin(
                    f"attempt {attempt}", parent_id=trace_ctx[1],
                    query_id=qid,
                )
            try:
                result = self._run_attempt(
                    root, qid,
                    tctx=(trace, aspan.span_id) if trace else None,
                    adapt=adapt,
                )
                if trace is not None:
                    trace.finish(aspan)
                return result
            except RuntimeError as exc:
                if trace is not None:
                    trace.finish(aspan, "error", error=str(exc)[:200])
                retryable = getattr(exc, "retryable", None)
                if retryable is None:
                    retryable = _retryable_message(str(exc))
                if not retryable or attempt >= self.max_query_retries:
                    raise
                # a MID-STREAM failure attributed to a worker counts
                # toward its blacklist streak too — a live-but-faulting
                # worker must drain even when its tasks start cleanly
                uri = getattr(exc, "uri", "")
                if uri:
                    self._note_task_failure(uri, str(exc))
                with self._lock:
                    self.stats.query_retries += 1
                    self.stats.last_error = str(exc)[:300]
                time.sleep(self._backoff(attempt))
                if not self.nodes.active_workers():
                    raise

    def _run_attempt(self, root: N.PlanNode, query_id: str,
                     tctx: Optional[tuple] = None, adapt: bool = True):
        # snapshot membership for the whole attempt (threaded explicitly
        # so concurrent queries can't clobber each other): producer
        # partition counts must match consumer task counts even if a node
        # fails mid-query (per-task retry then re-posts the SAME spec to
        # an alternate member of the snapshot)
        workers = self.nodes.active_workers()
        if not workers:
            raise TaskFailure("no active workers", retryable=False)
        # wire-format handshake: intersect the snapshot's advertised
        # capabilities (+ the coordinator's own) once per attempt and
        # ship the result in every task spec — a mixed fleet agrees on
        # codecs/encodings instead of failing on deserialize
        wire_caps = negotiate(self.nodes.wire_caps_all(workers))
        with self._lock:
            self.stats.wire_caps = wire_caps
            self.stats.exchange = {}
            self.stats.memory = {}
            self.stats.hier = {}
        all_tasks: List[Tuple[str, str, bool]] = []
        try:
            fragment, specs = self._cut(root)
            sources = self._resolve_sources(
                specs, False, workers, all_tasks, query_id,
                dyn_links=self._dyn_links(fragment, specs),
                dyn_values={},
                wire_caps=wire_caps,
                tctx=tctx,
                adapt=adapt,
            )
            rspan = (
                tctx[0].begin("root-fragment", parent_id=tctx[1])
                if tctx else None
            )
            ex = FragmentExecutor(self.catalog, {}, sources)
            try:
                result = ex.run(fragment)
            except Exception:
                if rspan is not None:
                    tctx[0].finish(rspan, "error")
                raise
            if rspan is not None:
                tctx[0].finish(rspan)
            return result
        finally:
            # sweep final worker span + hier payloads into the merged
            # accounting BEFORE cancellation deletes task state
            self._collect_task_obs(all_tasks, tctx)
            # free worker-side output buffers (reference: task results are
            # acknowledged and deleted after consumption); on failure this
            # doubles as sibling-task cancellation
            self._cancel_tasks(all_tasks)

    def _collect_task_obs(self, tasks: List[Tuple[str, str, bool]],
                          tctx: Optional[tuple]) -> None:
        """Final merge sweep: pull task status once and fold its span
        payload into the query trace plus its hierarchical-exchange
        snapshot into the query rollup. Mid-tree producer stages are
        never status-polled on the happy path (their consumers are other
        workers), so without this sweep their spans AND their hier stats
        would be lost. With tracing off, only partitioned-output
        producers are polled (the sole carriers of hier stats) — the
        common untraced single-stage query pays zero extra round-trips.
        Tasks from failed POSTs 404 here — best effort by design."""
        trace = tctx[0] if tctx is not None else None
        if trace is None:
            tasks = [t for t in tasks if t[2]]
        if not tasks:
            return
        from ..obs.export import export_hier_stats
        from .hier import HierExchangeStats

        hier = HierExchangeStats()
        for uri, task_id, _partitioned in tasks:
            try:
                st = self._task_status(uri, task_id)
            except Exception:  # noqa: BLE001 — observability, best effort
                continue
            if trace is not None:
                trace.add_remote(st.get("spans") or ())
            hier.merge_snapshot(
                (st.get("exchangeStats") or {}).get("hier")
            )
        snap = hier.snapshot()
        if snap.get("exchanges") or snap.get("fallbacks"):
            with self._lock:
                self.stats.hier = snap
            export_hier_stats(hier, role="gather")

    def _cancel_tasks(self, tasks: List[Tuple[str, str, bool]]) -> None:
        for uri, task_id, _partitioned in tasks:
            try:
                req = urllib.request.Request(
                    f"{uri}/v1/task/{task_id}", method="DELETE"
                )
                urllib.request.urlopen(req, timeout=5).read()
            except Exception:  # noqa: BLE001 - cleanup is best-effort
                pass

    def _backoff(self, attempt: int) -> float:
        """Exponential backoff with full jitter (attempt counts from 0)."""
        ceiling = min(self.backoff_base * (2 ** attempt), self.backoff_cap)
        return random.uniform(0, ceiling)

    # -- plan cutting --

    def _cut(self, node: N.PlanNode):
        """Replace each Exchange child with a RemoteSource; returns
        (fragment, {source_id: Exchange})."""
        specs: Dict[str, Exchange] = {}

        def walk(n):
            import dataclasses as dc

            if isinstance(n, Exchange):
                sid = f"s{len(specs)}"
                specs[sid] = n
                return RemoteSource(sid, tuple(n.fields))
            replace = {}
            for f in dc.fields(n):
                v = getattr(n, f.name)
                if isinstance(v, N.PlanNode):
                    nv = walk(v)
                    if nv is not v:
                        replace[f.name] = nv
                elif isinstance(v, tuple) and v and isinstance(v[0], N.PlanNode):
                    nv = tuple(walk(c) for c in v)
                    if nv != v:
                        replace[f.name] = nv
            return dc.replace(n, **replace) if replace else n

        return walk(node), specs

    @staticmethod
    def _has_scan(node: N.PlanNode) -> bool:
        if isinstance(node, N.TableScan):
            return True
        return any(HttpScheduler._has_scan(c) for c in node.children)

    # -- cross-task dynamic filters (exec/dynfilter.py) --

    @staticmethod
    def _dyn_links(fragment: N.PlanNode, specs: Dict[str, Exchange]):
        """(produce, consume) stage links for dynamic filters crossing
        task boundaries. produce: source_id -> [(filter_id, channel)] for
        joins in `fragment` whose BUILD side is directly a RemoteSource —
        that producer stage's output IS the build rows, so its tasks can
        summarize the key channel. consume: source_id -> {filter_id} for
        producer subtrees containing annotated probe scans."""
        from ..expr import ir

        produce: Dict[str, list] = {}

        def walk(n):
            if isinstance(n, (N.Join, N.SemiJoin)) and getattr(
                n, "dynamic_filters", ()
            ):
                build = n.children[1]
                keys = (
                    n.right_keys
                    if isinstance(n, N.Join)
                    else n.source_keys
                )
                if isinstance(build, RemoteSource):
                    fields = {f for f, _ in build.fields}
                    for fid, i, _c in n.dynamic_filters:
                        k = keys[i]
                        if isinstance(k, ir.ColumnRef) and k.name in fields:
                            produce.setdefault(build.source_id, []).append(
                                (fid, k.name)
                            )
            for c in n.children:
                walk(c)

        walk(fragment)

        consume: Dict[str, set] = {}

        def scan_fids(n, acc: set):
            if isinstance(n, N.TableScan):
                for fid, *_rest in n.dynamic_filters:
                    acc.add(fid)
            for c in n.children:
                scan_fids(c, acc)

        for sid, ex in specs.items():
            acc: set = set()
            scan_fids(ex.child, acc)
            if acc:
                consume[sid] = acc
        return produce, consume

    def _await_dyn_filters(self, handles, entries, dyn_values: dict) -> None:
        """Bounded wait for a build stage's tasks to FINISH, then merge
        their per-task summaries into `dyn_values`. Expiry or a failed
        task drops the filter (proceed-without-filter) — dynamic filters
        are an optimization, never a correctness dependency."""
        from ..exec.dynfilter import merge_summaries

        deadline = time.time() + self.dynfilter_wait
        t0 = time.perf_counter()
        per_task: List[Optional[dict]] = []
        timed_out = False
        for uri, task in handles:
            status = None
            while time.time() < deadline:
                try:
                    status = self._task_status(uri, task)
                except TaskFailure:
                    status = None
                    break
                if status.get("state") in ("FINISHED", "FAILED"):
                    break
                time.sleep(0.05)
            else:
                timed_out = True
            if status is None or status.get("state") != "FINISHED":
                per_task.append(None)
            else:
                per_task.append(status.get("dynFilters") or {})
        with self._lock:
            self.stats.dynfilter_wait_s += time.perf_counter() - t0
            if timed_out:
                self.stats.dynfilter_timeouts += 1
        if any(p is None for p in per_task):
            return  # a task failed/timed out: filter untrusted
        for fid, _channel in entries:
            merged = merge_summaries([p.get(fid) for p in per_task])
            if merged is not None:
                dyn_values[fid] = merged
                with self._lock:
                    self.stats.dynfilters_shipped += 1

    # -- stage execution --

    def _resolve_sources(self, specs, sharded_consumer: bool,
                         workers: List[str], all_tasks,
                         query_id: Optional[str] = None,
                         dyn_links=None, dyn_values: Optional[dict] = None,
                         wire_caps: Optional[dict] = None,
                         tctx: Optional[tuple] = None,
                         adapt: bool = False):
        """Run producer stages for each exchange; returns either
        {sid: (kind, handles)} (sharded consumer) or {sid: [pages]}
        (coordinator consumer).

        Dynamic-filter link scheduling: a stage producing a filter some
        sibling stage's scans consume launches FIRST; the coordinator then
        waits (bounded) for its summaries and ships the merged filter in
        the later stages' task specs — the cross-task half of dynamic
        filtering (exec/dynfilter.py)."""
        produce, consume = dyn_links if dyn_links else ({}, {})
        if dyn_values is None:
            dyn_values = {}
        wanted: set = set()
        for fids in consume.values():
            wanted |= fids
        if self.dynfilter_wait <= 0:
            produce, consume, wanted = {}, {}, set()

        def is_producer(sid):
            return any(f in wanted for f, _ in produce.get(sid, ()))

        order = sorted(specs, key=lambda sid: (not is_producer(sid),))
        resolved = {}
        for sid in order:
            ex = specs[sid]
            entries = [
                (f, ch) for f, ch in produce.get(sid, ()) if f in wanted
            ]
            if ex.kind == "repartition" and sharded_consumer:
                handles = self._run_sharded_stage(
                    ex.child, ("hash", ex.keys), workers, all_tasks,
                    query_id, dyn_produce=entries, dyn_values=dyn_values,
                    wire_caps=wire_caps, tctx=tctx,
                )
                resolved[sid] = ("repartition", handles)
            else:
                # gather / replicate — and repartition consumed by the
                # coordinator itself, which reads everything anyway (hash
                # partitioning there would just drop partitions != 0).
                # Replicated outputs are pulled by EVERY consumer without
                # acks, so their producer buffers must be unbounded.
                handles = self._run_sharded_stage(
                    ex.child, ("single",), workers, all_tasks, query_id,
                    unbounded_output=(
                        sharded_consumer and ex.kind == "replicate"
                    ),
                    dyn_produce=entries, dyn_values=dyn_values,
                    wire_caps=wire_caps, tctx=tctx,
                )
                resolved[sid] = ("gather", handles)
            if entries and any(
                other != sid
                and (consume.get(other, set()) & {f for f, _ in entries})
                for other in specs
            ):
                self._await_dyn_filters(handles, entries, dyn_values)
        if sharded_consumer:
            return resolved
        # coordinator-side: materialize every source into Pages through
        # the PIPELINED exchange client — one puller per producer task,
        # multi-page responses, deserialization overlapped with in-flight
        # pulls (replaces the round-5 sequential one-thread drain)
        out = {}
        for sid, (kind, handles) in resolved.items():
            ex_stats = ExchangeStats()
            client = ExchangeClient(
                [(uri, task, 0) for uri, task in handles],
                ack=True,
                deadline=self.task_deadline,
                stats=ex_stats,
            )
            gspan = (
                tctx[0].begin(f"exchange {sid}", parent_id=tctx[1])
                if tctx else None
            )
            pages = []
            try:
                for page in client.pages():
                    pages.append(page)
            except ExchangeError as e:
                # attribute the mid-stream failure to its worker so
                # query-level retry can feed the blacklist. Pull stats
                # only — polling still-RUNNING producers' statuses here
                # would add ~0.5s of server-side wait per producer to
                # every retry attempt
                self._record_exchange(sid, ex_stats, ())
                if gspan is not None:
                    tctx[0].finish(gspan, "error", error=str(e)[:200])
                raise TaskFailure(
                    str(e), uri=e.uri, task_id=e.task_id,
                    retryable=_retryable_message(str(e)),
                ) from None
            self._record_exchange(sid, ex_stats, handles)
            if gspan is not None:
                snap = ex_stats.snapshot()
                tctx[0].finish(
                    gspan, pages=snap["pages"], bytes=snap["wire_bytes"],
                    wire_ms=snap["pull_ms"],
                    hidden_ms=snap["hidden_ms"],
                    overlap=snap["overlap_frac"],
                )
            if adapt:
                self._maybe_adaptive_replan(specs[sid], pages)
            out[sid] = pages
        return out

    def _maybe_adaptive_replan(self, ex, pages) -> None:
        """Mid-query adaptation (plan/history.py): the coordinator just
        materialized a producer stage, so its TRUE cardinality is known
        while the downstream fragments are still unexecuted. When the
        observation contradicts the estimate grossly enough
        (PRESTO_TPU_FEEDBACK_REPLAN_FACTOR) the observation is recorded
        and AdaptiveReplan raised; the session layer re-plans the
        downstream fragments against the now-updated history and
        re-runs through the same retry machinery worker failures use
        (it re-runs with adapt=False, so one replan per query)."""
        from ..plan import history as H
        from . import knobs

        try:
            if not H.feedback_on():
                return
            child = ex.child
            if _has_remote_source(child) or not self._has_scan(child):
                return  # nested-exchange estimates are not comparable
            observed = float(sum(int(p.count) for p in pages))
            if observed < knobs.feedback_replan_min_rows():
                return
            from ..plan.stats import derive

            est = float(derive(child, self.catalog).rows)
            if observed < knobs.feedback_replan_factor() * max(est, 1.0):
                return
            from ..exec.qcache import plan_tables

            recorded = H.HISTORY.record(
                H.fingerprint(child), catalog=self.catalog,
                tables=plan_tables(child), rows=observed, est_rows=est,
                kind=type(child).__name__,
            )
            if not recorded:
                return  # unversioned tables: a re-plan would not differ
        except Exception as exc:  # noqa: BLE001 — adaptation must never
            from ..exec.breaker import BREAKERS  # fail a healthy query

            BREAKERS.record_failure("adaptive_plan", repr(exc))
            return
        with H.HISTORY.stats._lock:
            H.HISTORY.stats.replans += 1
        with self._lock:
            self.stats.adaptive_replans += 1
        raise H.AdaptiveReplan(
            f"stage output {observed:,.0f} rows vs estimate {est:,.0f}: "
            "re-planning downstream fragments on observed cardinality"
        )

    def _record_exchange(self, sid: str, ex_stats: "ExchangeStats",
                         handles) -> None:
        """Fold one gather's pull stats + best-effort producer encode
        stats (task status exchangeStats — the producers are FINISHED
        here, so each poll answers immediately; still queryable until
        query cleanup) into the scheduler's observable accounting."""
        entry = ex_stats.snapshot()
        encode = WireStats()
        from .hier import HierExchangeStats

        hier = HierExchangeStats()
        mem_events: set = set()
        spilled = revocations = 0
        for uri, task in handles:
            try:
                st = self._task_status(uri, task)
            except Exception:  # noqa: BLE001 — observability, best effort
                continue
            ex = st.get("exchangeStats") or {}
            encode.merge_snapshot(ex)
            hier.merge_snapshot(ex.get("hier"))
            sp = st.get("spillStats") or {}
            spilled += int(sp.get("disk_bytes") or 0)
            mem_events.update(sp.get("events") or ())
            ms = st.get("memoryStats") or {}
            revocations += int(ms.get("revocations") or 0)
        entry["producer"] = encode.snapshot()
        hier_snap = hier.snapshot()
        if hier_snap.get("exchanges") or hier_snap.get("fallbacks"):
            entry["hier"] = hier_snap
        # unified metrics plane: one fold per gather (each ExchangeStats
        # and producer-encode accumulator lives for exactly one gather).
        # hier stats are NOT exported here — the final status sweep
        # (_collect_task_obs) covers every task exactly once, including
        # these gather producers
        from ..obs.export import export_exchange_stats, export_wire_stats

        export_exchange_stats(ex_stats)
        export_wire_stats("producer_encode", encode)
        with self._lock:
            self.stats.exchange[sid] = entry
            if spilled or revocations or mem_events:
                m = self.stats.memory
                m["spilled_bytes"] = (
                    int(m.get("spilled_bytes") or 0) + spilled
                )
                m["revocations"] = (
                    int(m.get("revocations") or 0) + revocations
                )
                m["events"] = sorted(
                    set(m.get("events") or ()) | mem_events
                )

    def _run_sharded_stage(self, node: N.PlanNode, output,
                           all_workers: List[str], all_tasks,
                           query_id: Optional[str] = None,
                           unbounded_output: bool = False,
                           dyn_produce=None,
                           dyn_values: Optional[dict] = None,
                           wire_caps: Optional[dict] = None,
                           tctx: Optional[tuple] = None) -> List[Tuple[str, str]]:
        """One task per worker for sharded stages (splits/repartition
        inputs); scan-less single-distribution stages run as ONE task so
        rows are never duplicated. Returns [(worker_uri, task_id)]."""
        nw = len(all_workers)
        fragment, specs = self._cut(node)
        sharded = self._has_scan(fragment) or any(
            ex.kind == "repartition" for ex in specs.values()
        )
        workers = all_workers if sharded else all_workers[:1]
        sspan = None
        if tctx is not None:
            sspan = tctx[0].begin(
                f"stage {output[0]}:{type(fragment).__name__}",
                parent_id=tctx[1], tasks=len(workers),
            )
            tctx = (tctx[0], sspan.span_id)
        child_resolved = self._resolve_sources(
            specs, True, all_workers, all_tasks, query_id,
            dyn_links=self._dyn_links(fragment, specs),
            dyn_values=dyn_values,
            wire_caps=wire_caps,
            tctx=tctx,
        )

        # row-range splits per scanned table
        tables = self._scan_tables(fragment)
        ranges = {}
        for t in tables:
            total = self.catalog.row_count(t)
            exact = getattr(self.catalog, "exact_row_count", None)
            if exact is not None:
                total = exact(t)
            per = -(-total // nw)
            ranges[t] = [
                (w * per, min((w + 1) * per, total)) for w in range(nw)
            ]

        frag_b64 = base64.b64encode(pickle.dumps(fragment)).decode()
        part_keys_b64 = None
        nparts = 1
        if output[0] == "hash":
            part_keys_b64 = base64.b64encode(pickle.dumps(output[1])).decode()
            nparts = nw

        launched = []  # (uri, task_id, spec) — spec kept for retries
        for w, uri in enumerate(workers):
            sources = {}
            for sid, (kind, child_handles) in child_resolved.items():
                if kind == "repartition":
                    # partition w has exactly ONE consumer: acks may free
                    # producer pages as this task consumes them
                    locs = [(u, t, w) for (u, t) in child_handles]
                    exclusive = True
                else:  # gather/replicate: every consumer pulls buffer 0
                    locs = [(u, t, 0) for (u, t) in child_handles]
                    exclusive = len(workers) == 1
                sources[sid] = {"locations": locs, "exclusive": exclusive}
            spec = {
                "fragment": frag_b64,
                "splits": {t: list(ranges[t][w]) for t in tables},
                "sources": sources,
                "partition_keys": part_keys_b64,
                "num_partitions": nparts,
                "query_id": query_id,
                "buffer_unbounded": unbounded_output,
                # cross-task dynamic filters: summaries this stage must
                # PRODUCE over its output, and resolved filter values its
                # scans may CONSUME (a snapshot — stages launched before a
                # build stage finished simply run unfiltered)
                "dyn_filter_produce": list(dyn_produce or ()) or None,
                "dyn_filters": dict(dyn_values) if dyn_values else None,
                # fleet-negotiated wire capabilities: this task's output
                # serializer must stay within them
                "wire": wire_caps,
            }
            launched.append(
                self._post_with_retry(uri, spec, all_workers, all_tasks,
                                      tctx=tctx)
            )
        # surface start failures eagerly, retrying each failed task onto
        # an alternate healthy worker (catalogs are deterministic across
        # nodes, so the same spec — splits, sources, partitioning — is
        # valid anywhere in the snapshot)
        handles = []
        for uri, task_id, spec, _post_attempts in launched:
            # fresh attempt budget: POST retries (connection-level) and
            # start-failure retries (task-level) are separate concerns
            handles.append(
                self._ensure_started(uri, task_id, spec, all_workers,
                                     all_tasks, tctx=tctx)
            )
        if sspan is not None:
            # the stage span covers launch (dispatch + start confirmation);
            # its children — per-attempt dispatch spans and the workers'
            # remote task spans — carry the execution wall
            tctx[0].finish(sspan)
        return handles

    # -- task start + retry --

    def _post_with_retry(self, uri: str, spec: dict,
                         snapshot: List[str], all_tasks,
                         tctx: Optional[tuple] = None):
        """POST a task, retrying a refused connection onto alternates.
        Returns (uri, task_id, spec, attempts_used)."""
        attempt = 1
        while True:
            task_id = f"t_{next(self._task_ids)}"
            failed = self._try_post(uri, task_id, spec, all_tasks,
                                    tctx=tctx)
            if failed is None:
                return uri, task_id, spec, attempt
            error = failed["error"]
            retryable = bool(failed["errorInfo"]["retryable"])
            self._note_task_failure(uri, error)
            if not retryable or attempt > self.max_task_retries:
                raise TaskFailure(
                    f"task {task_id} could not be started "
                    f"(last worker {uri}, attempt {attempt}, "
                    f"retryable={retryable}): {error}",
                    uri=uri, task_id=task_id, attempt=attempt,
                    retryable=retryable,
                )
            time.sleep(self._backoff(attempt - 1))
            uri = self._pick_alternate(uri, snapshot)
            attempt += 1
            with self._lock:
                self.stats.task_retries += 1

    def _try_post(self, uri: str, task_id: str, spec: dict,
                  all_tasks, tctx: Optional[tuple] = None) -> Optional[dict]:
        """POST a task; returns None on success, else a synthesized
        FAILED status dict (never raises for transport errors). The task
        id is registered for cleanup BEFORE posting: if the POST response
        is lost after the worker already accepted the task, query cleanup
        still deletes it (DELETE of an unknown task is a no-op).

        This is the single choke point every task POST goes through, so
        the per-ATTEMPT dispatch span lives here: each (re)post gets its
        own span under the stage, and the spec carries (trace_id, that
        span's id) so the worker parents its task span to this exact
        attempt — a retry is a sibling subtree, never an overwrite."""
        # partitioned-output producers are the only tasks the final
        # observability sweep must poll when tracing is off (their
        # exchangeStats["hier"] is unreachable any other way — their
        # consumers are other workers, not the coordinator)
        all_tasks.append((uri, task_id, bool(spec.get("partition_keys"))))
        dspan = None
        if tctx is not None:
            dspan = tctx[0].begin(
                f"dispatch {task_id}", parent_id=tctx[1], worker=uri,
            )
            spec["trace"] = {
                "trace_id": tctx[0].trace_id, "parent": dspan.span_id,
            }
        try:
            self._post_task(uri, task_id, spec)
            if dspan is not None:
                tctx[0].finish(dspan)
            return None
        except urllib.error.HTTPError as e:
            # the worker answered: honor its structured verdict
            detail, retryable = _http_error_details(e)
            if dspan is not None:
                tctx[0].finish(dspan, "error", error=detail[:200])
            return {
                "state": "FAILED",
                "error": detail,
                "errorInfo": {"retryable": retryable},
            }
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            if dspan is not None:
                tctx[0].finish(dspan, "error", error=str(e)[:200])
            return {
                "state": "FAILED",
                "error": f"POST to {uri} refused: {e}",
                "errorInfo": {"retryable": True},
            }

    def _ensure_started(self, uri: str, task_id: str, spec: dict,
                        snapshot: List[str], all_tasks,
                        attempt: int = 1,
                        tctx: Optional[tuple] = None) -> Tuple[str, str]:
        """Eager failure surfacing with bounded retry: a task FAILED at
        the status check is re-posted (same spec) to an alternate worker
        after backoff + jitter; unrecoverable failures cancel the
        query's sibling tasks and raise."""
        status: Optional[dict] = None  # None = POST itself failed
        posted = True
        while True:
            if posted:
                try:
                    status = self._task_status(uri, task_id, attempt=attempt)
                except TaskFailure as tf:
                    status = {
                        "state": "FAILED",
                        "error": str(tf),
                        "errorInfo": {"retryable": tf.retryable},
                    }
            if tctx is not None:
                # merge whatever spans the worker reported — a FAILED
                # attempt's task span (status="error") lands in the tree
                # HERE, before its replacement is even posted
                tctx[0].add_remote(status.get("spans") or ())
            if status.get("state") != "FAILED":
                # started (RUNNING or FINISHED): reset the consecutive-
                # failure streak feeding the blacklist
                self.nodes.record_task_success(uri)
                return uri, task_id
            error = status.get("error") or "unknown"
            info = status.get("errorInfo") or {}
            retryable = bool(
                info.get("retryable", _retryable_message(error))
            )
            self._note_task_failure(uri, error)
            if not retryable or attempt > self.max_task_retries:
                self._cancel_tasks(list(all_tasks))
                raise TaskFailure(
                    f"task {task_id} on worker {uri} failed "
                    f"(attempt {attempt}/{self.max_task_retries + 1}, "
                    f"retryable={retryable}):\n{error}",
                    uri=uri, task_id=task_id, attempt=attempt,
                    retryable=retryable,
                )
            time.sleep(self._backoff(attempt - 1))
            uri = self._pick_alternate(uri, snapshot)
            task_id = f"t_{next(self._task_ids)}"
            failed = self._try_post(uri, task_id, spec, all_tasks,
                                    tctx=tctx)
            posted = failed is None
            if not posted:
                status = failed  # skip the status poll: classify directly
            attempt += 1
            with self._lock:
                self.stats.task_retries += 1

    def _pick_alternate(self, failed_uri: str, snapshot: List[str]) -> str:
        """Prefer a currently-active snapshot member that is not the
        failed worker; fall back to any snapshot member (single-worker
        clusters still get in-place retries)."""
        active = set(self.nodes.active_workers())
        candidates = [
            u for u in snapshot if u != failed_uri and u in active
        ] or [u for u in snapshot if u != failed_uri] or [failed_uri]
        return random.choice(candidates)

    def _note_task_failure(self, uri: str, error: str) -> None:
        with self._lock:
            self.stats.tasks_failed += 1
            self.stats.worker_failures[uri] = (
                self.stats.worker_failures.get(uri, 0) + 1
            )
            self.stats.last_error = error[:300]
        self.nodes.record_task_failure(uri, error)

    @staticmethod
    def _scan_tables(node: N.PlanNode) -> List[str]:
        out = []

        def walk(n):
            if isinstance(n, N.TableScan):
                out.append(n.table)
            for c in n.children:
                walk(c)

        walk(node)
        return sorted(set(out))

    # -- HTTP --

    @staticmethod
    def _post_task(uri: str, task_id: str, spec: dict):
        body = json.dumps(spec).encode()
        req = urllib.request.Request(
            f"{uri}/v1/task/{task_id}", data=body, method="POST"
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    def _task_status(self, uri: str, task_id: str,
                     attempt: int = 1) -> dict:
        """Short-poll the task status endpoint under a configurable
        deadline (replaces the raw 300 s blocking urlopen): the worker
        answers within ~0.5 s, so looping only happens across transient
        network errors; exhausting the deadline raises a TaskFailure
        naming the worker, task, and attempt."""
        deadline = time.time() + self.status_deadline
        last = None
        while True:
            try:
                with urllib.request.urlopen(
                    f"{uri}/v1/task/{task_id}", timeout=self.status_timeout
                ) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as e:
                # the worker answered with an error status (404 unknown
                # task after a restart, 500 handler bug): definitive —
                # not worth polling out the deadline
                try:
                    detail = json.loads(e.read()).get("error") or str(e)
                except Exception:  # noqa: BLE001 — body parse is
                    # best-effort detail; the TaskFailure below still
                    # carries the HTTP error either way
                    detail = str(e)
                raise TaskFailure(
                    f"status of task {task_id} on worker {uri} "
                    f"(attempt {attempt}): HTTP {e.code}: {detail}",
                    uri=uri, task_id=task_id, attempt=attempt,
                ) from None
            except Exception as e:  # noqa: BLE001 - poll again until deadline
                last = e
            if time.time() >= deadline:
                raise TaskFailure(
                    f"status poll for task {task_id} on worker {uri} "
                    f"(attempt {attempt}) exceeded "
                    f"{self.status_deadline:.0f}s deadline: {last}",
                    uri=uri, task_id=task_id, attempt=attempt,
                ) from None
            time.sleep(0.1)


class ClusterMemoryManager:
    """Coordinator-side cluster memory management (reference
    memory/ClusterMemoryManager.java:89,210 + LowMemoryKiller.java:26):
    polls every worker's /v1/memory, aggregates per-query reservation
    across the cluster, and when any worker is memory-blocked kills the
    query with the LARGEST total reservation (the TotalReservation
    strategy) by aborting its tasks on every worker."""

    def __init__(self, nodes: NodeManager, interval: float = 0.25,
                 on_kill=None, grace_polls: int = 4,
                 revoke_watermark: Optional[float] = None):
        self.nodes = nodes
        self.interval = interval
        self.on_kill = on_kill
        self.grace_polls = grace_polls  # sustained blockage before a kill
        self.revoke_watermark = (
            knobs.revoke_watermark()
            if revoke_watermark is None else revoke_watermark
        )
        self._blocked_streak = 0
        self.killed: List[str] = []
        # memory-manager blindness observability: per-worker poll
        # failures are counted and surfaced, never silently skipped
        self.poll_failures: Dict[str, int] = {}
        self._unpollable: set = set()
        self.loop_errors = 0
        self.last_loop_error = ""
        self.last_snapshot: Dict[str, dict] = {}
        self._pressure = False
        # PER-WORKER last-seen revocation counters: a flapping worker's
        # counter dropping out of (and back into) a summed total would
        # oscillate the progress signal and indefinitely defer the killer
        self._last_rev_by_worker: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "ClusterMemoryManager":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception as exc:  # noqa: BLE001 - keep polling, but
                # COUNT the blindness instead of swallowing it bare
                self.loop_errors += 1
                self.last_loop_error = repr(exc)[:300]

    def above_watermark(self) -> bool:
        """Is any worker above the revocation watermark (or blocked)?
        Resource-group admission refuses to start new queries while True
        (server/resource_groups.py cluster_pressure)."""
        return self._pressure

    def _note_poll_failure(self, uri: str, exc: Exception) -> None:
        self.poll_failures[uri] = self.poll_failures.get(uri, 0) + 1
        if uri not in self._unpollable:
            self._unpollable.add(uri)
            bus = getattr(self.nodes, "event_bus", None)
            if bus is not None:
                # memory-manager blindness is an observable worker event,
                # not an invisible `continue`
                bus.fire_worker_state(
                    uri, "MEMORY_UNPOLLABLE",
                    f"/v1/memory poll failed: {exc!r}"[:200],
                )

    def poll_once(self) -> Optional[str]:
        """One manager cycle; returns the killed query id, if any."""
        states = []
        snapshot: Dict[str, dict] = {}
        for uri in self.nodes.active_workers():
            try:
                with urllib.request.urlopen(
                    f"{uri}/v1/memory", timeout=5
                ) as resp:
                    states.append((uri, json.loads(resp.read())))
            except Exception as exc:  # noqa: BLE001 - count + surface;
                # liveness demotion stays the failure detector's job
                self._note_poll_failure(uri, exc)
                snapshot[uri] = {
                    "unreachable": True,
                    "poll_failures": self.poll_failures[uri],
                }
                continue
            if uri in self._unpollable:
                self._unpollable.discard(uri)
                bus = getattr(self.nodes, "event_bus", None)
                if bus is not None:
                    bus.fire_worker_state(
                        uri, "MEMORY_POLLABLE", "memory polls recovered"
                    )
        # live gauge snapshot for system.jmx.memory
        progress = False
        pressure = False
        for uri, st in states:
            reserved = int(st.get("reserved") or 0)
            limit = st.get("limit") or 0
            rev = st.get("revocations") or {}
            completed = int(rev.get("completed") or 0)
            # progress is judged PER WORKER against its own last-seen
            # counter (only updated when the worker answers), so an
            # unpollable worker neither fakes nor hides progress
            if completed > self._last_rev_by_worker.get(uri, completed):
                progress = True
            self._last_rev_by_worker[uri] = completed
            if st.get("blocked") or (
                limit and reserved >= self.revoke_watermark * limit
            ):
                pressure = True
            snapshot[uri] = {
                "reserved": reserved,
                "limit": limit,
                "blocked": len(st.get("blocked") or ()),
                "exec_reserved": int(st.get("exec_reserved") or 0),
                "revocations": rev,
                "over_frees": int(st.get("over_frees") or 0),
                "spilled_bytes": int(
                    (st.get("spill") or {}).get("total_written") or 0
                ),
                "poll_failures": self.poll_failures.get(uri, 0),
            }
        self.last_snapshot = snapshot
        self._pressure = pressure
        blocked = any(st.get("blocked") for _, st in states)
        if not blocked:
            self._blocked_streak = 0
            return None
        # revoke-before-kill: while executors keep completing revocations
        # (freeing state into the spill tier), the blockage is being
        # WORKED ON — the killer only fires after revocation fails to
        # free enough for `grace_polls` consecutive polls
        if progress:
            self._blocked_streak = 0
            return None
        # transient blocking is normal flow control (acks free bytes
        # continuously); only SUSTAINED exhaustion triggers the killer
        self._blocked_streak += 1
        if self._blocked_streak < self.grace_polls:
            return None
        self._blocked_streak = 0
        victim = self.choose_victim(states)
        if victim is None:
            return None
        self.kill(victim)
        return victim

    @staticmethod
    def choose_victim(states) -> Optional[str]:
        """TotalReservation: the query holding the most bytes cluster-wide
        (blocked-but-unreserved queries are victims of last resort)."""
        totals: Dict[str, int] = {}
        for _uri, st in states:
            for qid, nbytes in (st.get("queries") or {}).items():
                totals[qid] = totals.get(qid, 0) + int(nbytes)
            for qid in st.get("blocked") or ():
                totals.setdefault(qid, 0)
        if not totals:
            return None
        return max(totals, key=lambda q: (totals[q], q))

    def kill(self, query_id: str) -> None:
        # kill on EVERY known worker — a blacklisted (drained) worker
        # can still hold tasks of the victim query
        for uri in self.nodes.all_workers():
            try:
                req = urllib.request.Request(
                    f"{uri}/v1/query/{query_id}", method="DELETE"
                )
                urllib.request.urlopen(req, timeout=5).read()
            except Exception:  # noqa: BLE001 - best effort per worker
                pass
        self.killed.append(query_id)
        if self.on_kill is not None:
            self.on_kill(query_id)


class HttpClusterSession:
    """Session facade executing SQL over an HTTP worker cluster — the
    DistributedQueryRunner analog for the DCN path."""

    def __init__(self, catalog, nodes: NodeManager,
                 broadcast_threshold=None,  # None = cost-based
                 memory_manager: bool = False,
                 scheduler_opts: Optional[dict] = None):
        from ..session import Session

        self._planner = Session(catalog)  # reuse parse/plan/fragment
        self._planner.mesh = None
        self.catalog = catalog
        self.broadcast_threshold = broadcast_threshold
        self.scheduler = HttpScheduler(
            catalog, nodes, **(scheduler_opts or {})
        )
        self._query_ids = itertools.count(1)
        self.memory_manager = (
            ClusterMemoryManager(nodes).start() if memory_manager else None
        )

    def _run_fragmented(self, sql: str, use_result_cache: bool = True):
        """The one plan -> fragment -> schedule pipeline both query()
        and explain_analyze() go through; returns (fragmented node,
        result page, trace_or_None, phase_ms). Both serving caches
        (exec/qcache.py) sit in front of the scheduler: the fragmented
        plan is cached per (sql, worker count, broadcast config) and
        validated against connector snapshot versions, and a
        snapshot-identical repeat serves its page without touching the
        fleet at all. Worker-count changes (blacklist, re-admission)
        change the plan key, so failover replans instead of reusing a
        stale fragmentation.

        Tracing (docs/observability.md): the coordinator opens the query
        root + plan/execute phase spans; the scheduler hangs per-attempt
        / per-stage / per-dispatch spans under the execute span and
        merges the workers' remote spans into the same tree."""
        from ..exec import qcache
        from ..obs import span as obs_span
        from ..obs.export import export_query
        from ..plan.fragment import fragment_plan

        trace = obs_span.TRACES.new_trace() if obs_span.enabled() else None
        root = (
            trace.begin("query", sql=sql[:200])
            if trace is not None else None
        )
        status = "ok"
        phase_ms: dict = {}
        try:
            pspan = (
                trace.begin("plan", parent=root)
                if trace is not None else None
            )
            from ..plan import history as H

            n_workers = max(len(self.scheduler.nodes.active_workers()), 2)

            def plan_fresh():
                # pkey carries the feedback generation: a history record
                # or invalidation must re-plan, never reuse a fragmented
                # plan built on superseded observations
                key = ("c", sql, self.broadcast_threshold, n_workers,
                       id(self.catalog), H.plan_env_token())
                ent = qcache.PLAN_CACHE.lookup(key, self.catalog)
                if ent is not None:
                    return ent.plan
                planned = self._planner.plan(sql)
                planned = fragment_plan(planned, self.catalog,
                                        self.broadcast_threshold,
                                        num_workers=n_workers)
                qcache.PLAN_CACHE.store(key, planned, self.catalog)
                return planned

            node = plan_fresh()
            if trace is not None:
                trace.finish(pspan)
                phase_ms["plan"] = round(pspan.wall_s * 1e3, 3)
            rkey = ("cr", sql, self.broadcast_threshold, n_workers,
                    id(self.catalog))
            pre = None
            if use_result_cache:
                hit = qcache.RESULT_CACHE.lookup(rkey, self.catalog)
                if hit is not None:
                    self.scheduler.record_caches(qcache.snapshot_all())
                    return node, hit.page, trace, phase_ms
                pre = qcache.RESULT_CACHE.preversions(node, self.catalog)
            espan = (
                trace.begin("execute", parent=root)
                if trace is not None else None
            )
            try:
                try:
                    page = self.scheduler.run(
                        node, query_id=f"q_{next(self._query_ids)}",
                        trace_ctx=(
                            (trace, espan.span_id) if trace is not None
                            else None
                        ),
                    )
                except H.AdaptiveReplan:
                    # mid-query adaptation: the scheduler recorded the
                    # contradicting observation before raising, so a
                    # fresh plan (new generation -> new pkey) reorders /
                    # re-distributes downstream fragments on measured
                    # rows. The re-run has adaptation off: one replan
                    # per query, and a second misprediction just runs.
                    node = plan_fresh()
                    page = self.scheduler.run(
                        node, query_id=f"q_{next(self._query_ids)}",
                        trace_ctx=(
                            (trace, espan.span_id) if trace is not None
                            else None
                        ),
                        adapt=False,
                    )
                    from ..exec.breaker import BREAKERS

                    BREAKERS.record_success("adaptive_plan")
            except Exception:
                if trace is not None:
                    trace.finish(espan, "error")
                raise
            if trace is not None:
                trace.finish(espan, rows=int(page.count))
                phase_ms["execute"] = round(espan.wall_s * 1e3, 3)
            if pre is not None and qcache.plan_is_deterministic(node):
                qcache.RESULT_CACHE.store(
                    rkey, page, getattr(node, "titles", ()), self.catalog,
                    pre,
                )
            self.scheduler.record_caches(qcache.snapshot_all())
            return node, page, trace, phase_ms
        except Exception:
            status = "error"
            raise
        finally:
            if trace is not None:
                trace.finish(root, status)
                export_query(status, root.wall_s, phase_ms)

    def query(self, sql: str):
        from ..session import QueryResult

        node, page, trace, phase_ms = self._run_fragmented(sql)
        res = QueryResult(page, node.titles)
        if trace is not None:
            res.trace_id = trace.trace_id
            res.phase_ms = phase_ms
        return res

    def explain_analyze(self, sql: str) -> str:
        """Run the query over the cluster and render the fragmented plan
        with per-exchange WIRE stats: pages, wire vs raw bytes and the
        compression ratio, encode/decode wall, and pull concurrency —
        the distributed half of EXPLAIN ANALYZE (the single-process half
        lives in Session.explain_analyze_plan)."""
        # bypass the result cache: EXPLAIN ANALYZE must actually execute
        # to have wire/memory stats worth reporting
        node, _page, trace, _phase_ms = self._run_fragmented(
            sql, use_result_cache=False
        )
        tree = N.plan_tree_str(node)
        lines = [tree]
        st = self.scheduler.stats_snapshot()
        if st["wire_caps"]:
            lines.append(
                "-- wire: v%s, codecs %s"
                % (st["wire_caps"].get("version"),
                   "/".join(st["wire_caps"].get("codecs") or ()))
            )
        for sid, ex in sorted(st["exchange"].items()):
            prod = ex.get("producer") or {}
            ratio = prod.get("compression_ratio")
            lines.append(
                f"-- exchange {sid}: {ex['pages']} pages from "
                f"{ex['sources']} producers, wire "
                f"{ex['wire_bytes']:,}B"
                + (
                    f" (raw {prod['raw_bytes']:,}B, {ratio}x)"
                    if prod.get("raw_bytes") and ratio
                    else ""
                )
                + f", encode {prod.get('encode_ms', 0)}ms, decode "
                f"{ex['decode_ms']}ms, pull peak {ex['peak_concurrent']} "
                f"concurrent"
            )
            if ex.get("pull_ms") is not None:
                # overlap proof: wire wall vs what the consumer actually
                # waited for — the difference was hidden behind compute
                lines.append(
                    f"-- exchange {sid} overlap: wire "
                    f"{ex['pull_ms']}ms, consumer wait "
                    f"{ex.get('consumer_wait_ms', 0)}ms, hidden "
                    f"{ex.get('hidden_ms', 0)}ms "
                    f"({round(100 * ex.get('overlap_frac', 0.0))}%)"
                )
            hier = ex.get("hier")
            if hier:
                lines.append(
                    f"-- exchange {sid} hier: "
                    f"{hier['collective_exchanges']}/{hier['exchanges']} "
                    f"collective, device {hier['collective_ms']}ms, "
                    f"{hier['wire_pages']} ragged pages, pad "
                    f"{hier['ragged_pad_rows']} rows (fixed would be "
                    f"{hier['fixed_pad_rows']}), "
                    f"fallbacks {hier['fallbacks']}"
                )
        if st.get("hier"):
            # query-wide rollup from the final task sweep: mid-tree
            # repartition producers' hierarchical regroup accounting
            h = st["hier"]
            lines.append(
                f"-- hier: {h['collective_exchanges']}/{h['exchanges']} "
                f"batches collective, device {h['collective_ms']}ms, "
                f"{h['wire_pages']} ragged pages, pad "
                f"{h['ragged_pad_rows']} rows (fixed would be "
                f"{h['fixed_pad_rows']}), fallbacks {h['fallbacks']}"
            )
        if st["memory"]:
            m = st["memory"]
            lines.append(
                "-- memory: spill "
                + ",".join(m.get("events") or ("none",))
                + f", disk {m.get('spilled_bytes', 0):,}B, "
                f"revocations {m.get('revocations', 0)}"
            )
        if st["caches"]:
            from ..exec import qcache

            lines.append("-- caches: " + qcache.format_summary(st["caches"]))
        if trace is not None:
            # same renderer as Session.explain_analyze_plan — one source
            # of truth for the single-process and cluster critical path
            from ..obs.span import render_critical_path

            lines.append(
                "-- trace: "
                + render_critical_path(trace, knobs.trace_topk())
            )
        return "\n".join(lines)

    def close(self):
        if self.memory_manager is not None:
            self.memory_manager.stop()
