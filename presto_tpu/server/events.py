"""Query event listener SPI.

Re-designed equivalent of the reference's EventListener SPI
(presto-spi/.../spi/eventlistener/EventListener.java: queryCreated /
queryCompleted / splitCompleted) fed by QueryMonitor
(presto-main/.../event/QueryMonitor.java:73,112,171). Listeners are plain
objects registered on the QueryManager; failures in a listener never fail
the query (matching the reference's isolation of listener plugins).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import List, Optional

log = logging.getLogger("presto_tpu.events")


@dataclasses.dataclass(frozen=True)
class QueryCreatedEvent:
    query_id: str
    sql: str
    user: str
    source: Optional[str]
    create_time: float


@dataclasses.dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    sql: str
    user: str
    source: Optional[str]
    state: str  # FINISHED | FAILED | CANCELED
    error: Optional[str]
    create_time: float
    start_time: Optional[float]
    end_time: float
    wall_s: float
    rows: Optional[int]
    # observability plane (obs/span.py): the query's trace id — join key
    # into system.runtime.tasks / the trace store — and per-phase wall
    # timings ({"plan": ms, "execute": ms, ...}) from the span tree.
    # None when tracing is disabled (PRESTO_TPU_TRACE=0)
    trace_id: Optional[str] = None
    phase_ms: Optional[dict] = None


@dataclasses.dataclass(frozen=True)
class WorkerStateEvent:
    """A worker transitioned liveness state (reference analog: the
    HeartbeatFailureDetector's state changes surfaced via node-state
    JMX + the coordinator log). States: ACTIVE (re-admitted / up),
    FAILED (heartbeat probes exhausted), BLACKLISTED (drained after
    consecutive task failures), MEMORY_UNPOLLABLE / MEMORY_POLLABLE
    (the cluster memory manager lost / regained sight of the worker's
    /v1/memory — manager blindness is observable, not an invisible
    skipped poll)."""

    uri: str
    state: str  # ACTIVE | FAILED | BLACKLISTED
    reason: str
    time: float


class EventListener:
    """Subclass and override the hooks you care about."""

    def query_created(self, event: QueryCreatedEvent) -> None:  # noqa: B027
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:  # noqa: B027
        pass

    def worker_state_changed(self, event: WorkerStateEvent) -> None:  # noqa: B027
        pass


class LoggingEventListener(EventListener):
    """Reference analog: the event-listener plugins that write query logs."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        log.info("query created %s user=%s", event.query_id, event.user)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        log.info(
            "query completed %s state=%s wall=%.3fs rows=%s",
            event.query_id, event.state, event.wall_s, event.rows,
        )

    def worker_state_changed(self, event: WorkerStateEvent) -> None:
        log.warning(
            "worker %s -> %s (%s)", event.uri, event.state, event.reason
        )


class EventBus:
    def __init__(self, listeners: Optional[List[EventListener]] = None):
        self.listeners = list(listeners or [])

    def add(self, listener: EventListener) -> None:
        self.listeners.append(listener)

    def fire_created(self, info) -> None:
        ev = QueryCreatedEvent(
            info.query_id, info.sql, getattr(info, "user", "user"),
            getattr(info, "source", None), info.created_at,
        )
        self._fire("query_created", ev)

    def fire_completed(self, info) -> None:
        end = info.finished_at or time.time()
        ev = QueryCompletedEvent(
            info.query_id, info.sql, getattr(info, "user", "user"),
            getattr(info, "source", None), info.state, info.error,
            info.created_at, info.started_at, end,
            end - (info.started_at or end),
            len(info.rows) if info.rows is not None else None,
            trace_id=getattr(info, "trace_id", None),
            phase_ms=getattr(info, "phase_ms", None),
        )
        self._fire("query_completed", ev)

    def fire_worker_state(self, uri: str, state: str, reason: str) -> None:
        self._fire(
            "worker_state_changed",
            WorkerStateEvent(uri, state, reason, time.time()),
        )

    def _fire(self, hook: str, event) -> None:
        for listener in self.listeners:
            try:
                getattr(listener, hook)(event)
            except Exception:  # noqa: BLE001 - listener isolation
                log.exception("event listener %r failed", listener)
