"""Coordinator HTTP server: the client statement protocol.

Re-designed equivalent of the reference's server layer (SURVEY L2):
StatementResource (`POST /v1/statement`, server/protocol/
StatementResource.java:84,128) with QueryResults nextUri paging
(presto-client/.../QueryResults.java:41), QueryResource listings,
NodeResource-style /v1/info + /v1/status, and graceful shutdown
(server/GracefulShutdownHandler.java:43). Python stdlib HTTP (threading
server) replaces airlift/Jetty — the control plane is latency-bound, not
throughput-bound; the data plane stays on device.

Protocol (wire-compatible in spirit, JSON):
  POST /v1/statement            body = SQL   -> QueryResults JSON
  GET  /v1/statement/{id}/{token}?maxWait=s  -> next QueryResults chunk
  DELETE /v1/statement/{id}                  -> cancel
  GET  /v1/query                             -> query list
  GET  /v1/query/{id}                        -> detail incl. plan
  GET  /v1/info | /v1/status                 -> node info / liveness
  PUT  /v1/info/state  body='"SHUTTING_DOWN"'-> graceful shutdown
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .state import FINISHED, QueryManager

PAGE_ROWS = 1000  # rows per QueryResults chunk (client paging)
VERSION = "presto-tpu/0.2"


def _json_default(v):
    import datetime
    import decimal

    if isinstance(v, (decimal.Decimal,)):
        return str(v)
    if hasattr(v, "item"):
        return v.item()
    if isinstance(v, (datetime.date,)):
        return v.isoformat()
    return str(v)


class CoordinatorServer:
    """Embeddable coordinator (reference TestingPrestoServer): wraps a
    Session in a QueryManager and serves the REST protocol."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 0,
                 max_concurrent: int = 1, resource_groups=None,
                 selectors=None, listeners=None, node_manager=None,
                 access_control=None, authenticator=None, tls=None,
                 impersonation_principals=(), cluster_pressure=None):
        # expose system.runtime.* through the served session's catalog
        # (reference connector/system/; the user's own session is untouched).
        # Duck-typed sessions (HttpClusterSession) are served as-is — they
        # execute on remote workers whose catalogs we don't rewrite.
        from ..connectors.system import SystemCatalog
        from ..session import Session

        self.syscat = None
        served = session
        if isinstance(session, Session):
            syscat = SystemCatalog(session.catalog)
            served = Session(
                syscat,
                mesh=session.mesh,
                broadcast_threshold=session.broadcast_threshold,
                streaming=session.streaming,
                batch_rows=session.batch_rows,
                memory_budget=session.memory_budget,
                access_control=session.access_control,
                user=session.user,
            )
            self.syscat = syscat
        # cluster_pressure: admission gate fed by the cluster memory
        # manager (HttpClusterSession.memory_manager.above_watermark) —
        # new queries queue while the fleet is above the revocation
        # watermark. Derived automatically for cluster sessions.
        if cluster_pressure is None:
            mm = getattr(session, "memory_manager", None)
            if mm is not None:
                cluster_pressure = mm.above_watermark
        self.manager = QueryManager(
            served, max_concurrent=max_concurrent,
            resource_groups=resource_groups, selectors=selectors,
            listeners=listeners, access_control=access_control,
            cluster_pressure=cluster_pressure,
        )
        if self.syscat is not None:
            self.syscat.manager = self.manager
            self.syscat.node_manager = node_manager
        # resource-group occupancy on /v1/metrics: a scrape-time
        # producer under a fixed key (a re-created coordinator replaces
        # the previous registration, never accumulates)
        from ..obs.export import register_resource_groups

        register_resource_groups(self.manager.groups)
        self.started_at = time.time()
        self.shutting_down = False
        self.authenticator = authenticator
        self.tls = tls
        # principals allowed to run queries AS another user (reference:
        # principal-to-user impersonation rules in SystemAccessControl) —
        # how an authenticating proxy forwards its clients' identities
        self.impersonation_principals = frozenset(impersonation_principals)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _authenticate(self):
                """With an authenticator installed, the principal comes
                from Basic credentials and X-Presto-User must match it —
                the header alone is no longer trusted (reference
                server/security + password authenticators). Returns the
                authenticated user, or None after sending 401."""
                if outer.authenticator is None:
                    return self.headers.get("X-Presto-User", "user")
                from .auth import AuthenticationError, parse_basic_auth

                creds = parse_basic_auth(self.headers.get("Authorization"))
                if creds is None:
                    self.send_response(401)
                    self.send_header(
                        "WWW-Authenticate", 'Basic realm="presto"'
                    )
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return None
                try:
                    principal = outer.authenticator.authenticate(*creds)
                except AuthenticationError as e:
                    self._send(401, {"error": str(e)})
                    return None
                asserted = self.headers.get("X-Presto-User")
                if asserted and asserted != principal:
                    if principal in outer.impersonation_principals:
                        return asserted  # e.g. the proxy's clients
                    self._send(
                        403,
                        {"error": f"user {asserted!r} does not match "
                                  f"authenticated principal {principal!r}"},
                    )
                    return None
                return principal

            # -- helpers --
            def _send(self, code: int, payload, content_type="application/json"):
                body = (
                    payload
                    if isinstance(payload, bytes)
                    else json.dumps(payload, default=_json_default).encode()
                )
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n)

            # -- routes --
            def do_POST(self):
                if self.path == "/v1/statement":
                    if outer.shutting_down:
                        self._send(503, {"error": "shutting down"})
                        return
                    sql = self._read_body().decode()
                    user = self._authenticate()
                    if user is None:
                        return
                    source = self.headers.get("X-Presto-Source")
                    props_hdr = self.headers.get("X-Presto-Session", "")
                    try:
                        from ..session import parse_session_properties

                        props = parse_session_properties(props_hdr)
                    except ValueError as e:
                        self._send(400, {"error": str(e)})
                        return
                    info = outer.manager.submit(
                        sql, user=user, source=source, properties=props
                    )
                    # immediate first response: QUEUED with nextUri
                    self._send(200, outer._query_results(info, 0))
                    return
                self._send(404, {"error": "not found"})

            def do_GET(self):
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                # health/status/metrics stay unauthenticated (load
                # balancers, cluster heartbeats, Prometheus scrapers);
                # every data-bearing surface requires the principal
                if parts[:2] not in (
                    ["v1", "info"], ["v1", "status"], ["v1", "metrics"]
                ) and (self._authenticate() is None):
                    return
                qs = {}
                if "?" in self.path:
                    for kv in self.path.split("?", 1)[1].split("&"):
                        if "=" in kv:
                            k, v = kv.split("=", 1)
                            qs[k] = v
                if parts[:2] == ["v1", "statement"] and len(parts) == 4:
                    qid, token = parts[2], int(parts[3])
                    info = outer.manager.get(qid)
                    if info is None:
                        self._send(404, {"error": f"unknown query {qid}"})
                        return
                    max_wait = float(qs.get("maxWait", 1.0))
                    if not info.done:
                        info = outer.manager.wait(qid, max_wait)
                        if info is None:  # purged while waiting
                            self._send(404, {"error": f"query {qid} expired"})
                            return
                    self._send(200, outer._query_results(info, token))
                    return
                if parts[:2] == ["v1", "query"] and len(parts) == 2:
                    self._send(
                        200,
                        [outer._query_summary(i) for i in outer.manager.list_queries()],
                    )
                    return
                if parts[:2] == ["v1", "query"] and len(parts) == 3:
                    info = outer.manager.get(parts[2])
                    if info is None:
                        self._send(404, {"error": "unknown query"})
                        return
                    d = outer._query_summary(info)
                    if info.plan is None and info.error is None:
                        try:  # lazily rendered on the detail endpoint only
                            info.plan = outer.manager.session.explain(info.sql)
                        except Exception:  # noqa: BLE001 — the plan is UI
                            # decoration; the query detail (incl. its real
                            # error field) is served regardless
                            pass
                    d["plan"] = info.plan
                    d["error"] = info.error
                    self._send(200, d)
                    return
                if parts == ["v1", "info"]:
                    self._send(
                        200,
                        {
                            "nodeVersion": VERSION,
                            "coordinator": True,
                            "uptime_s": round(time.time() - outer.started_at, 1),
                            "state": "SHUTTING_DOWN"
                            if outer.shutting_down
                            else "ACTIVE",
                        },
                    )
                    return
                if parts == ["v1", "status"]:
                    from ..exec import qcache

                    # serving-cache observability (exec/qcache.py):
                    # hits/misses/evictions/bytes for the plan, result
                    # and kernel caches — the dashboard the qps driver
                    # and ops polling read hit rates from
                    self._send(200, {
                        "state": "ACTIVE",
                        "version": VERSION,
                        "caches": qcache.snapshot_all(),
                    })
                    return
                if parts == ["v1", "metrics"]:
                    # Prometheus text exposition 0.0.4 over the unified
                    # MetricsRegistry (obs/metrics.py): every stats silo
                    # — qcache, breakers, exchange, wire, scheduler,
                    # kernel profile, resource groups — in one scrape
                    from ..obs.metrics import METRICS

                    self._send(
                        200, METRICS.render().encode(),
                        content_type=(
                            "text/plain; version=0.0.4; charset=utf-8"
                        ),
                    )
                    return
                if not parts or parts == ["ui"]:
                    self._send(
                        200, outer._render_ui().encode(),
                        content_type="text/html; charset=utf-8",
                    )
                    return
                if parts[:1] == ["query"] and len(parts) == 2:
                    page = outer._render_query_detail(parts[1])
                    if page is None:
                        self._send(404, {"error": "unknown query"})
                        return
                    self._send(
                        200, page.encode(),
                        content_type="text/html; charset=utf-8",
                    )
                    return
                if parts == ["timeline"]:
                    self._send(
                        200, outer._render_timeline().encode(),
                        content_type="text/html; charset=utf-8",
                    )
                    return
                if parts == ["v1", "resourceGroupState"]:
                    self._send(
                        200,
                        [
                            {
                                "group": s.name,
                                "running": s.running,
                                "queued": s.queued,
                                "cpu_used_s": round(s.cpu_used_s, 3),
                            }
                            for s in outer.manager.groups.stats()
                        ],
                    )
                    return
                self._send(404, {"error": "not found"})

            def do_DELETE(self):
                if self._authenticate() is None:
                    return
                parts = [p for p in self.path.split("/") if p]
                if parts[:2] == ["v1", "statement"] and len(parts) == 3:
                    ok = outer.manager.cancel(parts[2])
                    self._send(200 if ok else 404, {"canceled": ok})
                    return
                self._send(404, {"error": "not found"})

            def do_PUT(self):
                if self.path == "/v1/info/state":
                    body = self._read_body().decode().strip().strip('"')
                    # shutdown is privileged: authenticate first (body is
                    # already drained so a 401 leaves the stream clean)
                    if self._authenticate() is None:
                        return
                    if body == "SHUTTING_DOWN":
                        outer.shutting_down = True  # drain: reject new queries
                        self._send(200, {"state": "SHUTTING_DOWN"})
                        return
                self._send(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        if tls is not None:
            from .auth import server_ssl_context

            certfile, keyfile = tls
            self._httpd.socket = server_ssl_context(
                certfile, keyfile
            ).wrap_socket(self._httpd.socket, server_side=True)
        self.host, self.port = self._httpd.server_address
        self.scheme = "https" if tls is not None else "http"
        if self.syscat is not None:
            self.syscat.self_uri = f"{self.scheme}://{self.host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    # -- web UI (reference: presto-main webapp/ React query list; here a
    # dependency-free server-rendered page off the same QueryManager) --

    def _render_ui(self) -> str:
        import html

        rows = []
        for info in sorted(
            self.manager.list_queries(),
            key=lambda i: i.created_at, reverse=True,
        )[:50]:
            elapsed = (info.finished_at or time.time()) - info.created_at
            q = html.escape(info.sql.replace("\n", " ")[:120])
            err = html.escape((info.error or "").strip().split("\n")[-1][:120])
            rows.append(
                f"<tr class='{info.state.lower()}'>"
                f"<td><a href='/query/{info.query_id}'>{info.query_id}</a>"
                f"</td>"
                f"<td>{info.state}</td><td>{html.escape(info.user)}</td>"
                f"<td>{elapsed:.2f}s</td><td><code>{q}</code>"
                f"{'<br><small>' + err + '</small>' if err else ''}</td></tr>"
            )
        groups = "".join(
            f"<tr><td>{s.name}</td><td>{s.running}</td><td>{s.queued}</td>"
            f"<td>{s.cpu_used_s:.2f}s</td></tr>"
            for s in self.manager.groups.stats()
        )
        return f"""<!doctype html><html><head><meta charset="utf-8">
<meta http-equiv="refresh" content="5"><title>presto-tpu</title><style>
body{{font-family:system-ui,sans-serif;margin:2em;background:#fafafa}}
table{{border-collapse:collapse;width:100%;margin-bottom:2em;background:#fff}}
td,th{{border:1px solid #ddd;padding:6px 10px;text-align:left;font-size:14px}}
th{{background:#2b3a4a;color:#fff}} .failed td{{background:#fde8e8}}
.running td{{background:#e8f4fd}} .finished td{{background:#f2fdf2}}
code{{font-size:12px}}</style></head><body>
<h1>presto-tpu coordinator</h1>
<p>{VERSION} &middot; uptime {time.time() - self.started_at:.0f}s &middot;
state {"SHUTTING_DOWN" if self.shutting_down else "ACTIVE"}</p>
<h2>Queries</h2>
<table><tr><th>id</th><th>state</th><th>user</th><th>elapsed</th>
<th>query</th></tr>{''.join(rows)}</table>
<h2>Resource groups</h2>
<table><tr><th>group</th><th>running</th><th>queued</th><th>cpu used</th></tr>
{groups}</table></body></html>"""

    def _render_query_detail(self, query_id: str) -> Optional[str]:
        """Per-query page: SQL, state, plan tree, error (reference webapp
        query.html/plan.html views, server-rendered)."""
        import html

        info = self.manager.get(query_id)
        if info is None:
            return None
        if info.plan is None and info.error is None:
            try:  # same lazy render as the /v1/query/{id} endpoint
                info.plan = self.manager.session.explain(info.sql)
            except Exception:  # noqa: BLE001 - plan render is advisory
                pass
        elapsed = (info.finished_at or time.time()) - info.created_at
        plan = html.escape(info.plan or "(plan not recorded)")
        err = (
            f"<h2>Error</h2><pre class='err'>{html.escape(info.error)}</pre>"
            if info.error
            else ""
        )
        # LIVE view (reference webapp query.html auto-updates): running
        # queries re-render every 2s until terminal
        live = (
            "" if info.done
            else '<meta http-equiv="refresh" content="2">'
        )
        stages = self._render_stages(info)
        return f"""<!doctype html><html><head><meta charset="utf-8">{live}
<title>{query_id}</title><style>
body{{font-family:system-ui,sans-serif;margin:2em;background:#fafafa}}
pre{{background:#fff;border:1px solid #ddd;padding:1em;overflow:auto;
font-size:13px}} .err{{background:#fde8e8}}
.meta td{{padding:4px 12px 4px 0}}</style></head><body>
<p><a href="/">&larr; queries</a></p>
<h1>{query_id}</h1>
<table class="meta">
<tr><td>state</td><td><b>{info.state}</b></td></tr>
<tr><td>user</td><td>{html.escape(info.user)}</td></tr>
<tr><td>elapsed</td><td>{elapsed:.2f}s</td></tr>
</table>
<h2>SQL</h2><pre>{html.escape(info.sql)}</pre>
<h2>Plan</h2><pre>{plan}</pre>
{stages}
{err}</body></html>"""

    def _render_stages(self, info) -> str:
        """Stage breakdown (reference webapp stage.html): the FRAGMENTED
        plan with one section per stage when the session is distributed;
        single-stage note otherwise."""
        import html

        sess = self.manager.session
        if getattr(sess, "mesh", None) is None:
            return (
                "<h2>Stages</h2><p>single stage (one-process session — "
                "pass a mesh for fragmented execution)</p>"
            )
        # render once per query and cache on the QueryInfo: the live page
        # refreshes every 2s and must not re-plan each time (and the plan
        # at SUBMIT time is the one that executed)
        cached = getattr(info, "stages_html", None)
        if cached is None:
            try:
                node = sess.plan(info.sql)
                from ..plan import nodes as N

                txt = html.escape(N.plan_tree_str(node))
            except Exception as e:  # noqa: BLE001 - advisory view
                txt = html.escape(f"(stage render failed: {e})")
            cached = f"<h2>Stages (fragmented)</h2><pre>{txt}</pre>"
            try:
                info.stages_html = cached
            except AttributeError:
                pass  # frozen dataclass: render per view
        return cached

    def _render_timeline(self) -> str:
        """Query lifecycle timeline (reference webapp timeline.html): an
        SVG gantt of the most recent queries — queued span (created ->
        started) and execution span (started -> finished/now), refreshed
        live every 2s."""
        import html

        infos = sorted(
            self.manager.list_queries(),
            key=lambda q: q.created_at,
        )[-30:]
        now = time.time()
        if infos:
            t0 = min(q.created_at for q in infos)
            t1 = max((q.finished_at or now) for q in infos)
        else:
            t0, t1 = now - 1, now
        span = max(t1 - t0, 1e-3)
        W, ROW = 900, 22
        bars = []
        for i, q in enumerate(infos):
            y = i * ROW
            qs = (q.created_at - t0) / span * W
            xs = ((q.started_at or q.created_at) - t0) / span * W
            xe = ((q.finished_at or now) - t0) / span * W
            color = {
                "FINISHED": "#2e7d32", "FAILED": "#c62828",
                "RUNNING": "#1565c0",
            }.get(q.state, "#999")
            label = html.escape(q.sql.replace("\n", " ")[:60])
            bars.append(
                f'<rect x="{qs:.1f}" y="{y + 4}" '
                f'width="{max(xs - qs, 1):.1f}" height="12" fill="#ccc"/>'
                f'<rect x="{xs:.1f}" y="{y + 4}" '
                f'width="{max(xe - xs, 1):.1f}" height="12" '
                f'fill="{color}"><title>{label}</title></rect>'
                f'<text x="{min(xe + 4, W - 150):.1f}" y="{y + 14}" '
                f'font-size="10">'
                f'<a href="/query/{q.query_id}">{q.query_id}</a></text>'
            )
        h = max(len(infos) * ROW + 10, 40)
        return f"""<!doctype html><html><head><meta charset="utf-8">
<meta http-equiv="refresh" content="2"><title>timeline</title>
<style>body{{font-family:system-ui,sans-serif;margin:2em}}</style>
</head><body><p><a href="/">&larr; queries</a></p>
<h1>Query timeline</h1>
<p>grey = queued, colored = executing (green finished / red failed /
blue running)</p>
<svg width="{W + 160}" height="{h}">{''.join(bars)}</svg>
</body></html>"""

    # -- protocol payloads --

    def _query_summary(self, info) -> dict:
        return {
            "queryId": info.query_id,
            "state": info.state,
            "query": info.sql,
            "elapsed_s": round(
                (info.finished_at or time.time()) - info.created_at, 3
            ),
        }

    def _query_results(self, info, token: int) -> dict:
        base = f"{self.scheme}://{self.host}:{self.port}"
        out = {
            "id": info.query_id,
            "infoUri": f"{base}/v1/query/{info.query_id}",
            "stats": {"state": info.state},
        }
        if info.state == FINISHED and info.rows is not None:
            out["columns"] = info.columns
            start = token * PAGE_ROWS
            chunk = info.rows[start : start + PAGE_ROWS]
            out["data"] = [list(r) for r in chunk]
            if start + PAGE_ROWS < len(info.rows):
                out["nextUri"] = (
                    f"{base}/v1/statement/{info.query_id}/{token + 1}"
                )
        elif info.done:
            out["error"] = {"message": info.error or info.state}
        else:
            out["nextUri"] = f"{base}/v1/statement/{info.query_id}/{token}"
        return out

    # -- lifecycle --

    def start(self) -> "CoordinatorServer":
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def uri(self) -> str:
        return f"{self.scheme}://{self.host}:{self.port}"
