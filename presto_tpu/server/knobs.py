"""Single parse sites for server-side PRESTO_TPU_* knobs.

prestolint's knob-consistency pass enforces one parse site per knob:
before this module, PRESTO_TPU_TASK_DEADLINE_S was parsed in three
files with two different defaults (300 in the coordinator, 600 in the
worker relay and the exchange client), so the coordinator abandoned a
slow task stream at half the budget its own workers were still willing
to wait — set the env var and the skew disappears, leave it unset and
it silently configures the fleet two ways. Every server-side knob
parses HERE, once, and callers import the function.

Knobs are read per call, not cached at import: tests and the benchmark
harness set/restore env vars around individual runs."""

from __future__ import annotations

import os


def task_deadline_s() -> float:
    """Progress deadline (seconds) for any single task results stream:
    the wall time between pages before a puller declares the producer
    wedged and fails retryably. Shared by the coordinator pull, the
    worker relay pull, and the pipelined exchange client — one clock,
    or the slowest link decides who gives up first."""
    return float(os.environ.get("PRESTO_TPU_TASK_DEADLINE_S", "600"))


def trace_enabled() -> bool:
    """Master switch for the observability plane's per-query span trees
    and kernel compile/execute profiling (docs/observability.md). On by
    default — the bench gate asserts the overhead stays ≤5% of warm
    northstar p50; set PRESTO_TPU_TRACE=0 to shed even that."""
    return os.environ.get("PRESTO_TPU_TRACE", "1") not in ("0", "false", "")


def trace_keep() -> int:
    """How many completed traces the in-process TraceStore retains for
    `system.runtime.tasks` and EXPLAIN ANALYZE's `-- trace:` footer;
    older traces are evicted FIFO."""
    try:
        return int(os.environ.get("PRESTO_TPU_TRACE_KEEP", "64"))
    except ValueError:
        return 64


def trace_topk() -> int:
    """How many spans (ranked by exclusive wall) the `-- trace:`
    critical-path rendering lists."""
    try:
        return int(os.environ.get("PRESTO_TPU_TRACE_TOPK", "5"))
    except ValueError:
        return 5


def hier_exchange_enabled() -> bool:
    """Master switch for the hierarchical exchange plane (server/hier.py):
    partitioned task output regroups rows with ONE device dispatch (a
    `lax.all_to_all` collective when the local mesh has enough devices, a
    fused grouping kernel otherwise) and ships ragged paged partitions
    over the PTP2 wire. Off (`PRESTO_TPU_HIER_EXCHANGE=0`) every producer
    uses the flat per-partition loop. The knob gates the PRODUCER only —
    consumers decode both shapes, so flipping it mid-fleet is safe."""
    return os.environ.get("PRESTO_TPU_HIER_EXCHANGE", "1") not in (
        "0", "false", ""
    )


def hier_exchange_min_devices() -> int:
    """Local devices required before the intra-host regroup uses the
    shard_map `lax.all_to_all` collective; below it (including the
    1-chip case) the fused single-dispatch grouping kernel runs
    instead — still one dispatch per exchange, no per-partition loop."""
    try:
        return int(os.environ.get("PRESTO_TPU_HIER_EXCHANGE_MIN_DEVICES",
                                  "2"))
    except ValueError:
        return 2


def hier_exchange_min_rows() -> int:
    """Rows below which the collective regroup is not worth the
    host→device shard scatter: small batches take the fused grouping
    kernel even on a multi-device host."""
    try:
        return int(os.environ.get("PRESTO_TPU_HIER_EXCHANGE_MIN_ROWS",
                                  "8192"))
    except ValueError:
        return 8192


def hier_exchange_prefetch() -> int:
    """Tranche prefetch depth for the pull side: each puller thread may
    keep this many `max_response_bytes` responses staged ahead of the
    consumer, so the next inter-host tranche is already on the wire
    while the current tranche's device-side work runs — the overlap
    that hides wire latency behind collective compute."""
    try:
        return int(os.environ.get("PRESTO_TPU_HIER_EXCHANGE_PREFETCH",
                                  "2"))
    except ValueError:
        return 2


def feedback_enabled() -> bool:
    """Master switch for history-based adaptive execution
    (plan/history.py): record observed per-plan-node cardinalities at
    query completion and feed them back into join ordering, broadcast
    switching, hybrid-join sizing, matview delta decisions, and the
    coordinator's mid-query replan. Off by default — flip
    PRESTO_TPU_FEEDBACK=1 to opt in; the adaptive_plan breaker reverts
    to static plans on repeated faults either way."""
    return os.environ.get("PRESTO_TPU_FEEDBACK", "0") not in (
        "0", "false", ""
    )


def feedback_replan_factor() -> float:
    """Observed-vs-estimated row factor at an exchange boundary past
    which the coordinator abandons the attempt and re-plans downstream
    fragments against the recorded observation (server/cluster.py).
    Generous by default: a replan repeats producer work, so only a
    gross misprediction should pay for one."""
    try:
        return float(os.environ.get("PRESTO_TPU_FEEDBACK_REPLAN_FACTOR",
                                    "8"))
    except ValueError:
        return 8.0


def feedback_replan_min_rows() -> int:
    """Observed rows below which a mid-query misprediction is never
    worth a replan, whatever the ratio — re-running producers costs
    more than finishing a small stage badly."""
    try:
        return int(os.environ.get("PRESTO_TPU_FEEDBACK_REPLAN_MIN_ROWS",
                                  "4096"))
    except ValueError:
        return 4096


def revoke_watermark() -> float:
    """Fraction of the memory limit at which revocation (offload/spill)
    starts, shared by the worker-local memory pool and the cluster
    memory manager — the two must agree or the cluster killer fires
    before workers were asked to revoke."""
    return float(os.environ.get("PRESTO_TPU_REVOKE_WATERMARK", "0.8"))
