"""Hierarchical resource groups: admission control for the coordinator.

Re-designed equivalent of the reference's resource-group subsystem
(execution/resourceGroups/InternalResourceGroup.java:78,584,748 with
FifoQueue/WeightedFairQueue, config via presto-resource-group-managers'
file-based manager, and the selector SPI spi/resourceGroups/). Kept
TPU-honest: quotas gate how many queries may be RUNNING at once and how
much accumulated wall-clock a group may burn per quota period — the
device executes one kernel at a time, so concurrency here is about
coordinator scheduling, not chip timeslicing.

Config shape (mirrors the reference's resource-groups JSON):

    {"name": "global", "hard_concurrency_limit": 10, "max_queued": 100,
     "scheduling_policy": "fair" | "weighted" | "query_priority",
     "cpu_quota_period_s": 60.0, "hard_cpu_limit_s": 30.0,
     "sub_groups": [
        {"name": "etl", "hard_concurrency_limit": 2, "max_queued": 10,
         "scheduling_weight": 3},
        {"name": "adhoc", ...}],
    }
    selectors = [{"user": "regex", "source": "regex", "group": "global.etl"},
                 ...]  # first match wins; default last group
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from typing import Callable, Dict, List, Optional


class QueryRejected(RuntimeError):
    """Group queue full (reference: QUERY_QUEUE_FULL error)."""


@dataclasses.dataclass
class GroupStats:
    name: str
    running: int
    queued: int
    cpu_used_s: float


class ResourceGroup:
    """One node of the group tree. Leaf groups hold query queues; interior
    groups aggregate limits over their children (reference
    InternalResourceGroup: canRunMore/internalStartNext)."""

    def __init__(self, spec: dict, parent: Optional["ResourceGroup"] = None):
        self.name = spec["name"]
        self.parent = parent
        self.full_name = (
            f"{parent.full_name}.{self.name}" if parent else self.name
        )
        self.hard_concurrency_limit = int(
            spec.get("hard_concurrency_limit", 10)
        )
        self.max_queued = int(spec.get("max_queued", 100))
        self.scheduling_policy = spec.get("scheduling_policy", "fair")
        self.scheduling_weight = int(spec.get("scheduling_weight", 1))
        self.cpu_quota_period_s = float(spec.get("cpu_quota_period_s", 0.0))
        self.hard_cpu_limit_s = float(spec.get("hard_cpu_limit_s", 0.0))
        self.children = [
            ResourceGroup(s, self) for s in spec.get("sub_groups", [])
        ]
        # runtime state
        self.running = 0
        self.queue: List[object] = []  # queued query infos (leaf only)
        self.cpu_used_s = 0.0
        self._last_refill = time.monotonic()
        self._last_started = 0.0  # fair-policy recency
        self._rr = 0

    # -- tree helpers --

    def find(self, full_name: str) -> Optional["ResourceGroup"]:
        if self.full_name == full_name:
            return self
        for c in self.children:
            hit = c.find(full_name)
            if hit is not None:
                return hit
        return None

    def _refill_cpu(self):
        if self.cpu_quota_period_s <= 0:
            return
        now = time.monotonic()
        elapsed = now - self._last_refill
        if elapsed > 0 and self.hard_cpu_limit_s > 0:
            refill = elapsed * (self.hard_cpu_limit_s / self.cpu_quota_period_s)
            self.cpu_used_s = max(0.0, self.cpu_used_s - refill)
            self._last_refill = now

    def can_run_more(self) -> bool:
        self._refill_cpu()
        if self.running >= self.hard_concurrency_limit:
            return False
        if self.hard_cpu_limit_s > 0 and self.cpu_used_s >= self.hard_cpu_limit_s:
            return False
        return True

    def queued_count(self) -> int:
        return len(self.queue) + sum(c.queued_count() for c in self.children)

    # -- scheduling --

    def _eligible_children(self) -> List["ResourceGroup"]:
        return [
            c
            for c in self.children
            if c.can_run_more() and c.queued_count() > 0
        ]

    def pop_next(self) -> Optional[object]:
        """Next query this subtree may start, honoring every ancestor's
        limits (caller checked self.can_run_more)."""
        if self.queue:
            if self.scheduling_policy == "query_priority":
                self.queue.sort(
                    key=lambda q: -getattr(q, "priority", 1)
                )
            return self.queue.pop(0)
        elig = self._eligible_children()
        if not elig:
            return None
        if self.scheduling_policy == "weighted":
            # deterministic weighted round-robin: highest credit first
            elig.sort(
                key=lambda c: (-c.scheduling_weight, c._last_started)
            )
        else:  # fair: least-recently-started subgroup first
            elig.sort(key=lambda c: c._last_started)
        for child in elig:
            q = child.pop_next()
            if q is not None:
                child._last_started = time.monotonic()
                return q
        return None

    def on_start(self):
        self.running += 1
        if self.parent:
            self.parent.on_start()

    def on_finish(self, cpu_s: float):
        self.running = max(0, self.running - 1)
        self.cpu_used_s += cpu_s
        if self.parent:
            self.parent.on_finish(cpu_s)

    def stats(self) -> List[GroupStats]:
        out = [
            GroupStats(
                self.full_name, self.running, len(self.queue), self.cpu_used_s
            )
        ]
        for c in self.children:
            out.extend(c.stats())
        return out


@dataclasses.dataclass
class Selector:
    """First-match-wins routing of (user, source) to a group (reference
    StaticSelector in presto-resource-group-managers)."""

    group: str
    user: Optional[str] = None
    source: Optional[str] = None

    def matches(self, user: str, source: Optional[str]) -> bool:
        if self.user is not None and not re.fullmatch(self.user, user or ""):
            return False
        if self.source is not None and not re.fullmatch(
            self.source, source or ""
        ):
            return False
        return True


class ResourceGroupManager:
    """Routes submissions into the group tree and releases them as slots
    free up (reference ResourceGroupManager + InternalResourceGroup.run).

    `dispatch` is called (on the submitting or finishing thread) with each
    query info the moment its group admits it."""

    def __init__(
        self,
        root_spec: dict,
        selectors: Optional[List[dict]] = None,
        dispatch: Optional[Callable[[object], None]] = None,
        poll_interval_s: float = 0.2,
        cluster_pressure: Optional[Callable[[], bool]] = None,
    ):
        self.root = ResourceGroup(root_spec)
        self.selectors = [Selector(**s) for s in (selectors or [])]
        self.dispatch = dispatch or (lambda info: None)
        # memory-pressure gate (the admission rung of the degradation
        # ladder): while the cluster memory manager reports usage above
        # the revocation watermark, new queries QUEUE instead of starting
        # (reference: ClusterMemoryManager's lastKilledQuery admission
        # backoff). Typically ClusterMemoryManager.above_watermark.
        self.cluster_pressure = cluster_pressure
        self.pressure_deferrals = 0  # submissions queued due to pressure
        self._lock = threading.Lock()
        self._groups_of: Dict[str, ResourceGroup] = {}
        # periodic drain: CPU quotas refill with TIME (and memory
        # pressure clears with time), not with query completions, so
        # queued queries need a ticker to wake them (reference:
        # ResourceGroupManager's scheduled processQueuedQueries)
        if self._has_cpu_quota(self.root) or cluster_pressure is not None:
            t = threading.Thread(
                target=self._poll_loop, args=(poll_interval_s,), daemon=True
            )
            t.start()

    def _under_pressure(self) -> bool:
        if self.cluster_pressure is None:
            return False
        try:
            return bool(self.cluster_pressure())
        except Exception:  # noqa: BLE001 - a broken gauge must not wedge
            return False  # admission (fail open, the killer still guards)

    @staticmethod
    def _has_cpu_quota(group: ResourceGroup) -> bool:
        if group.hard_cpu_limit_s > 0:
            return True
        return any(
            ResourceGroupManager._has_cpu_quota(c) for c in group.children
        )

    def _poll_loop(self, interval: float):
        while True:
            time.sleep(interval)
            with self._lock:
                released = self._drain_eligible_locked()
            for q in released:
                self.dispatch(q)

    def _select(self, user: str, source: Optional[str]) -> ResourceGroup:
        for sel in self.selectors:
            if sel.matches(user, source):
                g = self.root.find(sel.group)
                if g is not None:
                    return g
        return self.root

    def submit(self, info) -> None:
        """Queue or immediately dispatch. Raises QueryRejected when the
        selected group's queue is full."""
        released = []
        with self._lock:
            group = self._select(
                getattr(info, "user", "user"), getattr(info, "source", None)
            )
            self._groups_of[info.query_id] = group
            chain_ok = True
            g = group
            while g is not None:
                if not g.can_run_more():
                    chain_ok = False
                    break
                g = g.parent
            if chain_ok and self._under_pressure():
                # cluster above the revocation watermark: queue instead
                # of piling more reservations onto a straining fleet
                chain_ok = False
                self.pressure_deferrals += 1
            if chain_ok and not group.queue:
                group.on_start()
                released.append(info)
            else:
                if len(group.queue) >= group.max_queued:
                    self._groups_of.pop(info.query_id, None)
                    raise QueryRejected(
                        f"queue full for resource group {group.full_name!r} "
                        f"(max_queued={group.max_queued})"
                    )
                # FIFO within the group: earlier queued queries (e.g. held
                # back by an exhausted CPU quota that has since refilled)
                # start before this one
                group.queue.append(info)
                released.extend(self._drain_eligible_locked())
        for q in released:
            self.dispatch(q)

    def _drain_eligible_locked(self) -> List[object]:
        out = []
        while self.root.can_run_more() and not self._under_pressure():
            nxt = self.root.pop_next()
            if nxt is None:
                break
            g = self._groups_of.get(nxt.query_id)
            if g is None:  # canceled while queued
                continue
            g.on_start()
            out.append(nxt)
        return out

    def finished(self, info, cpu_s: float) -> None:
        """Release the slot and start whatever became eligible."""
        self.finished_by_id(info.query_id, cpu_s)

    def finished_by_id(self, query_id: str, cpu_s: float) -> None:
        """Release by id — usable when the QueryInfo itself was already
        purged from coordinator history."""
        with self._lock:
            group = self._groups_of.pop(query_id, None)
            if group is None:
                return
            group.on_finish(cpu_s)
            released = self._drain_eligible_locked()
        for q in released:
            self.dispatch(q)

    def remove_queued(self, info) -> bool:
        with self._lock:
            group = self._groups_of.get(info.query_id)
            if group is not None and info in group.queue:
                group.queue.remove(info)
                self._groups_of.pop(info.query_id, None)
                return True
        return False

    def stats(self) -> List[GroupStats]:
        with self._lock:
            return self.root.stats()
