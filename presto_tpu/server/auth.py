"""Password authenticators + TLS helpers for the REST surface.

Re-designed equivalent of the reference's presto-password-authenticators
(470 LoC: FileAuthenticator over a password db, the PasswordAuthenticator
SPI in presto-spi/security) and the coordinator's HTTPS listener
(presto-docs security/tls.rst). Identity flow matches the reference:
with an authenticator installed the HTTP principal comes from Basic
credentials and the session user must match it — a bare X-Presto-User
header is no longer trusted (closing round-3 weakness #8)."""

from __future__ import annotations

import base64
import binascii
import hashlib
import hmac
import os
import secrets
import ssl
import subprocess
from typing import Dict, Optional, Tuple


class AuthenticationError(RuntimeError):
    """Reference: AccessDeniedException from an authenticator."""


class PasswordAuthenticator:
    """SPI (reference spi/security/PasswordAuthenticator): return the
    authenticated principal for (user, password) or raise."""

    def authenticate(self, user: str, password: str) -> str:
        raise NotImplementedError


_ITERATIONS = 50_000


def hash_password(password: str, salt: Optional[bytes] = None) -> str:
    """salt$hex(pbkdf2-sha256) — the stored credential form."""
    salt = salt or secrets.token_bytes(16)
    digest = hashlib.pbkdf2_hmac(
        "sha256", password.encode(), salt, _ITERATIONS
    )
    return f"{salt.hex()}${digest.hex()}"


class FilePasswordAuthenticator(PasswordAuthenticator):
    """`path`: lines of `user:salt$pbkdf2hex` (reference file-based
    password authenticator; htpasswd-style)."""

    def __init__(self, path: str):
        self.creds: Dict[str, str] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or ":" not in line:
                    continue
                user, stored = line.split(":", 1)
                self.creds[user] = stored

    @staticmethod
    def write(path: str, users: Dict[str, str]) -> None:
        with open(path, "w") as f:
            for user, password in users.items():
                f.write(f"{user}:{hash_password(password)}\n")
        os.chmod(path, 0o600)

    def authenticate(self, user: str, password: str) -> str:
        stored = self.creds.get(user)
        if stored is None or "$" not in stored:
            raise AuthenticationError("invalid credentials")
        salt_hex, want_hex = stored.split("$", 1)
        try:
            salt = bytes.fromhex(salt_hex)
            want = bytes.fromhex(want_hex)
        except ValueError:
            raise AuthenticationError("invalid credentials") from None
        got = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), salt, _ITERATIONS
        )
        if not hmac.compare_digest(got, want):
            raise AuthenticationError("invalid credentials")
        return user


def parse_basic_auth(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """Authorization: Basic base64(user:password) -> (user, password)."""
    if not header or not header.startswith("Basic "):
        return None
    try:
        decoded = base64.b64decode(header[len("Basic "):]).decode()
    except (binascii.Error, UnicodeDecodeError):
        return None
    if ":" not in decoded:
        return None
    user, password = decoded.split(":", 1)
    return user, password


def basic_auth_header(user: str, password: str) -> str:
    return "Basic " + base64.b64encode(
        f"{user}:{password}".encode()
    ).decode()


# -- TLS ---------------------------------------------------------------------


def generate_self_signed_cert(directory: str, cn: str = "localhost"):
    """(certfile, keyfile) under `directory` — openssl-generated
    self-signed pair for tests/dev (production supplies real certs)."""
    cert = os.path.join(directory, "server.crt")
    key = os.path.join(directory, "server.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", key, "-out", cert, "-days", "2", "-nodes",
            "-subj", f"/CN={cn}",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True,
        capture_output=True,
    )
    return cert, key


def server_ssl_context(certfile: str, keyfile: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    return ctx


def client_ssl_context(cafile: Optional[str] = None) -> ssl.SSLContext:
    """Verifying client context; `cafile` pins a self-signed server."""
    ctx = ssl.create_default_context(cafile=cafile)
    return ctx
