"""Server layer: coordinator + workers + REST protocol (SURVEY L2/L3/L11).

Single-node embedding:  CoordinatorServer(Session(...)).start()
Cluster execution:      WorkerServer(catalog).start() per node,
                        NodeManager([...uris]) + HttpClusterSession.
Client:                 Client(coordinator_uri).execute(sql).
"""

from .client import Client, QueryError
from .cluster import HttpClusterSession, HttpScheduler, NodeManager, TaskFailure
from .coordinator import CoordinatorServer
from .exchange import ExchangeClient, ExchangeError, ExchangeStats
from .serde import (
    DictionaryCache,
    WireStats,
    deserialize_page,
    local_capabilities,
    negotiate,
    serialize_page,
)
from .worker import WorkerServer

__all__ = [
    "Client",
    "QueryError",
    "CoordinatorServer",
    "WorkerServer",
    "NodeManager",
    "HttpScheduler",
    "HttpClusterSession",
    "TaskFailure",
    "ExchangeClient",
    "ExchangeError",
    "ExchangeStats",
    "serialize_page",
    "deserialize_page",
    "local_capabilities",
    "negotiate",
    "WireStats",
    "DictionaryCache",
]
