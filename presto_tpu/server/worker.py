"""Worker server: task execution + pull-based output buffers over HTTP.

Re-designed equivalent of the reference's worker surface (SURVEY L6 + L8):
TaskResource (`POST /v1/task/{id}`, server/TaskResource.java:120),
SqlTaskExecution running a PlanFragment, partitioned output buffers
(execution/buffer/PartitionedOutputBuffer) and the pull protocol
`GET /v1/task/{id}/results/{bufferId}/{token}` (TaskResource.java:239).

This is the DCN path of the communication backend (SURVEY §2.7): pages
move between processes as serde bytes over HTTP; the in-process shard_map
path (exec/dist.py) remains the ICI path within one slice. A task's
fragment is a pickled plan subtree whose exchange inputs appear as
RemoteSource placeholders resolved by pulling upstream buffers.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import pickle
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..exec.executor import Executor
from ..ops.union import concat_pages
from ..page import Page
from ..plan import nodes as N
from .serde import deserialize_page, serialize_page


@dataclasses.dataclass(frozen=True)
class RemoteSource(N.PlanNode):
    """Placeholder for an exchange input materialized by pulling upstream
    task buffers (reference RemoteSourceNode)."""

    source_id: str
    schema: Tuple[Tuple[str, object], ...]  # (channel, Type)

    @property
    def fields(self):
        return self.schema


class TaskState:
    def __init__(self):
        self.state = "RUNNING"
        self.error: Optional[str] = None
        # buffer_id -> list of serialized pages
        self.buffers: Dict[int, List[bytes]] = {}
        self.done = threading.Event()


class FragmentExecutor(Executor):
    """Executes a fragment subtree; scans are split-limited, RemoteSources
    read pulled pages (reference SqlTaskExecution + LocalExecutionPlanner)."""

    def __init__(self, catalog, splits, sources):
        super().__init__(catalog)
        self.splits = splits or {}
        self.sources = sources or {}

    def _exec_tablescan(self, node: N.TableScan) -> Page:
        rng = self.splits.get(node.table)
        if rng is None:
            return super()._exec_tablescan(node)
        start, stop = rng
        scan = getattr(self.catalog, "scan", None)
        cols = [c for _, c, _ in node.columns]
        src = scan(node.table, start, stop, columns=cols)
        blocks, names = [], []
        for ch, colname, _t in node.columns:
            blocks.append(src.block(colname))
            names.append(ch)
        return Page(tuple(blocks), tuple(names), src.count)

    def _exec_remotesource(self, node: RemoteSource) -> Page:
        pages = self.sources[node.source_id]
        if not pages:
            raise RuntimeError(f"no pages for source {node.source_id}")
        return pages[0] if len(pages) == 1 else concat_pages(pages)


class WorkerServer:
    """One worker process/port: executes tasks against its own catalog
    instance (catalogs must be deterministic across nodes — the TPC-H
    generator and parquet files are)."""

    def __init__(self, catalog, host: str = "127.0.0.1", port: int = 0):
        self.catalog = catalog
        self.tasks: Dict[str, TaskState] = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, payload):
                body = (
                    payload
                    if isinstance(payload, bytes)
                    else json.dumps(payload).encode()
                )
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                parts = [p for p in self.path.split("/") if p]
                if parts[:2] == ["v1", "task"] and len(parts) == 3:
                    n = int(self.headers.get("Content-Length", 0))
                    spec = json.loads(self.rfile.read(n))
                    outer._start_task(parts[2], spec)
                    self._send(200, {"taskId": parts[2], "state": "RUNNING"})
                    return
                self._send(404, {"error": "not found"})

            def do_GET(self):
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                if parts == ["v1", "status"]:
                    self._send(200, {"state": "ACTIVE"})
                    return
                if parts[:2] == ["v1", "task"] and len(parts) == 3:
                    t = outer.tasks.get(parts[2])
                    if t is None:
                        self._send(404, {"error": "unknown task"})
                        return
                    t.done.wait(timeout=60)  # long-poll; RUNNING if not done
                    self._send(200, {"state": t.state, "error": t.error})
                    return
                if (
                    parts[:2] == ["v1", "task"]
                    and len(parts) == 6
                    and parts[3] == "results"
                ):
                    tid, buffer_id, token = parts[2], int(parts[4]), int(parts[5])
                    t = outer.tasks.get(tid)
                    if t is None:
                        self._send(404, {"error": "unknown task"})
                        return
                    if not t.done.wait(timeout=60):
                        # still running: tell the consumer to retry — an
                        # empty-buffer answer here would silently drop rows
                        self._send(503, {"retry": True, "state": t.state})
                        return
                    if t.state == "FAILED":
                        self._send(500, {"error": t.error})
                        return
                    pages = t.buffers.get(buffer_id, [])
                    if token < len(pages):
                        self._send(
                            200,
                            {
                                "page": base64.b64encode(pages[token]).decode(),
                                "complete": token + 1 >= len(pages),
                            },
                        )
                    else:
                        self._send(200, {"page": None, "complete": True})
                    return
                self._send(404, {"error": "not found"})

            def do_DELETE(self):
                parts = [p for p in self.path.split("/") if p]
                if parts[:2] == ["v1", "task"] and len(parts) == 3:
                    outer.tasks.pop(parts[2], None)
                    self._send(200, {"deleted": True})
                    return
                self._send(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    # -- task execution --

    def _start_task(self, task_id: str, spec: dict):
        state = TaskState()
        self.tasks[task_id] = state
        threading.Thread(
            target=self._run_task, args=(task_id, spec, state), daemon=True
        ).start()

    def _run_task(self, task_id: str, spec: dict, state: TaskState):
        try:
            fragment = pickle.loads(base64.b64decode(spec["fragment"]))
            splits = {
                t: tuple(rng) for t, rng in (spec.get("splits") or {}).items()
            }
            sources = {}
            for sid, src in (spec.get("sources") or {}).items():
                pages = []
                for uri, utask, buf in src["locations"]:
                    for data in _pull_buffer(uri, utask, buf):
                        pages.append(deserialize_page(data))
                sources[sid] = pages
            ex = FragmentExecutor(self.catalog, splits, sources)
            out = ex.run(fragment)
            part_keys = spec.get("partition_keys")
            nparts = int(spec.get("num_partitions", 1))
            if part_keys and nparts > 1:
                keys = pickle.loads(base64.b64decode(part_keys))
                state.buffers = _hash_partition(out, keys, nparts)
            else:
                state.buffers = {0: [serialize_page(out)]}
            state.state = "FINISHED"
        except Exception:  # noqa: BLE001
            state.error = traceback.format_exc(limit=20)
            state.state = "FAILED"
        finally:
            state.done.set()

    def start(self) -> "WorkerServer":
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def uri(self) -> str:
        return f"http://{self.host}:{self.port}"


def _hash_partition(page: Page, key_exprs, nparts: int) -> Dict[int, List[bytes]]:
    """Partition live rows by key hash -> serialized per-partition pages
    (reference PartitionedOutputOperator.partitionPage + PagesSerde)."""
    import jax.numpy as jnp

    from ..ops.filter import compact
    from ..ops.hashing import hash_rows
    from ..expr.compiler import evaluate

    keys = [evaluate(e, page) for e in key_exprs]
    h = hash_rows(keys)
    part = (h % jnp.uint64(nparts)).astype(jnp.int32)
    out: Dict[int, List[bytes]] = {}
    for p in range(nparts):
        sub = compact(page, part == p)
        out[p] = [serialize_page(sub)]
    return out


def _pull_buffer(uri: str, task_id: str, buffer_id: int):
    """Generator of serialized pages from an upstream buffer (reference
    ExchangeClient/HttpPageBufferClient pull + ack loop)."""
    import base64 as b64
    import json as js
    import urllib.request

    import urllib.error

    token = 0
    while True:
        url = f"{uri}/v1/task/{task_id}/results/{buffer_id}/{token}"
        try:
            with urllib.request.urlopen(url, timeout=300) as resp:
                payload = js.loads(resp.read())
        except urllib.error.HTTPError as e:
            if e.code == 503:  # producer still running: long-poll again
                continue
            raise
        if payload.get("page"):
            yield b64.b64decode(payload["page"])
        if payload.get("complete", True):
            return
        token += 1
